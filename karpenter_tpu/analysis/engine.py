"""graftlint engine: rule registry, suppression, baseline, and the runner.

The analyzer mechanically enforces the architecture contracts that
otherwise live only in prose (CLAUDE.md "Architecture invariants", the
bulkability gates atop solver/tpu_runs.py, docs/static-analysis.md). It is
pure stdlib `ast` — importing this package must never pull in JAX or
numpy, so the pytest gate (tests/test_static_analysis.py) runs in seconds.

Vocabulary:

- A *rule* inspects one parsed file (`FileContext`) and returns findings.
  Rules declare path targets; the engine only hands them files they apply
  to. Rule ids are kebab-case (`shared-comparator`).
- A *suppression* is a source comment `# graftlint: disable=<rule>[,<rule>]`.
  On a code line it silences findings on that line; on its own line it
  silences the next code line; on a `def`/`class` line it silences the
  whole body. `# graftlint: disable-file=<rule>` anywhere silences the
  file. `all` matches every rule.
- The *baseline* (graftlint.baseline.json) grandfathers intentional
  findings. Entries match on (rule, path, stripped source text) so they
  survive line drift; every entry carries a one-line justification and
  stale entries are reported so the file cannot rot.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import re
from typing import Iterable, Optional

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-file)=([\w-]+(?:\s*,\s*[\w-]+)*)"
)


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    text: str  # stripped source line — the baseline identity

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.text)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


# the IR tier's baseline filename, hoisted here (not analysis/ir.py)
# because ir.py imports JAX and the CLI's JSON pre-flight must be able
# to name the file without paying that import; locks.py keeps its own
# DEFAULT_BASELINE the same way
IR_DEFAULT_BASELINE = "graftlint.ir.baseline.json"
# the SPMD tier's baseline, hoisted for the same reason (spmd.py
# compiles real sharded programs and imports JAX)
SPMD_DEFAULT_BASELINE = "graftlint.spmd.baseline.json"
# the protocol tier's baseline (analysis/proto.py is stdlib-only, but
# its live-conformance scenarios import the real solver stack, so the
# CLI preflight names the file from here like the other deferred tiers)
PROTO_DEFAULT_BASELINE = "graftlint.proto.baseline.json"


@dataclasses.dataclass
class Config:
    """Per-run settings rules consult through `ctx.config`."""

    repo_root: str
    reference_root: str = "/root/reference"
    # pytest markers registered in pyproject.toml (pytest-markers rule)
    markers: frozenset[str] = frozenset()

    @classmethod
    def for_repo(
        cls, repo_root: str, reference_root: str = "/root/reference"
    ) -> "Config":
        return cls(
            repo_root=repo_root,
            reference_root=reference_root,
            markers=load_registered_markers(
                os.path.join(repo_root, "pyproject.toml")
            ),
        )


def load_registered_markers(pyproject_path: str) -> frozenset[str]:
    """Marker names from [tool.pytest.ini_options] markers. Regex, not
    tomllib — the floor interpreter is 3.10 (pyproject requires-python)."""
    try:
        with open(pyproject_path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return frozenset()
    m = re.search(r"markers\s*=\s*\[(.*?)\]", text, re.DOTALL)
    if not m:
        return frozenset()
    return frozenset(
        re.findall(r"\"([A-Za-z_]\w*)", m.group(1))
    )


class FileContext:
    """One parsed source file plus the lookups rules need."""

    def __init__(self, path: str, relpath: str, source: str, config: Config):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.config = config
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._line_suppress: dict[int, set[str]] = {}
        self._file_suppress: set[str] = set()
        self._span_suppress: list[tuple[int, int, set[str]]] = []
        self._parse_suppressions()

    # -- construction helpers ------------------------------------------------

    def _parse_suppressions(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",")}
            if m.group(1) == "disable-file":
                self._file_suppress |= rules
                continue
            target = i
            if line.lstrip().startswith("#"):
                # standalone comment shields the next CODE line — skip
                # blank lines and further comments in between
                target = i + 1
                while target <= len(self.lines):
                    nxt = self.lines[target - 1].strip()
                    if nxt and not nxt.startswith("#"):
                        break
                    target += 1
            self._line_suppress.setdefault(target, set()).update(rules)
        # a disable on a def/class line — or on one of its decorator
        # lines, where a standalone comment above a decorated function
        # lands — shields the whole body
        for node in ast.walk(self.tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                head_lines = [d.lineno for d in node.decorator_list] + [
                    node.lineno
                ]
                rules = set()
                for ln in head_lines:
                    rules |= self._line_suppress.get(ln, set())
                if rules:
                    self._span_suppress.append(
                        (min(head_lines), node.end_lineno or node.lineno, rules)
                    )

    # -- rule-facing API -----------------------------------------------------

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def segment(self, node: ast.AST) -> str:
        return ast.get_source_segment(self.source, node) or ""

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        line = (
            node_or_line
            if isinstance(node_or_line, int)
            else getattr(node_or_line, "lineno", 1)
        )
        return Finding(
            rule=rule,
            path=self.relpath,
            line=line,
            message=message,
            text=self.line_text(line),
        )

    def suppressed(self, finding: Finding) -> bool:
        for rules in (
            self._file_suppress,
            self._line_suppress.get(finding.line, ()),
        ):
            if finding.rule in rules or "all" in rules:
                return True
        for lo, hi, rules in self._span_suppress:
            if lo <= finding.line <= hi and (
                finding.rule in rules or "all" in rules
            ):
                return True
        return False


class Rule:
    """Base rule. Subclasses set `id`, `summary`, `targets` (fnmatch
    patterns over the repo-relative path) and implement `check`."""

    id: str = ""
    summary: str = ""
    targets: tuple[str, ...] = ("**/*.py",)

    def applies_to(self, relpath: str) -> bool:
        relpath = relpath.replace(os.sep, "/")
        # fnmatch has no recursive `**`: `dir/**/*.py` would demand an
        # intermediate directory and silently skip dir's direct children,
        # so each pattern also matches with `**/` collapsed away
        return any(
            fnmatch.fnmatch(relpath, pat)
            or ("**/" in pat and fnmatch.fnmatch(relpath, pat.replace("**/", "")))
            for pat in self.targets
        )

    def check(self, ctx: FileContext) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError

    def run(self, ctx: FileContext) -> list[Finding]:
        out, seen = [], set()
        for f in self.check(ctx):
            # one finding per (line, rule, message): multiline expressions
            # can hit a pattern several times on the same source line, but
            # distinct messages (two rotted citations in one docstring)
            # must both surface
            k = (f.line, f.rule, f.message)
            if k in seen or self.suppressed_in(ctx, f):
                continue
            seen.add(k)
            out.append(f)
        return out

    @staticmethod
    def suppressed_in(ctx: FileContext, finding: Finding) -> bool:
        return ctx.suppressed(finding)


# ---------------------------------------------------------------------------
# shared AST helpers


def base_name(node: ast.AST) -> Optional[str]:
    """Root Name id of an attribute/subscript/call chain (jnp.any -> jnp)."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = node.func if isinstance(node, ast.Call) else node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def iter_functions(tree: ast.Module) -> Iterable[ast.FunctionDef]:
    """Top-level functions and methods (nested defs ride their parent's
    source segment — accumulation guards are per outermost function)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield sub


def ordering_import_names(tree: ast.Module) -> set[str]:
    """Names bound from karpenter_tpu.solver.ordering (module aliases and
    imported functions) — the shared-comparator allowlist."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "karpenter_tpu.solver.ordering" or mod.endswith(
                ".ordering"
            ):
                names.update(a.asname or a.name for a in node.names)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith(".ordering") or a.name == "ordering":
                    names.add((a.asname or a.name).split(".")[0])
    return names


# ---------------------------------------------------------------------------
# canonical serialization (shared by the AST baseline and the IR tier's
# kernel_budgets.json: sorted keys, two-space indent, trailing newline —
# a re-written file with unchanged content is byte-identical)


def canonical_json(data: dict) -> str:
    return (
        json.dumps(data, indent=2, sort_keys=True, ensure_ascii=False) + "\n"
    )


# ---------------------------------------------------------------------------
# baseline


class Baseline:
    """Grandfathered findings with per-entry justification. Matching is a
    multiset over (rule, path, text): N identical findings need N entries."""

    def __init__(self, entries: list[dict], path: Optional[str] = None):
        self.entries = entries
        self.path = path

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls([], path)
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return cls(list(data.get("entries", [])), path)

    def unjustified(self) -> list[dict]:
        return [
            e
            for e in self.entries
            if not str(e.get("justification", "")).strip()
            or str(e.get("justification", "")).startswith("TODO")
        ]

    def apply(
        self, findings: list[Finding]
    ) -> tuple[list[Finding], list[dict]]:
        """Returns (unbaselined findings, stale entries)."""
        pool: dict[tuple, list[dict]] = {}
        for e in self.entries:
            k = (e.get("rule"), e.get("path"), e.get("text"))
            pool.setdefault(k, []).append(e)
        fresh = []
        for f in findings:
            bucket = pool.get(f.key())
            if bucket:
                bucket.pop()
            else:
                fresh.append(f)
        stale = [e for bucket in pool.values() for e in bucket]
        return fresh, stale

    @staticmethod
    def render_entries(findings: list[Finding]) -> dict:
        return {
            "entries": [
                {
                    "rule": f.rule,
                    "path": f.path,
                    "text": f.text,
                    "justification": "TODO: justify or fix",
                }
                for f in findings
            ]
        }

    def merge_justifications(self, data: dict) -> int:
        """Carry hand-written justifications from this baseline into a
        freshly rendered `data` (render_entries output): entries that
        still match keep their text, only genuinely new findings keep the
        TODO placeholder. Returns the number of new entries. Shared by
        the AST `--write-baseline` and the IR tier's baseline writer."""
        keep: dict[tuple, list[str]] = {}
        for e in self.entries:
            k = (e.get("rule"), e.get("path"), e.get("text"))
            keep.setdefault(k, []).append(str(e.get("justification", "")))
        fresh = 0
        for entry in data["entries"]:
            k = (entry["rule"], entry["path"], entry["text"])
            bucket = keep.get(k)
            if bucket:
                entry["justification"] = bucket.pop(0)
            else:
                fresh += 1
        return fresh


# ---------------------------------------------------------------------------
# runner


def all_rules() -> list[Rule]:
    from karpenter_tpu.analysis import (
        rules_data,
        rules_docs,
        rules_kernel,
        rules_metrics,
        rules_threads,
        rules_wire,
    )

    rules: list[Rule] = []
    for mod in (rules_kernel, rules_data, rules_threads, rules_docs,
                rules_metrics, rules_wire):
        rules.extend(r() for r in mod.RULES)
    return rules


# Rules switched off for tests/ (docs/static-analysis.md §profiles): test
# helpers carry no reference-parity docstrings and no jitted kernels, but
# their lock discipline and marker spelling still matter.
TEST_RELAXED_OFF = frozenset({"citation-check", "kernel-purity"})


def profile_rule_ids(relpath: str, rules: list[Rule]) -> set[str]:
    ids = {r.id for r in rules}
    rel = relpath.replace(os.sep, "/")
    if rel.startswith("tests/") or "/tests/" in rel:
        ids -= TEST_RELAXED_OFF
    return ids


def discover_files(repo_root: str, paths: Optional[list[str]] = None) -> list[str]:
    """Python files to analyze: the package plus tests/, or explicit paths."""
    roots = paths or [
        os.path.join(repo_root, "karpenter_tpu"),
        os.path.join(repo_root, "tests"),
    ]
    out = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [
                d for d in dirnames if d not in ("__pycache__", ".git")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def analyze_files(
    files: list[str],
    config: Config,
    rules: Optional[list[Rule]] = None,
    rule_ids: Optional[set[str]] = None,
) -> tuple[list[Finding], list[str]]:
    """Run rules over files. Returns (findings, errors) where errors are
    unparsable files (reported, never silently skipped)."""
    rules = rules if rules is not None else all_rules()
    if rule_ids is not None:
        rules = [r for r in rules if r.id in rule_ids]
    findings: list[Finding] = []
    errors: list[str] = []
    for path in files:
        rel = os.path.relpath(path, config.repo_root)
        active = profile_rule_ids(rel, rules)
        applicable = [
            r for r in rules if r.id in active and r.applies_to(rel)
        ]
        if not applicable:
            continue
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            ctx = FileContext(path, rel, source, config)
        except (OSError, SyntaxError) as e:
            errors.append(f"{rel}: {type(e).__name__}: {e}")
            continue
        for rule in applicable:
            findings.extend(rule.run(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, errors


def run_analysis(
    repo_root: str,
    paths: Optional[list[str]] = None,
    baseline_path: Optional[str] = None,
    reference_root: str = "/root/reference",
    rule_ids: Optional[set[str]] = None,
) -> dict:
    """The full pipeline: discover, analyze, apply baseline. Returns
    {"findings": [...unbaselined...], "stale": [...], "errors": [...],
    "total": int} — the CLI and the pytest gate both consume this."""
    config = Config.for_repo(repo_root, reference_root)
    files = discover_files(repo_root, paths)
    findings, errors = analyze_files(files, config, rule_ids=rule_ids)
    baseline = Baseline.load(
        baseline_path
        if baseline_path is not None
        else os.path.join(repo_root, "graftlint.baseline.json")
    )
    fresh, stale = baseline.apply(findings)
    return {
        "findings": fresh,
        "all_findings": findings,
        "stale": stale,
        "errors": errors,
        "unjustified": baseline.unjustified(),
        "total": len(findings),
    }
