"""proto — graftlint's fifth tier: explicit-state model checking of the
solver wire/epoch/breaker protocol, conformance-pinned to the live code.

Every serving-layer review fix in CHANGES.md — the resync loop, the
stranded half-open probe, the silent drain close, the one-refusal
bound, the epoch store-before-answer rule — is a PROTOCOL bug: a wrong
move in the distributed game of SolverClient x SolverServer x
CircuitBreaker x EpochStore under faults, invisible to the AST/IR/
race/SPMD tiers because it lives in no single function, jaxpr, lockset
or compiled program. This tier applies lightweight formal methods in
the AWS tradition (small executable specs, exhaustively explored) plus
the race tier's Eraser lesson (check the DISCIPLINE, not the
interleaving you got lucky with):

1. **Model** — `World` is a frozen value-object snapshot of the whole
   composed system: the client request lifecycle (snapshot / delta /
   resync / RETRY-backoff / deadline / poison, service.py SolverClient),
   the server handler (admission gate, drain with the one-refusal
   bound, epoch commit store-before-answer, service.py _handle), the
   circuit breaker (closed/open/half-open with the single-probe and
   RETRY-records-success rules, hybrid.py CircuitBreaker), and the
   epoch section store — composed asynchronously over a fault-capable
   channel (drop / truncate / duplicate / reorder / kill-either-side,
   mirroring testing/faults.py's proxy modes). `Knobs` makes each
   pinned review-fix behavior an explicit model parameter, so the
   deliberately-broken variant of every property is one flag away
   (tests/test_proto_analysis.py drives each).

2. **Checker** — `explore` runs explicit-state BFS with canonical-state
   dedup (epoch ids renumbered by first occurrence), bounded by
   per-scenario tick/fault/state budgets that the JSON report records
   (truncation is never silent). BFS finds the SHORTEST counterexample
   schedule; `shrink` then greedily drops labels while the replay still
   violates, and the result serializes into tests/proto_corpus/*.json
   — replayed FIRST by tests/test_proto_analysis.py, the fuzz-corpus
   lifecycle reused.

3. **Conformance** — `check_refinement` judges a RECORDED trace of the
   real code (analysis/protorec.py hooks in service.py/hybrid.py;
   installed for every `faults`-marked test by tests/conftest.py)
   against the model's transition discipline: breaker transition
   legality and per-thread probe obligations, the drain
   answer-then-close contract, epoch commit-implies-store, and the
   client's resync one-hop rule. `run_proto_analysis` additionally
   DRIVES two live scenarios (a scripted ResilientSolver and a real
   drained SolverServer) and refinement-checks their traces, so
   reverting a pinned fix in the real code — not just in the model —
   fails `graftlint --proto` with a replayable counterexample.

Module-level imports are stdlib-only: `import karpenter_tpu.analysis`
stays JAX- and numpy-free (tests/test_static_analysis.py pins it); the
live-conformance scenarios import the solver stack lazily, exactly like
analysis/ir.py defers JAX.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from collections import deque
from dataclasses import dataclass, replace
from typing import Iterable, Optional

from karpenter_tpu.analysis.engine import (
    Baseline,
    Finding,
    PROTO_DEFAULT_BASELINE,
)

# Wire kind codes, mirrored from solver/service.py (which imports numpy
# at module scope and therefore cannot be imported here;
# tests/test_proto_analysis.py pins the two tables equal).
KIND_SOLVE = 1
KIND_RESULT = 2
KIND_ERROR = 3
KIND_PING = 4
KIND_PONG = 5
KIND_SOLVE_DELTA = 6
KIND_EPOCH_RESYNC = 7
KIND_RETRY = 8

_SOLVE_KINDS = (KIND_SOLVE, KIND_SOLVE_DELTA)
_RESPONSE_KINDS = (KIND_RESULT, KIND_ERROR, KIND_PONG, KIND_EPOCH_RESYNC, KIND_RETRY)

PROTO_RULES = {
    "proto-converge": (
        "every solve converges to a RESULT or a bounded in-process degrade: "
        "no reachable state deadlocks or waits forever (the client deadline "
        "and bounded retry/backoff discipline, docs/resilience.md)"
    ),
    "proto-resync-one-hop": (
        "EPOCH_RESYNC converges in exactly one hop per solve: a resync "
        "falls back to the always-correct full snapshot, and a snapshot is "
        "never itself answered RESYNC (service.py _solve_delta contract)"
    ),
    "proto-drain-bounded": (
        "drain is bounded: a solve frame received during stop() is ANSWERED "
        "(one retriable refusal or the in-flight RESULT flush) before its "
        "connection closes, and no handler serves a second refusal "
        "(service.py _handle drain branch + _drain_close_check)"
    ),
    "proto-breaker-wedge": (
        "the breaker never wedges while the server is healthy: an admission "
        "RETRY is a transport SUCCESS, so it must resolve a half-open probe "
        "to closed instead of stranding it (hybrid.py RETRY-records-success)"
    ),
    "proto-epoch-consistent": (
        "epoch commit is consistent under faults and mid-delta kill: the "
        "sections the server solves from always equal the sections the "
        "client believes it acked — stored sections are COPIES, stores "
        "precede answers, and commits ride only RESULT frames"
    ),
    "proto-conformance": (
        "every recorded trace of the real client/server/breaker refines the "
        "model: transition legality, probe obligations, the drain "
        "answer-then-close bound, commit-implies-store, resync one-hop "
        "(analysis/protorec.py hooks; auto-recorded across the faults suite)"
    ),
}

# -- model parameters -------------------------------------------------------

DEADLINE_TICKS = 3  # client waits this many ticks before SolverUnavailable
BR_THRESHOLD = 2  # consecutive failures to open the model breaker
BR_COOLDOWN_TICKS = 2  # open -> half-open (and probe-takeover) cooldown
MAX_RETRIES = 1  # transport resends inside one _roundtrip


@dataclass(frozen=True)
class Knobs:
    """Each field is a pinned review-fix behavior, default = the REAL
    code. Flipping one yields the deliberately-broken model whose
    counterexample the checker must find (and whose shrunk schedule the
    corpus pins). The mapping to properties lives in BROKEN_KNOBS."""

    drain_mode: str = "refuse"  # "refuse" | "close_silent" (the old bug)
    drain_single_refusal: bool = True  # False: handler survives past one
    retry_resolves_probe: bool = True  # False: RETRY strands the probe
    lost_probe_recovery: bool = True  # False: a lost probe wedges forever
    copy_sections: bool = True  # False: stored sections alias the client's
    snapshot_resyncable: bool = False  # True: snapshots answered RESYNC
    store_before_answer: bool = True  # False: answer, then store
    client_deadline: bool = True  # False: a lost response waits forever


REAL_KNOBS = Knobs()


@dataclass(frozen=True)
class Scenario:
    """One bounded exploration: which faults the adversary may inject,
    how many, and how much simulated time exists. Budgets are part of
    the JSON report — a truncated exploration is reported, not hidden."""

    name: str
    n_solves: int
    faults: tuple = ()
    fault_budget: int = 1
    max_ticks: int = 10
    allow_drain: bool = False
    over0: int = 0  # admission gate answers this many RETRYs
    max_states: int = 200_000


SCENARIOS = (
    Scenario(
        "steady",
        n_solves=3,
        faults=("drop_c2s", "drop_s2c", "dup_s2c", "trunc_s2c"),
        fault_budget=1,
        max_ticks=10,
    ),
    Scenario(
        "churn",
        n_solves=2,
        faults=("drop_s2c", "trunc_c2s", "reorder_s2c", "dup_c2s"),
        fault_budget=1,
        over0=1,
        max_ticks=10,
    ),
    Scenario(
        "restart",
        n_solves=3,
        faults=("kill_server",),
        fault_budget=1,
        max_ticks=12,
    ),
    Scenario(
        "drain",
        n_solves=2,
        faults=("kill_conn", "dup_c2s"),
        fault_budget=1,
        allow_drain=True,
        max_ticks=10,
    ),
    Scenario(
        "recover",
        n_solves=4,
        faults=("kill_server", "drop_s2c"),
        fault_budget=2,
        over0=1,
        max_ticks=16,
    ),
)

# property -> (scenario name, broken knobs): the deliberately-broken
# model per property. tests/test_proto_analysis.py asserts each finds a
# counterexample AND that the real knobs stay clean; the shrunk
# schedules are pinned in tests/proto_corpus/.
BROKEN_KNOBS = {
    "proto-converge": ("steady", Knobs(client_deadline=False)),
    "proto-resync-one-hop": ("steady", Knobs(snapshot_resyncable=True)),
    "proto-drain-bounded": ("drain", Knobs(drain_mode="close_silent")),
    "proto-breaker-wedge": ("recover", Knobs(retry_resolves_probe=False)),
    "proto-epoch-consistent": ("steady", Knobs(copy_sections=False)),
}


@dataclass(frozen=True)
class Config:
    knobs: Knobs
    scenario: Scenario


# -- the composed state -----------------------------------------------------
#
# Frames are tuples ("SOLVE", current, epoch, version) etc.; `current`
# is the client's correlation tripwire abstracted to one bit — a resend
# marks every in-flight frame stale, and reading a stale response
# poisons the stream exactly like a req_id mismatch does on the wire.


@dataclass(frozen=True)
class World:
    # client request lifecycle (service.py SolverClient + hybrid.py entry)
    solve: int = 0  # index of the solve in progress; done at n_solves
    phase: str = "idle"  # "idle" | "wait"
    sent: str = ""  # kind of the in-flight request: "snap" | "delta"
    acked_e: int = 0  # client-committed epoch id (0 = none)
    acked_v: int = 0  # ghost: true section version behind acked_e
    wait_age: int = 0  # ticks spent waiting on the in-flight request
    retries: int = 0  # transport resends used this roundtrip
    resyncs: int = 0  # RESYNC hops consumed by the CURRENT solve
    degrades: int = 0  # solves completed in-process (oracle floor)
    backoff: int = 0  # admission-backoff ticks remaining
    # circuit breaker (hybrid.py CircuitBreaker)
    br: str = "closed"  # "closed" | "open" | "half"
    brf: int = 0  # consecutive failures
    brcool: int = 0  # ticks until open->half / probe takeover
    probe: bool = False  # a half-open probe is outstanding
    # server handler + epoch store (service.py SolverServer)
    alive: bool = True
    drain: bool = False
    over: int = 0  # admission gate rejects this many more solves
    se: int = 0  # stored epoch id for the client (0 = none)
    sv: int = 0  # ghost: section version actually stored
    ssnap: bool = False  # stored sections came from a snapshot request
    pend: tuple = ()  # handler micro-ops: ("store",e,v,snap)/("send",f)/("close",)
    refusals: int = 0  # drain refusals sent on the CURRENT connection
    owed: int = 0  # received solve frames not yet answered (this conn)
    conn: bool = False  # the client connection is open
    # the fault-capable channel
    c2s: tuple = ()
    s2c: tuple = ()
    # budget counters (bounded => exploration terminates)
    ticks: int = 0
    faults: int = 0


def initial_world(scn: Scenario) -> World:
    return World(over=scn.over0)


def done(cfg: Config, w: World) -> bool:
    return w.solve >= cfg.scenario.n_solves and w.phase == "idle"


def canonical(w: World) -> tuple:
    """Hashable canonical form: epoch ids renumbered densely in order of
    first occurrence, so states differing only in epoch labeling dedup
    to one BFS node (the renumbering is what keeps the store/commit
    machinery finite-state under resyncs and restarts)."""
    mapping: dict[int, int] = {0: 0}

    def ren(e: int) -> int:
        if e not in mapping:
            mapping[e] = len(mapping)
        return mapping[e]

    def ren_frame(f: tuple) -> tuple:
        k = f[0]
        if k == "SOLVE":
            return (k, f[1], ren(f[2]), f[3])
        if k == "DELTA":
            return (k, f[1], ren(f[2]), f[3], ren(f[4]), f[5])
        if k == "RESULT":
            return (k, f[1], ren(f[2]), f[3])
        return f

    t = dataclasses.astuple(w)
    d = dataclasses.asdict(w)
    d["acked_e"] = ren(w.acked_e)
    d["se"] = ren(w.se)
    d["pend"] = tuple(
        ("store", ren(op[1]), op[2], op[3])
        if op[0] == "store"
        else (("send", ren_frame(op[1])) if op[0] == "send" else op)
        for op in w.pend
    )
    d["c2s"] = tuple(ren_frame(f) for f in w.c2s)
    d["s2c"] = tuple(ren_frame(f) for f in w.s2c)
    assert len(t) == len(d)
    return tuple(d.values())


# -- transition helpers -----------------------------------------------------


def _stale(frames: tuple) -> tuple:
    return tuple((f[0], False) + tuple(f[2:]) for f in frames)


def _dead_handler_unwind(w: World) -> dict:
    """The old connection's handler finishes against a closed socket:
    pending stores land in program order until the first send raises
    (EPIPE), which unwinds the handler — everything after (including an
    answer-then-store's late store) is genuinely lost, exactly as in
    the real code. Collapsed to one atomic action at reconnect to keep
    the model single-handler."""
    se, sv, ssnap = w.se, w.sv, w.ssnap
    for op in w.pend:
        if op[0] == "store":
            se, sv, ssnap = op[1], op[2], op[3]
        elif op[0] == "send":
            break
    return dict(pend=(), se=se, sv=sv, ssnap=ssnap, refusals=0, owed=0)


def _br_fail(w: World) -> dict:
    """record_failure: half-open or threshold -> open (fresh cooldown)."""
    brf = w.brf + 1
    if w.br == "half" or brf >= BR_THRESHOLD:
        return dict(br="open", brf=brf, brcool=BR_COOLDOWN_TICKS, probe=False)
    return dict(br=w.br, brf=brf, probe=False)


def _br_success() -> dict:
    return dict(br="closed", brf=0, brcool=0, probe=False)


def _advance(cfg: Config, w: World, fields: dict) -> dict:
    """Complete the current solve and prepare the next one. The client
    MUTATES its live world here — with copy_sections off, a
    snapshot-established store aliases that memory and its ghost version
    silently drifts (the PR 11 _encode_views bug, reproduced)."""
    fields.update(
        solve=w.solve + 1, resyncs=0, wait_age=0, retries=0, phase="idle",
        sent="",
    )
    if not cfg.knobs.copy_sections and w.ssnap and fields.get("se", w.se) != 0:
        fields["sv"] = fields.get("sv", w.sv) + 1
    return fields


def _request_frame(w: World) -> tuple[str, tuple]:
    e = v = w.solve + 1
    if w.acked_e:
        return "delta", ("DELTA", True, w.acked_e, w.acked_v, e, v)
    return "snap", ("SOLVE", True, e, v)


def _emit(trace, ev: str, **fields) -> None:
    if trace is not None:
        fields["ev"] = ev
        fields.setdefault("thread", 0)
        fields["i"] = len(trace.events)
        trace.events.append(fields)


def _brname(state: str) -> str:
    return {"half": "half-open"}.get(state, state)


def _emit_fail(trace, prev: str, bf: dict) -> None:
    """record_failure + attempt-failed, in the order the real code
    records them (hybrid.py records the transition, then the attempt)."""
    _emit(
        trace, "breaker_failure", prev=_brname(prev),
        state=_brname(bf["br"]), failures=bf["brf"],
        threshold=BR_THRESHOLD, name="model",
    )
    _emit(trace, "attempt", outcome="failure", breaker=bf["br"])


class _Trace:
    """Mutable companion for trace_of: protorec-schema events plus the
    model's connection-generation counter (connection identity is an
    emission detail, deliberately NOT part of World)."""

    def __init__(self) -> None:
        self.events: list[dict] = []
        self.conn = 0


# -- the successor relation -------------------------------------------------


def step(
    cfg: Config, w: World, trace: Optional[_Trace] = None
) -> list[tuple[str, World, tuple]]:
    """All enabled transitions: (label, successor, violated-properties).
    Labels are deterministic — one label names exactly one successor —
    so a schedule of labels replays bit-identically (the corpus/shrink
    contract)."""
    out: list[tuple[str, World, tuple]] = []
    kn, scn = cfg.knobs, cfg.scenario
    budget_left = w.faults < scn.fault_budget

    def add(label: str, viol: tuple = (), **fields) -> None:
        out.append((label, replace(w, **fields), viol))

    client_active = w.solve < scn.n_solves

    # ---- client -----------------------------------------------------------
    if w.phase == "idle" and client_active:
        if w.backoff > 0:
            # admission backoff is checked BEFORE breaker.allow() — the
            # probe slot must not be claimed by a caller that then skips
            # the sidecar (hybrid.py backoff-before-allow comment)
            _emit(trace, "attempt", outcome="backoff", breaker=w.br)
            add("c_attempt", **_advance(cfg, w, dict(degrades=w.degrades + 1)))
        else:
            allowed, claimed = False, False
            brfields: dict = {}
            if w.br == "closed":
                allowed = True
            elif w.br == "open" and w.brcool == 0:
                allowed, claimed = True, True
                brfields = dict(br="half", probe=True, brcool=BR_COOLDOWN_TICKS)
            elif (
                w.br == "half"
                and w.probe
                and w.brcool == 0
                and kn.lost_probe_recovery
            ):
                allowed, claimed = True, True  # lost probe; caller takes over
                brfields = dict(brcool=BR_COOLDOWN_TICKS)
            if not allowed:
                _emit(
                    trace, "breaker_allow", granted=False, probe=False,
                    state={"half": "half-open"}.get(w.br, w.br),
                    failures=w.brf, threshold=BR_THRESHOLD, name="model",
                )
                _emit(trace, "attempt", outcome="breaker_denied", breaker=w.br)
                add(
                    "c_attempt",
                    **_advance(cfg, w, dict(degrades=w.degrades + 1)),
                )
            else:
                post = brfields.get("br", w.br)
                _emit(
                    trace, "breaker_allow", granted=True, probe=claimed,
                    state={"half": "half-open"}.get(post, post),
                    failures=w.brf, threshold=BR_THRESHOLD, name="model",
                )
                if not w.alive or (w.drain and not w.conn):
                    # connect refused (dead or stop()ed accept loop):
                    # bounded retry exhausts -> SolverUnavailable ->
                    # record_failure + in-process degrade
                    f = _br_fail(replace(w, **brfields))
                    _emit_fail(trace, post, f)
                    fields = dict(brfields)
                    fields.update(f)
                    add(
                        "c_attempt",
                        **_advance(
                            cfg, w, dict(fields, degrades=w.degrades + 1)
                        ),
                    )
                else:
                    mode, frame = _request_frame(w)
                    if trace is not None and not w.conn:
                        trace.conn += 1
                    fields = dict(brfields)
                    if w.conn:
                        # same socket: old frames stay in flight but the
                        # correlation tripwire marks them stale
                        fields.update(
                            c2s=_stale(w.c2s) + (frame,), s2c=_stale(w.s2c)
                        )
                    else:
                        # fresh socket: empty buffers, and the old conn's
                        # handler unwinds against the closed peer
                        fields.update(_dead_handler_unwind(w))
                        fields.update(c2s=(frame,), s2c=())
                    fields.update(
                        conn=True, phase="wait", sent=mode, wait_age=0
                    )
                    add("c_attempt", **fields)

    if w.phase == "wait" and w.s2c:
        f, rest = w.s2c[0], w.s2c[1:]
        kindmap = {
            "SOLVE": KIND_SOLVE, "DELTA": KIND_SOLVE_DELTA,
        }
        sent_kind = kindmap.get(
            {"snap": "SOLVE", "delta": "DELTA"}.get(w.sent, ""), KIND_SOLVE
        )
        if f[0] == "JUNK" or not f[1]:
            # corrupted framing or a stale response: poison the stream
            # (correlation tripwire), ProtocolError propagates ->
            # record_failure + in-process degrade
            bf = _br_fail(w)
            _emit_fail(trace, w.br, bf)
            add(
                "c_recv",
                **_advance(
                    cfg, w,
                    dict(
                        bf, degrades=w.degrades + 1, s2c=(), c2s=(),
                        conn=False,
                    ),
                ),
            )
        elif f[0] == "RESULT":
            bs = _br_success()
            _emit(
                trace, "cli_roundtrip", client="model", kind=sent_kind,
                resp_kind=KIND_RESULT, req_id=w.solve + 1,
            )
            _emit(
                trace, "breaker_success", prev=w.br, state="closed",
                failures=0, threshold=BR_THRESHOLD, name="model",
            )
            _emit(trace, "attempt", outcome="success", breaker="closed")
            _emit(
                trace, "cli_epoch_commit", client="model", epoch=f[2],
                mode={"snap": "snapshot"}.get(w.sent, "delta"),
            )
            add(
                "c_recv",
                **_advance(
                    cfg, w, dict(bs, acked_e=f[2], acked_v=f[3], s2c=rest)
                ),
            )
        elif f[0] == "RESYNC":
            _emit(
                trace, "cli_roundtrip", client="model", kind=sent_kind,
                resp_kind=KIND_EPOCH_RESYNC, req_id=w.solve + 1,
            )
            viol = ()
            if w.sent == "snap":
                viol = (
                    (
                        "proto-resync-one-hop",
                        "a full-snapshot SOLVE was answered EPOCH_RESYNC: "
                        "the always-correct fallback has no fallback — the "
                        "client would loop",
                    ),
                )
            elif w.resyncs + 1 > 1:
                viol = (
                    (
                        "proto-resync-one-hop",
                        f"solve {w.solve} consumed {w.resyncs + 1} resync "
                        "hops; the contract is exactly one (delta -> "
                        "snapshot) per solve",
                    ),
                )
            add(
                "c_recv",
                viol,
                # capped: past 2 the one-hop property has already fired,
                # and an uncapped counter would make the broken
                # snapshot_resyncable model's state space infinite
                resyncs=min(w.resyncs + 1, 2),
                acked_e=0,
                acked_v=0,
                phase="idle",
                sent="",
                wait_age=0,
                s2c=rest,
            )
        elif f[0] == "RETRY":
            fields: dict
            if kn.retry_resolves_probe:
                fields = _br_success()
                _emit(
                    trace, "cli_roundtrip", client="model", kind=sent_kind,
                    resp_kind=KIND_RETRY, req_id=w.solve + 1,
                )
                _emit(
                    trace, "breaker_success", prev=w.br, state="closed",
                    failures=0, threshold=BR_THRESHOLD, name="model",
                )
            else:
                fields = {}
                _emit(
                    trace, "cli_roundtrip", client="model", kind=sent_kind,
                    resp_kind=KIND_RETRY, req_id=w.solve + 1,
                )
            post = fields.get("br", w.br)
            _emit(trace, "attempt", outcome="overloaded", breaker=post)
            viol = ()
            if post != "closed":
                viol = (
                    (
                        "proto-breaker-wedge",
                        "an admission RETRY round-tripped (the server is "
                        f"healthy) yet left the breaker {post!r}: the "
                        "half-open probe is stranded and every caller "
                        "degrades in-process for a cooldown it never owed",
                    ),
                )
            fields.update(backoff=f[2], degrades=w.degrades + 1, s2c=rest)
            add("c_recv", viol, **_advance(cfg, w, fields))
        elif f[0] == "ERRDRAIN":
            bf = _br_fail(w)
            _emit(
                trace, "cli_roundtrip", client="model", kind=sent_kind,
                resp_kind=KIND_ERROR, req_id=w.solve + 1,
            )
            _emit_fail(trace, w.br, bf)
            add(
                "c_recv",
                **_advance(
                    cfg, w, dict(bf, degrades=w.degrades + 1, s2c=rest)
                ),
            )
        elif f[0] == "ERROR":
            bf = _br_fail(w)
            _emit_fail(trace, w.br, bf)
            add(
                "c_recv",
                **_advance(
                    cfg, w, dict(bf, degrades=w.degrades + 1, s2c=rest)
                ),
            )

    if (
        w.phase == "wait"
        and kn.client_deadline
        and w.wait_age >= DEADLINE_TICKS
    ):
        bf = _br_fail(w)
        _emit_fail(trace, w.br, bf)
        add(
            "c_timeout",
            **_advance(
                cfg, w,
                dict(bf, degrades=w.degrades + 1, conn=False, c2s=(), s2c=()),
            ),
        )

    if w.phase == "wait" and not w.conn and not w.s2c:
        # the connection died under the request: _roundtrip resends
        # (bounded), then SolverUnavailable -> failure + degrade
        if w.retries < MAX_RETRIES and w.alive and not w.drain:
            mode = w.sent or "snap"
            frame = _request_frame(replace(w, acked_e=w.acked_e if mode == "delta" else 0))[1]
            if trace is not None:
                trace.conn += 1
            fields = _dead_handler_unwind(w)
            fields.update(
                retries=w.retries + 1, conn=True, c2s=(frame,), wait_age=0
            )
            add("c_conn_lost", **fields)
        else:
            bf = _br_fail(w)
            _emit_fail(trace, w.br, bf)
            add(
                "c_conn_lost",
                **_advance(cfg, w, dict(bf, degrades=w.degrades + 1)),
            )

    # ---- server -----------------------------------------------------------
    if w.alive and w.conn and not w.pend and w.c2s:
        f, rest = w.c2s[0], w.c2s[1:]
        if trace is not None:
            wire = {"SOLVE": KIND_SOLVE, "DELTA": KIND_SOLVE_DELTA}.get(f[0], 0)
            _emit(
                trace, "srv_recv", kind=wire, req_id=0, conn=trace.conn,
                draining=w.drain,
            )
        if f[0] == "JUNK":
            add(
                "s_recv",
                c2s=rest,
                pend=(("send", ("ERROR", f[1])), ("close",)),
            )
        elif w.drain:
            viol = ()
            refusals = w.refusals + 1
            if kn.drain_mode == "close_silent":
                pend: tuple = (("close",),)
                refusals = w.refusals
            elif kn.drain_single_refusal:
                pend = (("send", ("ERRDRAIN", f[1])), ("close",))
            else:
                pend = (("send", ("ERRDRAIN", f[1])),)
            if refusals > 1:
                viol = (
                    (
                        "proto-drain-bounded",
                        "a handler served a SECOND drain refusal on one "
                        "connection: a fast-sending peer holds its thread "
                        "and socket past stop()'s bounded join",
                    ),
                )
            add(
                "s_recv", viol, c2s=rest, pend=pend, refusals=refusals,
                owed=w.owed + 1,
            )
        elif w.over > 0:
            add(
                "s_recv",
                c2s=rest,
                over=w.over - 1,
                pend=(("send", ("RETRY", f[1], 1)),),
                owed=w.owed + 1,
            )
        elif f[0] == "SOLVE":
            if kn.snapshot_resyncable and w.se == 0:
                pend = (("send", ("RESYNC", f[1])),)
            else:
                store = ("store", f[2], f[3], True)
                send = ("send", ("RESULT", f[1], f[2], f[3]))
                pend = (store, send) if kn.store_before_answer else (send, store)
            add("s_recv", c2s=rest, pend=pend, owed=w.owed + 1)
        elif f[0] == "DELTA":
            if w.se != f[2]:
                add(
                    "s_recv", c2s=rest,
                    pend=(("send", ("RESYNC", f[1])),), owed=w.owed + 1,
                )
            else:
                applied = w.sv + (f[5] - f[3])
                viol = ()
                if applied != f[5]:
                    viol = (
                        (
                            "proto-epoch-consistent",
                            "silent epoch divergence: the delta applied "
                            f"cleanly (epoch ids match) but materialized "
                            f"version {applied} != the client's {f[5]} — "
                            "the stored sections were not a private copy",
                        ),
                    )
                store = ("store", f[4], applied, False)
                send = ("send", ("RESULT", f[1], f[4], f[5]))
                pend = (store, send) if kn.store_before_answer else (send, store)
                add("s_recv", viol, c2s=rest, pend=pend, owed=w.owed + 1)

    if w.alive and w.pend:
        op, rest = w.pend[0], w.pend[1:]
        if op[0] == "store":
            _emit(trace, "srv_epoch_store", client="model", epoch=op[1])
            add("s_step", pend=rest, se=op[1], sv=op[2], ssnap=op[3])
        elif op[0] == "send":
            f = op[1]
            if w.conn:
                if trace is not None:
                    wire = {
                        "RESULT": KIND_RESULT, "RESYNC": KIND_EPOCH_RESYNC,
                        "RETRY": KIND_RETRY, "ERRDRAIN": KIND_ERROR,
                        "ERROR": KIND_ERROR,
                    }[f[0]]
                    _emit(
                        trace, "srv_send", kind=wire, req_id=0,
                        conn=trace.conn, draining=w.drain,
                        refusal=f[0] == "ERRDRAIN",
                    )
                add(
                    "s_step",
                    pend=rest,
                    owed=max(0, w.owed - 1),
                    s2c=w.s2c + (f,),
                )
            else:
                # dead socket: the send raises EPIPE and the handler
                # unwinds — every later micro-op is lost
                add("s_step", pend=(), owed=0, refusals=0)
        else:  # close
            viol = ()
            if w.owed > 0 and w.drain:
                viol = (
                    (
                        "proto-drain-bounded",
                        "silent drain close: a solve frame received during "
                        "stop() was closed UNANSWERED — the client waits "
                        "out its full deadline instead of degrading now",
                    ),
                )
            _emit(trace, "srv_close", conn=trace.conn if trace else 0, draining=w.drain)
            add(
                "s_step", viol, pend=rest, conn=False, refusals=0, owed=0,
                c2s=(),
            )

    if w.alive and w.drain and w.conn and not w.pend and not w.c2s:
        _emit(trace, "srv_close", conn=trace.conn if trace else 0, draining=True)
        add("s_drain_close", conn=False, refusals=0, owed=0)

    # ---- environment ------------------------------------------------------
    if scn.allow_drain and w.alive and not w.drain:
        add("a_drain", drain=True)

    if not w.alive:
        add("a_server_up", alive=True)

    if budget_left:
        fb = w.faults + 1
        if "kill_server" in scn.faults and w.alive:
            add(
                "f_kill_server", alive=False, drain=False, conn=False,
                se=0, sv=0, ssnap=False, pend=(), over=0, refusals=0,
                owed=0, c2s=(), faults=fb,
            )
        if "kill_conn" in scn.faults and w.conn:
            add("f_kill_conn", conn=False, c2s=(), s2c=(), faults=fb)
        if "drop_c2s" in scn.faults and w.c2s:
            add("f_drop_c2s", c2s=w.c2s[1:], faults=fb)
        if "drop_s2c" in scn.faults and w.s2c:
            add("f_drop_s2c", s2c=w.s2c[1:], faults=fb)
        if "dup_c2s" in scn.faults and w.c2s and len(w.c2s) < 3:
            add("f_dup_c2s", c2s=(w.c2s[0],) + w.c2s, faults=fb)
        if "dup_s2c" in scn.faults and w.s2c and len(w.s2c) < 3:
            add("f_dup_s2c", s2c=(w.s2c[0],) + w.s2c, faults=fb)
        if "reorder_c2s" in scn.faults and len(w.c2s) >= 2:
            add(
                "f_reorder_c2s",
                c2s=(w.c2s[1], w.c2s[0]) + w.c2s[2:],
                faults=fb,
            )
        if "reorder_s2c" in scn.faults and len(w.s2c) >= 2:
            add(
                "f_reorder_s2c",
                s2c=(w.s2c[1], w.s2c[0]) + w.s2c[2:],
                faults=fb,
            )
        if "trunc_c2s" in scn.faults and w.c2s:
            add(
                "f_trunc_c2s",
                c2s=(("JUNK", w.c2s[0][1]),) + w.c2s[1:],
                faults=fb,
            )
        if "trunc_s2c" in scn.faults and w.s2c:
            add(
                "f_trunc_s2c",
                s2c=(("JUNK", w.s2c[0][1]),) + w.s2c[1:],
                faults=fb,
            )

    if w.ticks < scn.max_ticks and (
        (w.phase == "wait" and kn.client_deadline and w.wait_age < DEADLINE_TICKS)
        or w.backoff > 0
        or w.brcool > 0
    ):
        add(
            "tick",
            ticks=w.ticks + 1,
            wait_age=w.wait_age + 1
            if (w.phase == "wait" and w.wait_age < DEADLINE_TICKS)
            else w.wait_age,
            backoff=max(0, w.backoff - 1),
            brcool=max(0, w.brcool - 1),
        )

    return out


# -- exploration, replay, shrink --------------------------------------------


@dataclass
class Counterexample:
    rule: str
    scenario: str
    knobs: Knobs
    schedule: list
    message: str

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "scenario": self.scenario,
            "knobs": dataclasses.asdict(self.knobs),
            "schedule": list(self.schedule),
            "message": self.message,
            "repro": REPRO_HINT,
        }


REPRO_HINT = "pytest tests/test_proto_analysis.py -k corpus -q"


@dataclass
class ExploreResult:
    scenario: str
    states: int
    truncated: bool
    seconds: float
    counterexamples: list


def explore(cfg: Config, stop_on_first: bool = False) -> ExploreResult:
    """Breadth-first exploration with canonical dedup: the FIRST
    counterexample found per property is a shortest one (every
    transition is one label). Deadlock (a live client with no enabled
    transition) violates proto-converge. `stop_on_first` abandons the
    frontier once any property has a counterexample — the
    deliberately-broken models in tests use it; the real tier always
    runs to exhaustion (or its recorded budget)."""
    t0 = time.monotonic()
    scn = cfg.scenario
    w0 = initial_world(scn)
    seen = {canonical(w0)}
    parent: dict[tuple, tuple] = {}  # canon -> (parent canon, label)
    frontier = deque([(w0, canonical(w0))])
    ces: dict[str, Counterexample] = {}
    truncated = False

    def path_to(key: tuple, last: Optional[str] = None) -> list:
        labels: list[str] = []
        while key in parent:
            key, lab = parent[key]
            labels.append(lab)
        labels.reverse()
        if last is not None:
            labels.append(last)
        return labels

    while frontier:
        if stop_on_first and ces:
            break
        w, key = frontier.popleft()
        if done(cfg, w):
            continue
        succs = step(cfg, w)
        if not succs:
            if step(cfg, replace(w, ticks=0)):
                # only the tick budget blocks progress: that is the
                # exploration bound biting, not a protocol deadlock —
                # report truncation, never a phantom converge violation
                truncated = True
                continue
            if "proto-converge" not in ces:
                ces["proto-converge"] = Counterexample(
                    "proto-converge", scn.name, cfg.knobs, path_to(key),
                    f"deadlock: solve {w.solve}/{scn.n_solves} can never "
                    "complete (no transition is enabled; the client waits "
                    "forever)",
                )
            continue
        for label, w2, viols in succs:
            for rule, msg in viols:
                if rule not in ces:
                    ces[rule] = Counterexample(
                        rule, scn.name, cfg.knobs, path_to(key, label), msg
                    )
            k2 = canonical(w2)
            if k2 in seen:
                continue
            if len(seen) >= scn.max_states:
                truncated = True
                continue
            seen.add(k2)
            parent[k2] = (key, label)
            frontier.append((w2, k2))

    return ExploreResult(
        scn.name, len(seen), truncated, time.monotonic() - t0,
        list(ces.values()),
    )


def replay(
    cfg: Config, schedule: Iterable[str]
) -> tuple[Optional[World], list]:
    """Deterministically re-run a label schedule. Returns (final world,
    violations seen); (None, []) if some label was not enabled (an
    invalid shrink candidate). A final live-but-stuck world appends the
    proto-converge deadlock violation, so converge counterexamples
    replay too."""
    w = initial_world(cfg.scenario)
    seen_viols: list = []
    for label in schedule:
        succs = {lab: (w2, v) for lab, w2, v in step(cfg, w)}
        if label not in succs:
            return None, []
        w, viols = succs[label]
        seen_viols.extend(viols)
    if (
        not done(cfg, w)
        and not step(cfg, w)
        and not step(cfg, replace(w, ticks=0))  # tick budget != deadlock
    ):
        seen_viols.append(("proto-converge", "deadlock"))
    return w, seen_viols


def shrink(cfg: Config, ce: Counterexample) -> Counterexample:
    """Greedy delta-shrink: drop one label at a time while the replay
    still violates the same property. BFS already returned a shortest
    PATH; shrinking prunes labels that only pad the schedule (extra
    ticks, unrelated faults), leaving the minimal fault story to pin in
    the corpus."""
    schedule = list(ce.schedule)
    changed = True
    while changed:
        changed = False
        for i in range(len(schedule) - 1, -1, -1):
            candidate = schedule[:i] + schedule[i + 1 :]
            _, viols = replay(cfg, candidate)
            if any(rule == ce.rule for rule, _ in viols):
                schedule = candidate
                changed = True
    return Counterexample(ce.rule, ce.scenario, ce.knobs, schedule, ce.message)


def trace_of(cfg: Config, schedule: Iterable[str]) -> list[dict]:
    """Replay a schedule while emitting protorec-schema events — the
    bridge that lets model runs be judged by the SAME acceptors as
    recorded real traces (model soundness half of the refinement story;
    tests/test_proto_analysis.py pins real-knob model traces clean)."""
    w = initial_world(cfg.scenario)
    tr = _Trace()
    for label in schedule:
        # step() emits for EVERY enabled transition; run one
        # trace-enabled pass, keep only the chosen label's slice. The
        # conn counter stays consistent because the only incrementers
        # (c_attempt / c_conn_lost while disconnected) are mutually
        # exclusive with every server-side emitter (which needs w.conn).
        probe = _Trace()
        probe.conn = tr.conn
        slices: dict[str, tuple[int, int]] = {}
        succs: dict[str, World] = {}
        start = 0
        for lab, w2, _ in step(cfg, w, trace=probe):
            slices[lab] = (start, len(probe.events))
            succs[lab] = w2
            start = len(probe.events)
        if label not in succs:
            raise ValueError(f"label {label!r} not enabled during trace_of")
        lo, hi = slices[label]
        for e in probe.events[lo:hi]:
            e["i"] = len(tr.events)
            tr.events.append(e)
        if label in ("c_attempt", "c_conn_lost") and succs[label].conn and not w.conn:
            tr.conn = probe.conn
        w = succs[label]
    return tr.events


# -- refinement: judging recorded traces ------------------------------------


def check_refinement(events: list[dict]) -> list[str]:
    """Is this recorded trace an accepted behavior of the model? The
    acceptors encode the model's transition discipline over the
    protorec event schema; a violation names the broken contract and
    the offending events. Used three ways: on every `faults`-marked
    test (tests/conftest.py), on the live scenarios `graftlint --proto`
    drives, and on model-generated traces (tests pin both directions)."""
    out: list[str] = []
    out += _check_breaker_legality(events)
    out += _check_attempt_obligations(events)
    out += _check_drain_conns(events)
    out += _check_epoch_commits(events)
    out += _check_client_roundtrips(events)
    return out


def _check_breaker_legality(events: list[dict]) -> list[str]:
    out = []
    for e in events:
        ev = e.get("ev")
        if ev == "breaker_allow":
            st, granted, probe = e.get("state"), e.get("granted"), e.get("probe")
            if granted and st == "closed" and probe:
                out.append(f"breaker: closed allow claimed a probe: {e}")
            elif granted and st == "half-open" and not probe:
                out.append(
                    f"breaker: half-open allow without the probe slot: {e}"
                )
            elif granted and st == "open":
                out.append(f"breaker: allow granted while open: {e}")
            elif not granted and st == "closed":
                out.append(f"breaker: allow denied while closed: {e}")
        elif ev == "breaker_success":
            if e.get("state") != "closed" or e.get("failures", 0) != 0:
                out.append(
                    f"breaker: record_success must close and zero: {e}"
                )
        elif ev == "breaker_failure":
            prev, st = e.get("prev"), e.get("state")
            must_open = prev in ("half-open", "open") or e.get(
                "failures", 0
            ) >= e.get("threshold", 1)
            if must_open and st != "open":
                out.append(
                    f"breaker: failure at/past threshold (or on a probe) "
                    f"must open: {e}"
                )
            if not must_open and st != "closed":
                out.append(f"breaker: premature open: {e}")
    return out


def _check_attempt_obligations(events: list[dict]) -> list[str]:
    """Per-thread request discipline: a granted allow() must be resolved
    by the matching record_* BEFORE the attempt outcome is declared —
    the RETRY-records-success rule makes `overloaded` require a
    breaker_success (hybrid.py admission-rejection branch); reverting it
    strands the probe and fails HERE, with the event pair named."""
    out = []
    lanes: dict[tuple, dict] = {}
    for e in events:
        ev = e.get("ev")
        if ev not in ("breaker_allow", "breaker_success", "breaker_failure", "attempt"):
            continue
        key = (e.get("thread"), e.get("name", ""))
        if ev == "attempt":
            # attempt events carry no breaker name; match any lane of
            # the thread (the solver drives one breaker per attempt)
            cand = [k for k in lanes if k[0] == e.get("thread")]
            lane = lanes.get(cand[0]) if cand else None
            outcome = e.get("outcome")
            if outcome in ("success", "overloaded"):
                if lane is None or not lane.get("granted"):
                    out.append(f"attempt {outcome!r} without a granted allow: {e}")
                elif not lane.get("success"):
                    tag = (
                        " — the half-open probe is STRANDED (RETRY must "
                        "record_success)"
                        if outcome == "overloaded" and lane.get("probe")
                        else ""
                    )
                    out.append(
                        f"attempt {outcome!r} without record_success{tag}: {e}"
                    )
            elif outcome == "failure":
                if lane is None or not lane.get("granted"):
                    out.append(f"attempt 'failure' without a granted allow: {e}")
                elif not lane.get("failure"):
                    out.append(f"attempt 'failure' without record_failure: {e}")
            elif outcome == "breaker_denied":
                if lane is None or lane.get("granted"):
                    out.append(
                        f"attempt 'breaker_denied' without a denied allow: {e}"
                    )
            elif outcome == "backoff":
                if lane is not None:
                    out.append(
                        "attempt 'backoff' after allow(): backoff must be "
                        f"checked BEFORE the probe is claimed: {e}"
                    )
            for k in cand:
                lanes.pop(k, None)
        elif ev == "breaker_allow":
            lanes[key] = {
                "granted": bool(e.get("granted")),
                "probe": bool(e.get("probe")),
                "success": False,
                "failure": False,
            }
        elif key in lanes:
            lanes[key]["success" if ev == "breaker_success" else "failure"] = True
    return out


def _check_drain_conns(events: list[dict]) -> list[str]:
    out = []
    conns: dict[int, dict] = {}
    for e in events:
        ev = e.get("ev")
        if ev not in ("srv_recv", "srv_send", "srv_close"):
            continue
        c = conns.setdefault(
            e.get("conn"), {"owed": [], "refusals": 0, "refused": False}
        )
        if ev == "srv_recv":
            if e.get("kind") in _SOLVE_KINDS:
                c["owed"].append(bool(e.get("draining")))
        elif ev == "srv_send":
            if c["refused"]:
                out.append(
                    f"drain: a frame was sent AFTER the refusal on conn "
                    f"{e.get('conn')} (one refusal, then close): {e}"
                )
            if e.get("kind") in _RESPONSE_KINDS and c["owed"]:
                c["owed"].pop(0)
            if e.get("refusal"):
                c["refusals"] += 1
                c["refused"] = True
                if c["refusals"] > 1:
                    out.append(
                        f"drain: second refusal on conn {e.get('conn')}: {e}"
                    )
        else:  # srv_close
            if c["owed"] and (e.get("draining") or any(c["owed"])):
                out.append(
                    f"drain: silent close — {len(c['owed'])} received solve "
                    f"frame(s) on conn {e.get('conn')} closed unanswered "
                    f"during drain: {e}"
                )
            conns.pop(e.get("conn"), None)
    return out


def _check_epoch_commits(events: list[dict]) -> list[str]:
    """Commit-implies-store, with the mixed-version carve-out: a
    DELTA commit requires a prior store (the server solved from sections
    it must hold), and a store that exists must PRECEDE the commit
    riding its answer (the store-before-answer fix). A snapshot commit
    with no store at all is accepted — that is the pre-epoch peer
    (mixed-version rollout: the old server ignores the epoch key), and
    the acked state is a deliberate fiction the first delta's
    'unknown kind' downgrade corrects (service.py pre-epoch branch)."""
    out = []
    first_store: dict = {}
    for pos, e in enumerate(events):
        if e.get("ev") in ("srv_epoch_store", "srv_epoch_store_skipped"):
            first_store.setdefault((e.get("client"), e.get("epoch")), pos)
    for pos, e in enumerate(events):
        if e.get("ev") != "cli_epoch_commit":
            continue
        # the model emits client="model" on both sides; real traces
        # carry the wire client id on both hooks
        stored_at = first_store.get(
            (e.get("client"), e.get("epoch")),
            first_store.get(("model", e.get("epoch"))),
        )
        if stored_at is not None and stored_at < pos:
            continue
        if stored_at is not None:
            out.append(
                "epoch: the server stored epoch "
                f"{e.get('epoch')!r} AFTER the client committed it — "
                f"store must precede answer: {e}"
            )
        elif e.get("mode") != "snapshot":
            out.append(
                "epoch: client committed epoch "
                f"{e.get('epoch')!r} that the server never stored (nor "
                f"deliberately skipped) — store must precede answer: {e}"
            )
    return out


def _check_client_roundtrips(events: list[dict]) -> list[str]:
    out = []
    must_snapshot: dict = {}
    for e in events:
        if e.get("ev") != "cli_roundtrip":
            continue
        k, rk, cl = e.get("kind"), e.get("resp_kind"), e.get("client")
        if k not in _SOLVE_KINDS:
            continue
        if k == KIND_SOLVE and rk == KIND_EPOCH_RESYNC:
            out.append(
                f"resync: a full-snapshot SOLVE was answered EPOCH_RESYNC "
                f"(the fallback has no fallback): {e}"
            )
        if must_snapshot.get(cl) and k != KIND_SOLVE:
            out.append(
                f"resync: after EPOCH_RESYNC the next solve frame must be "
                f"the full snapshot, got kind {k}: {e}"
            )
        must_snapshot[cl] = k == KIND_SOLVE_DELTA and rk == KIND_EPOCH_RESYNC
    return out


def shrink_trace(events: list[dict], violation: str) -> list[dict]:
    """Minimal violating sub-trace for a conformance finding: keep only
    the events whose stream (conn / thread / client) the violation
    implicates, so the repro in the report reads as the few frames that
    matter, not the whole fault matrix."""
    for sub_len in range(1, len(events) + 1):
        sub = events[:sub_len]
        if violation in check_refinement(sub):
            last = sub[-1]
            keys = {
                ("conn", last.get("conn")),
                ("thread", last.get("thread")),
                ("client", last.get("client")),
            }
            kept = [
                e
                for e in sub
                if any(e.get(k) == v for k, v in keys if v is not None)
            ]
            if violation in check_refinement(kept):
                return kept
            return sub
    return events


# -- live conformance scenarios ---------------------------------------------


def _empty_decoded() -> dict:
    return {
        "new_node_claims": [],
        "existing_assignments": {},
        "pod_errors": {},
        "timed_out": False,
    }


def live_breaker_scenario() -> list[dict]:
    """Drive the REAL ResilientSolver + CircuitBreaker through the
    pinned recovery story on a fake clock: two transport failures open
    the breaker, the cooldown elapses, the half-open probe lands on an
    admission RETRY (which MUST resolve it to closed —
    hybrid.py:~612), and the immediate next attempt reaches the
    sidecar. Recorded via protorec and judged by check_refinement: if
    the RETRY-records-success line is reverted, the trace itself fails
    (stranded-probe obligation), not a hand-written assert."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from karpenter_tpu.analysis import protorec
    from karpenter_tpu.solver import hybrid
    from karpenter_tpu.solver.epochs import SolverOverloaded
    from karpenter_tpu.solver.service import SolverUnavailable

    clock = {"t": 0.0}

    def fail():
        raise SolverUnavailable("sidecar unreachable (scripted)")

    def overloaded():
        raise SolverOverloaded(
            "admission rejected (scripted)",
            backoff_hint_seconds=0.0,
            queue_depth=1,
        )

    script = [fail, fail, overloaded, _empty_decoded]

    class _Scripted:
        def solve(self, *args, **kwargs):
            return script.pop(0)()

    rs = hybrid.ResilientSolver(
        client=_Scripted(),
        failure_threshold=2,
        cooldown_seconds=10.0,
        clock=lambda: clock["t"],
    )
    rec = protorec.install()
    try:
        for advance in (0.0, 0.0, 0.0, 11.0, 0.0):
            clock["t"] += advance
            rs.solve([], {}, [], force_oracle=True)
        # deliberately NO assertion that the script was fully consumed:
        # the refinement acceptors are the judge. With the RETRY-records-
        # success line reverted, attempt 4's `overloaded` event arrives
        # without its breaker_success and check_refinement names the
        # stranded probe — a finding (exit 1), not a crashed gate (2).
        return rec.snapshot()
    finally:
        protorec.uninstall()


def live_drain_scenario() -> list[dict]:
    """Drive a REAL SolverServer through the drain contract on raw
    sockets: one connection holds an in-flight solve across stop() (its
    RESULT must flush), a second sends a fresh SOLVE during the drain
    window (it must get the one retriable refusal, then close). The
    PING right before stop() re-phases the handler's poll so the
    post-stop SOLVE lands inside the grace read, same determinism
    discipline as tests/test_service_faults.py's drain tests."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import socket
    import struct
    import tempfile
    import threading

    from karpenter_tpu.analysis import protorec
    from karpenter_tpu.solver import service

    release = threading.Event()

    class _SlowServer(service.SolverServer):
        def _solve(self, payload: bytes, req_id: int = 0) -> bytes:
            release.wait(10.0)
            return b"{}"

    def send(sock, kind, payload=b"{}", req_id=1):
        sock.sendall(
            service.MAGIC
            + struct.pack("<III", kind, req_id, len(payload))
            + payload
        )

    def read_frame(sock):
        head = b""
        while len(head) < service.HEADER_LEN:
            chunk = sock.recv(service.HEADER_LEN - len(head))
            if not chunk:
                return None
            head += chunk
        kind, req_id, length = struct.unpack("<III", head[4:])
        body = b""
        while len(body) < length:
            body += sock.recv(length - len(body))
        return kind, req_id, body

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "solver.sock")
        srv = _SlowServer(path, drain_seconds=5.0)
        rec = protorec.install()
        stopper = None
        try:
            srv.start()
            s1 = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s1.settimeout(10.0)
            s1.connect(path)
            s2 = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s2.settimeout(10.0)
            s2.connect(path)
            try:
                send(s1, service.KIND_SOLVE, req_id=7)  # in-flight, stalls
                # re-phase conn 2's idle poll right before the drain
                send(s2, service.KIND_PING, b"", req_id=8)
                assert read_frame(s2)[0] == service.KIND_PONG
                stopper = threading.Thread(target=srv.stop, daemon=True)
                stopper.start()
                while not srv._stop.is_set():
                    time.sleep(0.001)
                # a fresh solve inside the drain window: one retriable
                # refusal, then the connection closes
                send(s2, service.KIND_SOLVE, req_id=9)
                refusal = read_frame(s2)
                trailing = s2.recv(1)
                release.set()  # flush the in-flight RESULT on conn 1
                flushed = read_frame(s1)
                if refusal is not None and refusal[0] == service.KIND_ERROR:
                    pass  # the healthy answer; refinement judges the trace
                if flushed is not None and flushed[0] != service.KIND_RESULT:
                    raise RuntimeError(
                        f"in-flight solve flushed kind {flushed[0]}, "
                        "expected RESULT"
                    )
                del trailing
            finally:
                release.set()
                s1.close()
                s2.close()
            if stopper is not None:
                stopper.join(timeout=10.0)
            return rec.snapshot()
        finally:
            release.set()
            protorec.uninstall()
            if stopper is not None and stopper.is_alive():
                stopper.join(timeout=10.0)


LIVE_SCENARIOS: tuple = (
    ("live_breaker_retry", "karpenter_tpu/solver/hybrid.py", live_breaker_scenario),
    ("live_drain", "karpenter_tpu/solver/service.py", live_drain_scenario),
)


# -- the tier entry point ---------------------------------------------------

MODEL_PATH = "karpenter_tpu/analysis/proto.py"


def emit_counterexample(ce: Counterexample, corpus_dir: str) -> str:
    """Serialize a shrunk counterexample into the replay corpus (the
    fuzz-corpus lifecycle: pinned, replayed FIRST by
    tests/test_proto_analysis.py). Canonical serialization — sorted
    keys, LF, trailing newline — so a re-emit of the same schedule is
    byte-identical."""
    os.makedirs(corpus_dir, exist_ok=True)
    path = os.path.join(corpus_dir, f"{ce.rule}__{ce.scenario}.json")
    with open(path, "w") as fh:
        json.dump(ce.to_json(), fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def replay_corpus_case(case: dict) -> list:
    """Re-run a corpus entry; returns the violated rule names."""
    scn = next(s for s in SCENARIOS if s.name == case["scenario"])
    cfg = Config(Knobs(**case["knobs"]), scn)
    _, viols = replay(cfg, case["schedule"])
    return sorted({rule for rule, _ in viols})


def run_proto_analysis(
    repo_root: str,
    baseline_path: Optional[str] = None,
    knobs: Knobs = REAL_KNOBS,
    scenarios: Optional[tuple] = None,
    live: bool = True,
    corpus_dir: Optional[str] = None,
    live_fns: Optional[tuple] = None,
) -> dict:
    """The protocol tier: model-check every scenario under `knobs`, run
    the live conformance scenarios, apply the proto baseline. Mirrors
    the other tiers' report shape ({"findings", "stale", "unjustified",
    "errors", "total"}) plus the exploration budgets ("scenarios") and
    per-property verdicts ("properties") — a truncated exploration is
    visible in the report, never silent."""
    baseline_path = (
        baseline_path
        if baseline_path is not None
        else os.path.join(repo_root, PROTO_DEFAULT_BASELINE)
    )
    if corpus_dir is None:
        default_corpus = os.path.join(repo_root, "tests", "proto_corpus")
        corpus_dir = default_corpus if os.path.isdir(default_corpus) else ""

    findings: list[Finding] = []
    errors: list[str] = []
    scen_report: dict[str, dict] = {}
    properties = {rule: "ok" for rule in PROTO_RULES}

    for scn in scenarios if scenarios is not None else SCENARIOS:
        cfg = Config(knobs, scn)
        res = explore(cfg)
        scen_report[scn.name] = {
            "states": res.states,
            "truncated": res.truncated,
            "seconds": round(res.seconds, 3),
            "n_solves": scn.n_solves,
            "fault_budget": scn.fault_budget,
            "max_ticks": scn.max_ticks,
            "max_states": scn.max_states,
        }
        for ce in res.counterexamples:
            ce = shrink(cfg, ce)
            properties[ce.rule] = "violated"
            repro = REPRO_HINT
            if corpus_dir:
                try:
                    emit_counterexample(ce, corpus_dir)
                except OSError as e:
                    errors.append(f"corpus write failed: {e}")
            findings.append(
                Finding(
                    rule=ce.rule,
                    path=MODEL_PATH,
                    line=1,
                    message=(
                        f"[{ce.scenario}] {ce.message} | shrunk schedule "
                        f"({len(ce.schedule)} steps): "
                        f"{' '.join(ce.schedule)} | repro: {repro}"
                    ),
                    text=f"{ce.scenario}:{ce.rule}",
                )
            )

    conformance: dict[str, int] = {}
    if live:
        for name, path, fn in live_fns if live_fns is not None else LIVE_SCENARIOS:
            try:
                events = fn()
            except Exception as e:  # a broken gate, not a finding
                errors.append(f"{name}: {type(e).__name__}: {e}")
                continue
            conformance[name] = len(events)
            for violation in check_refinement(events):
                properties["proto-conformance"] = "violated"
                sub = shrink_trace(events, violation)
                findings.append(
                    Finding(
                        rule="proto-conformance",
                        path=path,
                        line=1,
                        message=(
                            f"[{name}] recorded trace does not refine the "
                            f"model: {violation} | minimal sub-trace "
                            f"({len(sub)} events): "
                            + "; ".join(
                                f"{e.get('ev')}({_fmt_event(e)})" for e in sub
                            )
                            + f" | repro: pytest tests/test_proto_analysis.py"
                            f" -k {name} -q"
                        ),
                        text=f"{name}:{violation.split(':', 1)[0]}",
                    )
                )

    findings.sort(key=lambda f: (f.rule, f.path, f.text))
    baseline = Baseline.load(baseline_path)
    fresh, stale = baseline.apply(findings)
    return {
        "findings": fresh,
        "all_findings": findings,
        "stale": stale,
        "unjustified": baseline.unjustified(),
        "errors": errors,
        "total": len(fresh),
        "scenarios": scen_report,
        "properties": properties,
        "conformance": conformance,
    }


def _fmt_event(e: dict) -> str:
    skip = {"ev", "i", "thread"}
    return ",".join(
        f"{k}={v}" for k, v in e.items() if k not in skip and v is not None
    )
