"""graftlint race tier, static half: whole-program lock analysis.

The AST tier's `lock-discipline` rule is per-class and per-file: it can
prove a guarded attribute is never written bare, but it cannot see a
lock-ORDER inversion (thread 1 takes A then B, thread 2 takes B then A —
each side locally consistent, jointly a deadlock), a blocking call made
while a lock is held (every contending thread stalls behind the socket /
sleep / device sync), or a write racing between a `threading.Thread`
body and the public surface of the same object. Those are the classic
lockset/happens-before bugs (Eraser, ThreadSanitizer), and this module
finds the statically findable slice of them:

- Inventory: every `threading.Lock/RLock/Condition/Event` bound to a
  `self.<attr>` in a class body or a module-level name, across the whole
  package and tests (an inversion is a property of the PROGRAM, not of
  one file).
- Held spans: `with self.<lock>:` blocks (including multi-item withs,
  in item order), `acquire()`…`release()` statement pairs, and the
  `*_locked`-suffix convention (the caller holds a lock by contract —
  same convention the AST tier's lock-discipline rule honors).
- Acquisition graph: a directed edge A -> B for every place lock B is
  acquired while A is held, followed INTERPROCEDURALLY through
  same-class method calls (`self.m()` under a lock inherits the held
  set) and same-module function calls.

Rules (engine-integrated: suppressions, graftlint.race.baseline.json,
`--json`, exit codes — see docs/static-analysis.md "Race tier"):

- `race-lock-order`: a cycle in the acquisition graph (two locks taken
  in both orders somewhere in the program), or a non-reentrant
  Lock/Condition re-acquired while already held on the same path (a
  guaranteed self-deadlock). The runtime half (analysis/racert.py)
  witnesses the same property dynamically under the fault suite.
- `race-blocking-hold`: a blocking call in a held span — socket
  recv/send/accept/connect, `subprocess.*`, `time.sleep`, a queue-style
  `.get()` with no timeout, and (in modules that import jax) device
  syncs (`block_until_ready`, `.item()`, `np.asarray`/`np.array`,
  `jax.device_get`) that ride the slow host<->device tunnel while every
  contending thread waits (CLAUDE.md transfer note).
- `race-unguarded-shared`: an attribute written both from a
  `threading.Thread(target=self.<m>)` body and from the class's public
  surface — each side followed transitively through same-class calls,
  so `stop()` delegating to `_shutdown()` counts as a public write —
  with no COMMON lock guarding every write; the interprocedural upgrade
  of the AST tier's lock-discipline rule, which only sees attributes
  that were formally guarded somewhere.

Pure stdlib `ast`: importing this module must never pull in JAX or
numpy (tests/test_race_analysis.py pins it the same way the AST tier's
gate does).
"""

from __future__ import annotations

import ast
import os
from typing import Optional

from karpenter_tpu.analysis.engine import (
    Baseline,
    Config,
    FileContext,
    Finding,
    base_name,
    discover_files,
)

RACE_RULES: dict[str, str] = {
    "race-lock-order": (
        "the program-wide lock acquisition graph must be acyclic, and "
        "non-reentrant locks must not be re-acquired on a path that "
        "already holds them"
    ),
    "race-blocking-hold": (
        "no blocking call (socket I/O, subprocess, sleep, untimed "
        "queue get, device sync) while holding a threading lock"
    ),
    "race-unguarded-shared": (
        "attributes written from both a threading.Thread target and a "
        "public method need one common lock guarding every write"
    ),
}

DEFAULT_BASELINE = "graftlint.race.baseline.json"

# constructor names inventoried as locks; Event carries no ordering (it
# is never held) and is inventoried only so the model knows the attr is
# synchronization state, not shared data
_HELD_KINDS = frozenset({"Lock", "RLock", "Condition"})
_LOCK_CTORS = _HELD_KINDS | frozenset({"Event"})
_REENTRANT = frozenset({"RLock", "Condition"})  # Condition wraps an RLock

# mutator methods that write their receiver in place (the AST tier's
# lock-discipline list, minus dict.get-style readers)
_MUTATORS = frozenset(
    {
        "add",
        "append",
        "appendleft",
        "clear",
        "discard",
        "extend",
        "insert",
        "pop",
        "popleft",
        "remove",
        "setdefault",
        "update",
    }
)

_SOCKET_BLOCKING = frozenset(
    {"recv", "recv_into", "recvfrom", "accept", "send", "sendall", "connect"}
)
_SUBPROCESS_BLOCKING = frozenset(
    {"run", "call", "check_call", "check_output", "Popen", "communicate"}
)

# the caller-holds-a-lock-by-contract convention (lock-discipline rule)
_LOCKED_SUFFIX = "_locked"

# the wildcard guard: a write inside a *_locked method is guarded by
# whatever lock the caller holds — it never breaks a common-guard claim
_ANY_GUARD = "*"


def _self_attr(node: ast.AST) -> str:
    """'x' for self.x / self.x[...] expressions, else ''."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


def _lock_ctor_kind(value: ast.AST) -> Optional[str]:
    """'Lock' / 'RLock' / 'Condition' / 'Event' when `value` is a call to
    one of the threading constructors (either spelling), else None."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    name = (
        f.attr
        if isinstance(f, ast.Attribute)
        else f.id
        if isinstance(f, ast.Name)
        else None
    )
    return name if name in _LOCK_CTORS else None


# ---------------------------------------------------------------------------
# per-file program model


class _Scope:
    """One lock-owning scope: a class (locks are `self.<attr>`) or the
    module itself (locks are module-level names). Functions inside the
    scope share the lock namespace and the call graph. Class scopes also
    see their module's locks (`module_locks`) — a method may hold a
    module-level lock, and that hold must land on the SAME graph node as
    module-function holds of it. Refs for module locks carry an `@`
    prefix ("@" cannot appear in an identifier), so a class lock attr
    and a module lock of the same name never alias."""

    def __init__(
        self,
        ctx: FileContext,
        name: str,
        is_class: bool,
        module_locks: Optional[dict[str, str]] = None,
    ):
        self.ctx = ctx
        self.name = name  # class name, or "<module>"
        self.is_class = is_class
        self.locks: dict[str, str] = {}  # attr/name -> kind
        self.module_locks: dict[str, str] = module_locks or {}
        self.lock_lines: dict[str, int] = {}
        self.functions: dict[str, "_Func"] = {}
        self.thread_targets: list[tuple[str, int]] = []  # (method, lineno)

    def kind_of(self, ref: str) -> Optional[str]:
        if ref.startswith("@"):
            return self.module_locks.get(ref[1:])
        return self.locks.get(ref)

    def lock_id(self, ref: str) -> str:
        if ref.startswith("@"):
            return f"{self.ctx.relpath}::<module>.{ref[1:]}"
        return f"{self.ctx.relpath}::{self.name}.{ref}"

    def lock_label(self, ref: str) -> str:
        if ref.startswith("@"):
            return ref[1:]
        return ref if self.name == "<module>" else f"{self.name}.{ref}"


class _Func:
    """One function/method in a scope, reduced to what the race rules
    need: held spans, nested acquisitions, intra-scope calls, blocking
    calls, and attribute writes — each tagged with the locks held there."""

    def __init__(self, scope: _Scope, node: ast.FunctionDef):
        self.scope = scope
        self.node = node
        self.name = node.name
        self.locked_by_contract = node.name.endswith(_LOCKED_SUFFIX)
        # (lock attr, span lo, span hi, acquisition line)
        self.spans: list[tuple[str, int, int, int]] = []
        self.calls: list[tuple[str, int]] = []  # (callee name, line)
        self.blocking: list[tuple[ast.AST, str]] = []  # (node, description)
        # (attr, node, guards held at the write)
        self.writes: list[tuple[str, ast.AST, frozenset[str]]] = []
        # (if-body line range, else line range) pairs: an acquire() span
        # runs to the NEXT release line, so a span opened in one branch
        # textually covers the sibling branch that can never execute
        # with it — lines in opposite branches must not read as "held"
        self.exclusive: list[tuple[tuple[int, int], tuple[int, int]]] = []

    def mutually_exclusive(self, a: int, b: int) -> bool:
        for r1, r2 in self.exclusive:
            if (r1[0] <= a <= r1[1] and r2[0] <= b <= r2[1]) or (
                r1[0] <= b <= r1[1] and r2[0] <= a <= r2[1]
            ):
                return True
        return False

    def held_at(self, line: int) -> list[str]:
        out = []
        for span in self.spans:
            attr, lo, hi, acq = span
            if (
                lo <= line <= hi
                and attr not in out
                and not self.mutually_exclusive(acq, line)
            ):
                out.append(attr)
        return out

    def acquired_locks(self) -> set[str]:
        return {attr for attr, _, _, _ in self.spans}


def _lock_ref(scope: _Scope, expr: ast.AST) -> str:
    """The scope-local lock ref an expression refers to, or ''. In a
    class scope: `self.<attr>` for own locks, `@name` for module-level
    locks (methods may hold module locks); in the module scope: bare
    names."""
    if scope.is_class:
        attr = _self_attr(expr)
        if attr in scope.locks:
            return attr
        if isinstance(expr, ast.Name) and expr.id in scope.module_locks:
            return "@" + expr.id
    elif isinstance(expr, ast.Name) and expr.id in scope.locks:
        return expr.id
    return ""


def _walk_skip_nested_classes(root: ast.AST):
    """ast.walk, minus ClassDef subtrees below the root."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, ast.ClassDef):
                stack.append(child)


def _build_scope(
    ctx: FileContext,
    name: str,
    body: list,
    is_class: bool,
    module_locks: Optional[dict[str, str]] = None,
) -> _Scope:
    scope = _Scope(ctx, name, is_class, module_locks=module_locks)
    # pass 1: lock inventory. Classes: anywhere in the body (__init__
    # included) EXCEPT nested ClassDef subtrees — an inner class's
    # `self._x` is a different object than the outer class's, and
    # conflating them both invents phantom held spans on the outer class
    # and splits one real lock role across two graph identities.
    # Modules: top-level assignments only — a local `lock = Lock()`
    # inside a function is not shared module state.
    candidates = (
        [
            sub
            for node in body
            if not isinstance(node, ast.ClassDef)
            for sub in _walk_skip_nested_classes(node)
        ]
        if is_class
        else list(body)
    )
    for sub in candidates:
        # `self._lock: threading.Lock = threading.Lock()` declares the
        # same shared lock as the bare assignment — missing AnnAssign
        # would silently drop the lock (and every rule over it) from the
        # whole-program analysis
        if isinstance(sub, ast.Assign):
            targets = sub.targets
        elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
            targets = [sub.target]
        else:
            continue
        kind = _lock_ctor_kind(sub.value)
        if kind is None:
            continue
        for t in targets:
            ref = _self_attr(t) if is_class else (
                t.id if isinstance(t, ast.Name) else ""
            )
            if ref:
                scope.locks[ref] = kind
                scope.lock_lines.setdefault(ref, sub.lineno)
    # pass 2: per-function reduction
    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope.functions[node.name] = _reduce_function(scope, node)
    return scope


def _body_region(body: list) -> tuple[int, int]:
    return (body[0].lineno, max(n.end_lineno or n.lineno for n in body))


def _reduce_function(scope: _Scope, fn: ast.FunctionDef) -> _Func:
    info = _Func(scope, fn)
    jaxy = scope.ctx.relpath and _file_imports_jax(scope.ctx)
    acquires: dict[str, list[int]] = {}
    releases: dict[str, list[int]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.If) and node.orelse:
            info.exclusive.append(
                (_body_region(node.body), _body_region(node.orelse))
            )
        if isinstance(node, ast.With):
            for item in node.items:
                ref = _lock_ref(scope, item.context_expr)
                if ref and scope.kind_of(ref) in _HELD_KINDS:
                    info.spans.append(
                        (ref, node.lineno, node.end_lineno or node.lineno, node.lineno)
                    )
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                ref = _lock_ref(scope, f.value)
                if ref and scope.kind_of(ref) in _HELD_KINDS:
                    if f.attr == "acquire":
                        acquires.setdefault(ref, []).append(node.lineno)
                    elif f.attr == "release":
                        releases.setdefault(ref, []).append(node.lineno)
                # intra-scope method call
                if (
                    scope.is_class
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                ):
                    info.calls.append((f.attr, node.lineno))
            elif isinstance(f, ast.Name) and not scope.is_class:
                info.calls.append((f.id, node.lineno))
            desc = _blocking_desc(node, jaxy)
            if desc:
                info.blocking.append((node, desc))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                attr = _self_attr(t) if scope.is_class else ""
                if attr:
                    info.writes.append((attr, node, frozenset()))
        # in-place mutator calls write their receiver
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
        ):
            attr = _self_attr(node.func.value) if scope.is_class else ""
            if attr:
                info.writes.append((attr, node, frozenset()))
    # acquire()/release() statement pairs become held spans: each acquire
    # is paired with the next release of the same lock (function end when
    # none follows — a leaked hold spans the rest of the body)
    end = fn.end_lineno or fn.lineno
    for ref, acq_lines in acquires.items():
        rel_lines = sorted(releases.get(ref, []))
        for a in sorted(acq_lines):
            hi = next((r for r in rel_lines if r >= a), end)
            info.spans.append((ref, a, hi, a))
    # writes get their guard sets now that every span is known
    guarded_writes = []
    for attr, node, _ in info.writes:
        held = frozenset(info.held_at(node.lineno))
        if info.locked_by_contract:
            held = held | {_ANY_GUARD}
        guarded_writes.append((attr, node, held))
    info.writes = guarded_writes
    # thread targets: threading.Thread(target=self.<m>) in a class scope
    if scope.is_class:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_thread = (
                isinstance(f, ast.Attribute) and f.attr == "Thread"
            ) or (isinstance(f, ast.Name) and f.id == "Thread")
            if not is_thread:
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    target = _self_attr(kw.value)
                    if target:
                        scope.thread_targets.append((target, node.lineno))
    return info


def _file_imports_jax(ctx: FileContext) -> bool:
    cached = getattr(ctx, "_race_imports_jax", None)
    if cached is None:
        cached = False
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                cached = cached or any(
                    a.name == "jax" or a.name.startswith("jax.") for a in node.names
                )
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                cached = cached or mod == "jax" or mod.startswith("jax.")
        ctx._race_imports_jax = cached
    return cached


def _blocking_desc(call: ast.Call, jax_module: bool) -> str:
    """A human-readable description when `call` is a blocking construct,
    else ''. Device-sync patterns only count in modules importing jax —
    `np.asarray` on host arrays is ordinary numpy, not a tunnel ride."""
    f = call.func
    if isinstance(f, ast.Attribute):
        root = base_name(f)
        if f.attr in _SOCKET_BLOCKING and root not in ("subprocess",):
            return f"socket-style .{f.attr}()"
        if root == "subprocess" and f.attr in _SUBPROCESS_BLOCKING:
            return f"subprocess.{f.attr}()"
        if f.attr == "sleep" and root in ("time", None):
            return "time.sleep()"
        if f.attr == "get" and not call.args:
            kwargs = {kw.arg: kw.value for kw in call.keywords}
            block_false = isinstance(
                kwargs.get("block"), ast.Constant
            ) and kwargs["block"].value is False
            # dict.get always has a positional key; a zero-positional
            # .get() is queue-style. **kwargs (arg None) is unknowable —
            # do not guess
            if "timeout" not in kwargs and not block_false and None not in kwargs:
                return "queue-style .get() with no timeout"
        if jax_module:
            if f.attr == "block_until_ready":
                return "device sync .block_until_ready()"
            if f.attr == "item" and not call.args:
                return "device sync .item()"
            if f.attr in ("asarray", "array") and root in ("np", "numpy"):
                return f"device fetch {root}.{f.attr}()"
            if f.attr == "device_get" and root == "jax":
                return "device fetch jax.device_get()"
    elif isinstance(f, ast.Name) and f.id == "sleep":
        return "sleep()"
    return ""


# ---------------------------------------------------------------------------
# the acquisition graph


class _Edge:
    __slots__ = ("src", "dst", "ctx", "line", "detail")

    def __init__(self, src: str, dst: str, ctx: FileContext, line: int, detail: str):
        self.src = src
        self.dst = dst
        self.ctx = ctx
        self.line = line
        self.detail = detail


def _reachable(scope: _Scope, entry: str):
    """The transitive same-scope call closure every interprocedural rule
    walks: yields (name, fn, path) for each DEFINED function reachable
    from `entry`, where `path` is the call chain ending in `name`. One
    traversal, or the rules silently diverge on a future fix (shadowed
    names, following `*_locked` contracts, ...)."""
    seen: set[str] = set()
    stack: list[tuple[str, tuple[str, ...]]] = [(entry, ())]
    while stack:
        name, path = stack.pop()
        if name in seen:
            continue
        seen.add(name)
        fn = scope.functions.get(name)
        if fn is None:
            continue
        path = path + (name,)
        yield name, fn, path
        for callee, _ in fn.calls:
            stack.append((callee, path))


def _closure_acquires(scope: _Scope, entry: str) -> dict[str, tuple[str, ...]]:
    """Locks acquired by `entry` or anything it transitively calls inside
    the scope: lock attr -> call path (for the finding message)."""
    out: dict[str, tuple[str, ...]] = {}
    for _, fn, path in _reachable(scope, entry):
        for attr in fn.acquired_locks():
            out.setdefault(attr, path)
    return out


def _closure_held(scope: _Scope, entry: str) -> dict[str, frozenset[str]]:
    """Locks GUARANTEED held whenever each function in `entry`'s call
    closure runs (entered via `entry`): the meet (intersection) over all
    call paths, where a call made at a line with locks held passes those
    locks down to the callee. This is what lets a write in `_shutdown()`
    keep its guard when the only caller is `with self._lock:
    self._shutdown()` — without it, guarded delegation reads as an
    unguarded write. Standard decreasing-fixpoint dataflow; the call
    graphs here are a handful of methods, so it converges immediately."""
    out: dict[str, frozenset[str]] = {entry: frozenset()}
    work = [entry]
    while work:
        name = work.pop()
        fn = scope.functions.get(name)
        if fn is None:
            continue
        held_here = out[name]
        for callee, line in fn.calls:
            if callee not in scope.functions:
                continue
            ctx = held_here | frozenset(fn.held_at(line))
            prev = out.get(callee)
            new = ctx if prev is None else prev & ctx
            if prev is None or new != prev:
                out[callee] = new
                work.append(callee)
    return out


def _entries_held(
    scope: _Scope, entries: list[str]
) -> dict[str, frozenset[str]]:
    """`_closure_held` met across several entry points: the locks held at
    a function no matter which of `entries` the thread came in through."""
    out: dict[str, frozenset[str]] = {}
    for entry in entries:
        for name, held in _closure_held(scope, entry).items():
            prev = out.get(name)
            out[name] = held if prev is None else prev & held
    return out


def _scope_edges(scope: _Scope) -> list[_Edge]:
    edges: list[_Edge] = []
    for fn in scope.functions.values():
        # nested held spans: B acquired at its span start while A held.
        # Strictly-earlier acquisition lines only: two locks in ONE
        # multi-item `with` share a lineno and are ordered by item
        # position below, not symmetrically here.
        for span in fn.spans:
            attr_b, _, _, acq_line = span
            holders = [
                s[0]
                for s in fn.spans
                if s is not span
                and s[3] < acq_line
                and s[1] <= acq_line <= s[2]
                # acquires in opposite if/else branches never coexist:
                # `if fast: lock.acquire() else: lock.acquire()` is one
                # hold, not a self-deadlock (span hi bleeds to the next
                # release, textually covering the sibling branch)
                and not fn.mutually_exclusive(s[3], acq_line)
            ]
            for attr_a in dict.fromkeys(holders):
                edges.append(
                    _Edge(
                        scope.lock_id(attr_a),
                        scope.lock_id(attr_b),
                        scope.ctx,
                        acq_line,
                        f"{scope.lock_label(attr_b)} acquired at "
                        f"{scope.ctx.relpath}:{acq_line} in {fn.name}() while "
                        f"holding {scope.lock_label(attr_a)}",
                    )
                )
        # multi-item withs acquire in item order even at the same line
        for node in ast.walk(fn.node):
            if isinstance(node, ast.With) and len(node.items) > 1:
                refs = [
                    r
                    for r in (_lock_ref(scope, i.context_expr) for i in node.items)
                    if r and scope.kind_of(r) in _HELD_KINDS
                ]
                for i in range(len(refs) - 1):
                    for j in range(i + 1, len(refs)):
                        edges.append(
                            _Edge(
                                scope.lock_id(refs[i]),
                                scope.lock_id(refs[j]),
                                scope.ctx,
                                node.lineno,
                                f"with {scope.lock_label(refs[i])}, "
                                f"{scope.lock_label(refs[j])}: at "
                                f"{scope.ctx.relpath}:{node.lineno}",
                            )
                        )
        # interprocedural: a call made while holding A inherits the held
        # set — every lock the callee closure acquires becomes an edge
        for callee, line in fn.calls:
            held = fn.held_at(line)
            if not held or callee not in scope.functions:
                continue
            for attr_b, path in _closure_acquires(scope, callee).items():
                for attr_a in held:
                    edges.append(
                        _Edge(
                            scope.lock_id(attr_a),
                            scope.lock_id(attr_b),
                            scope.ctx,
                            line,
                            f"{fn.name}() holds {scope.lock_label(attr_a)} and "
                            f"calls {'() -> '.join(path)}(), which acquires "
                            f"{scope.lock_label(attr_b)}",
                        )
                    )
    return edges


def _find_cycles(
    edges: list[_Edge], kinds: dict[str, str]
) -> list[tuple[list[_Edge], str]]:
    """Cycles in the acquisition graph. Self-loops on a non-reentrant
    lock are reported (a guaranteed deadlock); RLock/Condition self-loops
    are legal re-entry and skipped. Multi-node cycles always count —
    reentrancy does not save an A->B->A inversion. Returns one
    representative edge path per distinct cycle node-set."""
    by_src: dict[str, list[_Edge]] = {}
    for e in edges:
        by_src.setdefault(e.src, []).append(e)
    cycles: list[tuple[list[_Edge], str]] = []
    seen_sets: set[frozenset] = set()

    for e in edges:
        if e.src == e.dst:
            if kinds.get(e.src) in _REENTRANT:
                continue
            key = frozenset((e.src, "self"))
            if key in seen_sets:
                continue
            seen_sets.add(key)
            cycles.append(([e], "self-deadlock"))

    # DFS from each node for a path back to itself (graphs here are tiny:
    # a handful of locks per program)
    nodes = sorted({e.src for e in edges} | {e.dst for e in edges})
    for start in nodes:
        stack: list[tuple[str, list[_Edge]]] = [(start, [])]
        visited: set[str] = set()
        while stack:
            node, path = stack.pop()
            for e in by_src.get(node, ()):
                if e.src == e.dst:
                    continue
                if e.dst == start and path:
                    key = frozenset(x.src for x in path + [e])
                    if key not in seen_sets:
                        seen_sets.add(key)
                        cycles.append((path + [e], "inversion"))
                elif e.dst not in visited and e.dst != start:
                    visited.add(e.dst)
                    stack.append((e.dst, path + [e]))
    return cycles


# ---------------------------------------------------------------------------
# rules


def _check_lock_order(scopes: list[_Scope]) -> list[Finding]:
    edges: list[_Edge] = []
    kinds: dict[str, str] = {}
    for scope in scopes:
        for attr, kind in scope.locks.items():
            kinds[scope.lock_id(attr)] = kind
        edges.extend(_scope_edges(scope))
    findings = []
    for cycle, why in _find_cycles(edges, kinds):
        anchor = min(cycle, key=lambda e: (e.ctx.relpath, e.line))
        if why == "self-deadlock":
            e = cycle[0]
            name = e.src.split("::", 1)[1]
            msg = (
                f"non-reentrant {kinds.get(e.src, 'Lock')} {name} is "
                f"re-acquired on a path that already holds it "
                f"({e.detail}) — guaranteed self-deadlock"
            )
        else:
            order = " -> ".join(
                e.src.split("::", 1)[1] for e in cycle
            ) + " -> " + cycle[0].src.split("::", 1)[1]
            msg = (
                f"lock-order cycle {order} (potential deadlock): "
                + "; ".join(e.detail for e in cycle)
            )
        findings.append(anchor.ctx.finding("race-lock-order", anchor.line, msg))
    return findings


def _check_blocking_hold(scopes: list[_Scope]) -> list[Finding]:
    findings = []
    for scope in scopes:
        for fn in scope.functions.values():
            # blocking calls directly under a held span (or in a *_locked
            # method, where the caller holds a lock by contract)
            for node, desc in fn.blocking:
                held = fn.held_at(node.lineno)
                if held:
                    lock = scope.lock_label(held[0])
                elif fn.locked_by_contract and scope.locks:
                    lock = f"the caller's lock ({fn.name} is *_locked)"
                else:
                    continue
                findings.append(
                    scope.ctx.finding(
                        "race-blocking-hold",
                        node,
                        f"blocking call ({desc}) while holding {lock} — "
                        "every thread contending the lock stalls behind it",
                    )
                )
            # interprocedural: calling into a function whose closure
            # blocks, while holding a lock
            for callee, line in fn.calls:
                held = fn.held_at(line)
                if not held or callee not in scope.functions:
                    continue
                for bnode, bdesc, path in _closure_blocking(scope, callee):
                    findings.append(
                        scope.ctx.finding(
                            "race-blocking-hold",
                            line,
                            f"{fn.name}() holds {scope.lock_label(held[0])} "
                            f"and calls {'() -> '.join(path)}(), which makes "
                            f"a blocking call ({bdesc} at "
                            f"{scope.ctx.relpath}:{bnode.lineno})",
                        )
                    )
    return findings


def _closure_blocking(
    scope: _Scope, entry: str
) -> list[tuple[ast.AST, str, tuple[str, ...]]]:
    out = []
    for _, fn, path in _reachable(scope, entry):
        for node, desc in fn.blocking:
            # blocked-under-own-lock — and blocking inside a *_locked
            # method — is already reported at the definition; only
            # unguarded blocking calls propagate to callers (one defect,
            # one finding)
            if fn.held_at(node.lineno):
                continue
            if fn.locked_by_contract and scope.locks:
                continue
            out.append((node, desc, path))
    return out


def _check_unguarded_shared(scopes: list[_Scope]) -> list[Finding]:
    findings = []
    for scope in scopes:
        if not scope.is_class or not scope.thread_targets:
            continue
        closure: set[str] = set()
        for target, _ in scope.thread_targets:
            closure.update(name for name, _, _ in _reachable(scope, target))
        # the public surface follows the same call closure as the thread
        # side: `stop()` delegating to `_shutdown()` writes shared state
        # from public code just as surely as an inline assignment would
        # (methods in BOTH closures count as thread-side — that is where
        # the write actually races)
        public_closure: set[str] = set()
        for entry in scope.functions:
            if entry.startswith("_") or entry in closure:
                continue
            public_closure.update(
                name for name, _, _ in _reachable(scope, entry)
            )
        # gather writes per attribute on each side (construction in
        # __init__ is exempt: the object is not shared yet). A write's
        # guard set is its function-local held set PLUS whatever its
        # side's entry points guarantee is held on the way in — so
        # `with self._lock: self._shutdown()` keeps `_shutdown`'s writes
        # guarded instead of reading as bare.
        thread_held = _entries_held(
            scope, [target for target, _ in scope.thread_targets]
        )
        public_held = _entries_held(
            scope,
            [
                entry
                for entry in scope.functions
                if not entry.startswith("_") and entry not in closure
            ],
        )
        thread_writes: dict[str, list[tuple[ast.AST, frozenset, str]]] = {}
        public_writes: dict[str, list[tuple[ast.AST, frozenset, str]]] = {}
        for name, fn in scope.functions.items():
            if name == "__init__":
                continue
            if name in closure:
                side, inherited = thread_writes, thread_held.get(name)
            elif name in public_closure:
                side, inherited = public_writes, public_held.get(name)
            else:
                continue
            for attr, node, guards in fn.writes:
                if attr in scope.locks:
                    continue
                side.setdefault(attr, []).append(
                    (node, guards | (inherited or frozenset()), name)
                )
        for attr in sorted(set(thread_writes) & set(public_writes)):
            all_writes = thread_writes[attr] + public_writes[attr]
            common: Optional[frozenset] = None
            for _, guards, _ in all_writes:
                if _ANY_GUARD in guards:
                    continue
                common = guards if common is None else common & guards
            if common is None or common:
                continue  # every write shares at least one lock
            # anchor at an unguarded write when one exists, preferring
            # the thread side (that is the surprising half)
            anchor = next(
                (w for w in thread_writes[attr] if not w[1]),
                next((w for w in all_writes if not w[1]), all_writes[0]),
            )
            node, _, method = anchor
            t_names = sorted({m for _, _, m in thread_writes[attr]})
            p_names = sorted({m for _, _, m in public_writes[attr]})
            findings.append(
                scope.ctx.finding(
                    "race-unguarded-shared",
                    node,
                    f"{scope.name}.{attr} is written from Thread-target "
                    f"code ({', '.join(t_names)}) and from the public surface "
                    f"({', '.join(p_names)}) with no common lock across "
                    f"every write (anchored at the {method}() write) — "
                    "guard both sides with one lock",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# driver


def build_program(
    files: list[str], config: Config
) -> tuple[list[_Scope], dict[str, FileContext], list[str]]:
    """Parse every file into scopes. Unparsable files are reported, never
    silently skipped (the engine's contract)."""
    scopes: list[_Scope] = []
    contexts: dict[str, FileContext] = {}
    errors: list[str] = []
    for path in sorted(files):
        rel = os.path.relpath(path, config.repo_root)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            ctx = FileContext(path, rel, source, config)
        except (OSError, SyntaxError) as e:
            errors.append(f"{rel}: {type(e).__name__}: {e}")
            continue
        contexts[ctx.relpath] = ctx
        module_body = [
            n
            for n in ctx.tree.body
            if not isinstance(n, ast.ClassDef)
        ]
        # module scope first: classes resolve module-level lock names
        # against it, so a method holding a module lock lands on the
        # same graph node as a module function holding it
        mod_scope = _build_scope(ctx, "<module>", module_body, is_class=False)
        scopes.append(mod_scope)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                scopes.append(
                    _build_scope(
                        ctx,
                        node.name,
                        node.body,
                        is_class=True,
                        module_locks=mod_scope.locks,
                    )
                )
    return scopes, contexts, errors


def analyze_program(
    scopes: list[_Scope],
    contexts: dict[str, FileContext],
    rule_ids: Optional[set[str]] = None,
) -> list[Finding]:
    active = set(RACE_RULES) if rule_ids is None else set(rule_ids)
    findings: list[Finding] = []
    if "race-lock-order" in active:
        findings.extend(_check_lock_order(scopes))
    if "race-blocking-hold" in active:
        findings.extend(_check_blocking_hold(scopes))
    if "race-unguarded-shared" in active:
        findings.extend(_check_unguarded_shared(scopes))
    out, seen = [], set()
    for f in findings:
        ctx = contexts.get(f.path)
        key = (f.path, f.line, f.rule, f.message)
        if key in seen or (ctx is not None and ctx.suppressed(f)):
            continue
        seen.add(key)
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


def run_race_analysis(
    repo_root: str,
    baseline_path: Optional[str] = None,
    rule_ids: Optional[set[str]] = None,
) -> dict:
    """The full static race pipeline, mirroring engine.run_analysis:
    whole-program model, rules, baseline. Returns {"findings": [...],
    "all_findings": [...], "stale": [...], "errors": [...],
    "unjustified": [...], "total": int}."""
    config = Config.for_repo(repo_root)
    files = discover_files(repo_root)
    scopes, contexts, errors = build_program(files, config)
    findings = analyze_program(scopes, contexts, rule_ids=rule_ids)
    baseline = Baseline.load(
        baseline_path
        if baseline_path is not None
        else os.path.join(repo_root, DEFAULT_BASELINE)
    )
    fresh, stale = baseline.apply(findings)
    return {
        "findings": fresh,
        "all_findings": findings,
        "stale": stale,
        "errors": errors,
        "unjustified": baseline.unjustified(),
        "total": len(findings),
    }
