"""protorec — the protocol tier's runtime trace recorder.

The conformance half of `graftlint --proto` (analysis/proto.py): thin
hooks inside the real wire/breaker code paths — SolverClient roundtrips
and epoch commits, SolverServer frame recv/send/close and epoch stores,
CircuitBreaker transitions (solver/service.py, solver/hybrid.py) —
append structured events to a process-global recorder while one is
installed. `proto.check_refinement` then verifies the recorded trace is
an accepted behavior of the protocol model: the same acceptors that
judge model-generated traces judge the real code's traces, so a
reverted review fix (a silent drain close, a stranded half-open probe)
fails refinement instead of surviving until the unlucky interleaving.

Off by default, and DESIGNED to be free when off: every hook site is
`if protorec.RECORDER is not None:` — one module-attribute load and an
identity test on the serving hot path (tests/test_proto_analysis.py
pins the disabled cost with a micro-assert; `bench.py --check` runs
recorder-off). tests/conftest.py installs a recorder around every
`faults`-marked test (the racert pattern), so the whole fault-injection
matrix doubles as a refinement check on each tier-1 run.

Like racert, this module is stdlib-only — importing it (or the hooks
importing it from solver code) must never pull in JAX or numpy
(tests/test_static_analysis.py pins the package-level half of that).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

__all__ = ["TraceRecorder", "RECORDER", "install", "uninstall", "active"]


class TraceRecorder:
    """An append-only, thread-safe event log.

    Events are flat dicts; `record` stamps each with a monotonically
    increasing sequence number (`i`) and the recording thread's ident
    (`thread`) — the refinement acceptors in analysis/proto.py match
    per-thread protocol obligations (a claimed half-open probe must be
    resolved by the SAME thread's record_success/record_failure), so
    cross-thread interleaving of unrelated requests can never fake or
    mask a violation.

    Connection identity: sockets are recycled, so `id(conn)` alone can
    alias two streams. `conn_id` hands out dense ids through a live-map
    keyed on `id(conn)`; `conn_closed` pops the entry, so a recycled
    address gets a FRESH id and per-connection event streams stay
    disjoint.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: list[dict[str, Any]] = []
        self._conn_ids: dict[int, int] = {}
        self._next_conn = 0

    def record(self, **event: Any) -> None:
        tid = threading.get_ident()
        with self._lock:
            event["i"] = len(self._events)
            event["thread"] = tid
            self._events.append(event)

    def conn_id(self, conn: Any) -> int:
        key = id(conn)
        with self._lock:
            cid = self._conn_ids.get(key)
            if cid is None:
                cid = self._next_conn
                self._next_conn += 1
                self._conn_ids[key] = cid
            return cid

    def conn_closed(self, conn: Any) -> int:
        """Return the connection's id and retire it (address may be
        recycled by a later socket)."""
        key = id(conn)
        with self._lock:
            cid = self._conn_ids.pop(key, None)
            if cid is None:
                cid = self._next_conn
                self._next_conn += 1
            return cid

    def snapshot(self) -> list[dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events]

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


# The one global the hook sites poll. `None` means disabled — the hooks
# compile down to a LOAD_ATTR + identity test and fall through.
RECORDER: Optional[TraceRecorder] = None


def install() -> TraceRecorder:
    """Install (and return) a fresh global recorder. Idempotent per
    call: a second install replaces the first — each test gets its own
    event log."""
    global RECORDER
    rec = TraceRecorder()
    RECORDER = rec
    return rec


def uninstall() -> None:
    global RECORDER
    RECORDER = None


def active() -> Optional[TraceRecorder]:
    return RECORDER
