"""graftlint — the AST-based invariant analyzer for this codebase.

Mechanically enforces the architecture contracts documented in CLAUDE.md
and the gate comments atop solver/tpu_runs.py: shared FFD comparator
parity, kernel trace purity, int32-overflow guards in the consolidation
sweep, integer milli-unit resources, lock discipline at the service
boundary, `_ktpu_*` cache invalidation on relax mutations, reference
citation hygiene, and pytest marker registration.

Pure stdlib `ast` — importing this package MUST NOT import JAX or numpy
(tests/test_static_analysis.py pins this), so the lint gate runs in
seconds with no device/tunnel involvement.

Usage:
    python -m karpenter_tpu.analysis            # lint package + tests
    python -m karpenter_tpu.analysis --json     # machine-readable
    python -m karpenter_tpu.analysis --changed-only   # pre-commit mode

Rules, suppression syntax (`# graftlint: disable=<rule>`) and the
baseline workflow are documented in docs/static-analysis.md.
"""

from karpenter_tpu.analysis.engine import (
    Baseline,
    Config,
    FileContext,
    Finding,
    Rule,
    all_rules,
    analyze_files,
    discover_files,
    run_analysis,
)

__all__ = [
    "Baseline",
    "Config",
    "FileContext",
    "Finding",
    "Rule",
    "all_rules",
    "analyze_files",
    "discover_files",
    "run_analysis",
]
