"""graftlint — the five-tier invariant analyzer for this codebase.

The AST tier mechanically enforces the source-level architecture
contracts documented in CLAUDE.md and the gate comments atop
solver/tpu_runs.py: shared FFD comparator parity, kernel trace purity,
int32-overflow guards in the consolidation sweep, integer milli-unit
resources, lock discipline at the service boundary, `_ktpu_*` cache
invalidation on relax mutations, reference citation hygiene, pytest
marker registration, and wire-codec enum coverage (every str-enum-typed
api field registered in codec._ENUM_FIELDS).

The IR tier (analysis/ir.py, `--ir`) traces the real solver kernels on
small representative problems and walks the jaxprs: forbidden host
callbacks, 64-bit/weak-type avals, loop-carry byte budgets from the
checked-in kernel_budgets.json (analysis/budgets.py), the
trace-time-static relax contract, and per-solve upload/retrace
accounting.

The race tier checks the concurrency contracts of the solver service
boundary in two halves: a static whole-program lock analysis
(analysis/locks.py, `--race` — acquisition-graph cycles, blocking calls
under locks, unguarded thread-shared writes) and a tsan-lite runtime
witness (analysis/racert.py) that instruments threading's locks under
the fault-injection pytest suite and fails on observed lock-order
inversions.

The SPMD tier (analysis/spmd.py, `--spmd`) compiles the real solver
programs — including the lane-sharded fleet entry on an 8-virtual-device
mesh — and walks the compiled HLO / lowered StableHLO: a collective
census pinned exact (zero everywhere today: GSPMD inserting a collective
on the fleet axis means the lane axis leaked into a cross-device
reduction), per-device HBM ceilings cross-checked against the
aot_manifest.json cost catalog, a buffer-donation census, and the
launch-lock AST rule (sharded dispatches inside `_MESH_DISPATCH_LOCK`
with the result fetch).

The protocol tier (analysis/proto.py, `--proto`) model-checks the
solver wire/epoch/breaker state machines: small executable models of
the SolverClient request lifecycle, the SolverServer handler (admission
gate, drain, epoch store), and the CircuitBreaker, composed over a
fault-capable channel (drop/truncate/duplicate/reorder/kill, mirroring
testing/faults.py), explored by bounded breadth-first search with
canonical-state dedup. Counterexamples are shrunk to the shortest fault
schedule and pinned in tests/proto_corpus/. Its conformance half
(analysis/protorec.py) records real frame/breaker traces — across the
whole `faults`-marked pytest suite and two live scenarios the tier
drives itself — and verifies each trace refines the model.

Importing THIS package MUST NOT import JAX or numpy
(tests/test_static_analysis.py pins this) — the AST gate runs in seconds
with no device/tunnel involvement; only analysis/ir.py and
analysis/spmd.py import JAX, and only when loaded explicitly (the CLI
does so under `--ir`/`--spmd`). The race tier's both halves are
stdlib-only too (tests/test_race_analysis.py pins that), as are the
protocol tier's model and recorder (its live-conformance scenarios
import the solver stack lazily, inside `--proto` runs only).

Usage:
    python -m karpenter_tpu.analysis            # AST: lint package + tests
    python -m karpenter_tpu.analysis --json     # machine-readable
    python -m karpenter_tpu.analysis --changed-only   # pre-commit mode
    python -m karpenter_tpu.analysis --ir       # IR: trace kernels + budgets
    python -m karpenter_tpu.analysis --race     # race tier, static half
    python -m karpenter_tpu.analysis --spmd     # SPMD: compile + census
    python -m karpenter_tpu.analysis --proto    # protocol: model + traces
    python -m karpenter_tpu.analysis --all      # every tier, worst exit code
    python -m karpenter_tpu.analysis --all --jobs 3   # tiers in parallel

Rules, suppression syntax (`# graftlint: disable=<rule>`), the baseline
workflow, and the budget manifest are documented in
docs/static-analysis.md.
"""

from karpenter_tpu.analysis.engine import (
    Baseline,
    Config,
    FileContext,
    Finding,
    Rule,
    all_rules,
    analyze_files,
    discover_files,
    run_analysis,
)

__all__ = [
    "Baseline",
    "Config",
    "FileContext",
    "Finding",
    "Rule",
    "all_rules",
    "analyze_files",
    "discover_files",
    "run_analysis",
]
