"""graftlint — the two-tier invariant analyzer for this codebase.

The AST tier mechanically enforces the source-level architecture
contracts documented in CLAUDE.md and the gate comments atop
solver/tpu_runs.py: shared FFD comparator parity, kernel trace purity,
int32-overflow guards in the consolidation sweep, integer milli-unit
resources, lock discipline at the service boundary, `_ktpu_*` cache
invalidation on relax mutations, reference citation hygiene, and pytest
marker registration.

The IR tier (analysis/ir.py, `--ir`) traces the real solver kernels on
small representative problems and walks the jaxprs: forbidden host
callbacks, 64-bit/weak-type avals, loop-carry byte budgets from the
checked-in kernel_budgets.json (analysis/budgets.py), the
trace-time-static relax contract, and per-solve upload/retrace
accounting.

Importing THIS package MUST NOT import JAX or numpy
(tests/test_static_analysis.py pins this) — the AST gate runs in seconds
with no device/tunnel involvement; only analysis/ir.py imports JAX, and
only when loaded explicitly (the CLI does so under `--ir`).

Usage:
    python -m karpenter_tpu.analysis            # AST: lint package + tests
    python -m karpenter_tpu.analysis --json     # machine-readable
    python -m karpenter_tpu.analysis --changed-only   # pre-commit mode
    python -m karpenter_tpu.analysis --ir       # IR: trace kernels + budgets

Rules, suppression syntax (`# graftlint: disable=<rule>`), the baseline
workflow, and the budget manifest are documented in
docs/static-analysis.md.
"""

from karpenter_tpu.analysis.engine import (
    Baseline,
    Config,
    FileContext,
    Finding,
    Rule,
    all_rules,
    analyze_files,
    discover_files,
    run_analysis,
)

__all__ = [
    "Baseline",
    "Config",
    "FileContext",
    "Finding",
    "Rule",
    "all_rules",
    "analyze_files",
    "discover_files",
    "run_analysis",
]
