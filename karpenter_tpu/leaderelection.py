"""Leader election: the single-writer guarantee for HA deployments.

Reference /root/reference/pkg/operator/operator.go:157-182 configures
controller-runtime leader election over a coordination.k8s.io Lease:
candidates race to write their identity into the lease, the holder renews
within the lease duration, and a stuck holder is deposed when the lease
expires. This module implements the same algorithm over a lease FILE
(JSON record, atomically replaced) guarded by an OS-level advisory lock:

- acquisition: take the flock, read the record, and claim iff the lease
  is empty, expired (renewed_at + lease_duration < now), or already ours;
- renewal: the holder re-writes renewed_at every renew_period; a holder
  that cannot renew before expiry considers itself deposed and stops
  acting (the reference manager exits; here `is_leader` turns False and
  Operator.step() goes standby);
- crash safety: the record survives the process, so a crashed leader is
  replaced after one lease_duration — identical to Lease semantics. The
  flock only serializes record updates; it is NOT held between calls, so
  a wedged process cannot fence out successors.

The clock is injected for testability (controllers/kube.FakeClock works).
"""

from __future__ import annotations

import fcntl
import json
import os
import socket
import time
from typing import Optional


class _WallClock:
    def now(self) -> float:
        return time.time()


_instance_seq = iter(range(1, 1 << 62))


class LeaderElector:
    """One candidate's view of a file-backed lease."""

    def __init__(
        self,
        lease_path: str,
        identity: Optional[str] = None,
        lease_duration: float = 15.0,
        renew_period: float = 5.0,
        clock=None,
    ):
        if renew_period >= lease_duration:
            raise ValueError("renew_period must be < lease_duration")
        self.lease_path = lease_path
        # the default identity carries a per-instance nonce: two electors in
        # ONE process (tests, embedded operators) must not alias each other
        self.identity = identity or (
            f"{socket.gethostname()}-{os.getpid()}-{next(_instance_seq)}"
        )
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.clock = clock or _WallClock()
        self._last_renew: float = -1.0
        self._leading = False

    # -- record IO (caller holds the flock) ------------------------------

    def _read_record(self) -> dict:
        try:
            with open(self.lease_path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return {}

    def _write_record(self, rec: dict) -> None:
        tmp = f"{self.lease_path}.{self.identity}.tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.lease_path)

    def _with_lock(self, fn):
        lock_path = self.lease_path + ".lock"
        with open(lock_path, "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                return fn()
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

    # -- the lease algorithm ---------------------------------------------

    def ensure(self) -> bool:
        """Advance the state machine one tick: acquire if free/expired,
        renew if due, depose ourselves if the record moved on. Returns
        whether this candidate is the leader now. Call from the control
        loop; cheap no-op between renew periods."""
        now = self.clock.now()
        if self._leading and now - self._last_renew < self.renew_period:
            return True

        def step():
            rec = self._read_record()
            holder = rec.get("holder")
            renewed = float(rec.get("renewed_at", 0.0))
            # expiry is judged by the HOLDER's advertised duration (stored
            # in the record) — judging by the challenger's own config would
            # let a short-lease candidate depose a healthy long-lease
            # holder mid-lease and run as a second writer
            holder_duration = float(
                rec.get("lease_duration", self.lease_duration)
            )
            expired = now > renewed + holder_duration
            if holder == self.identity or holder is None or expired:
                self._write_record(
                    {
                        "holder": self.identity,
                        "renewed_at": now,
                        "acquired_at": (
                            rec.get("acquired_at", now)
                            if holder == self.identity
                            else now
                        ),
                        "lease_duration": self.lease_duration,
                    }
                )
                return True
            return False

        got = self._with_lock(step)
        if got:
            self._last_renew = now
        self._leading = got
        return got

    @property
    def is_leader(self) -> bool:
        """Leadership as of the last ensure(); a holder past its own lease
        duration no longer counts itself leader even without a successor
        (the fencing rule that keeps two writers from overlapping)."""
        return (
            self._leading
            and self.clock.now() - self._last_renew <= self.lease_duration
        )

    def release(self) -> None:
        """Voluntary handoff (the reference releases on shutdown so the
        successor needn't wait out the lease)."""
        if not self._leading:
            return

        def step():
            rec = self._read_record()
            if rec.get("holder") == self.identity:
                self._write_record({})

        self._with_lock(step)
        self._leading = False
        self._last_renew = -1.0

    def holder(self) -> Optional[str]:
        """Current holder per the record (observability; may be stale the
        instant it returns)."""
        rec = self._with_lock(self._read_record)
        holder = rec.get("holder")
        if holder is None:
            return None
        if self.clock.now() > float(rec.get("renewed_at", 0.0)) + float(
            rec.get("lease_duration", self.lease_duration)
        ):
            return None  # expired == vacant
        return holder
