"""Shared fault-injection harness for the solver service boundary.

`FaultyProxy` is the programmable UDS man-in-the-middle the fault suite
(tests/test_service_faults.py) has soaked the resilience contract with
since the fault-tolerance PR; the differential chaos fuzzer
(karpenter_tpu/testing/fuzz.py chaos mode) replays seeded fuzz cases
through the same proxy, so both consumers inject byte-level faults
through ONE implementation — a proxy behavior fix or a new fault mode
lands in the fault matrix and the fuzzer at once.
"""

from __future__ import annotations

import socket
import threading
import time


class FaultyProxy:
    """A UDS man-in-the-middle with programmable faults on the
    server->client direction (responses), applied once then reverting to
    pass-through:

    - "pass":      forward both directions untouched
    - "blackhole": swallow client bytes; the server never sees the
                   request, the client never gets a response
    - "truncate":  forward the request; relay only `truncate_after` bytes
                   of the response, then close both sides
    - "corrupt":   forward the request; flip the response's first byte
                   (the frame magic) so framing is unrecoverable
    - "delay":     forward the request; sleep `delay` before relaying the
                   response
    """

    def __init__(self, listen_path: str, target_path: str):
        self.listen_path = listen_path
        self.target_path = target_path
        self.mode = "pass"
        self.once = False
        self.delay = 0.0
        self.truncate_after = 20
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(listen_path)
        self._sock.listen(8)
        self._sock.settimeout(0.1)
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def set_fault(self, mode: str, once: bool = True, **kw) -> None:
        with self._lock:
            self.mode = mode
            self.once = once
            for k, v in kw.items():
                setattr(self, k, v)

    def _take_fault(self) -> str:
        with self._lock:
            mode = self.mode
            if self.once and mode != "pass":
                self.mode = "pass"
            return mode

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self._sock.close()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._relay, args=(client,), daemon=True
            ).start()

    def _relay(self, client: socket.socket) -> None:
        mode = self._take_fault()
        try:
            upstream = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            upstream.connect(self.target_path)
        except OSError:
            client.close()
            return
        try:
            if mode == "blackhole":
                # read and discard until the client gives up
                client.settimeout(0.2)
                while not self._stop.is_set():
                    try:
                        if not client.recv(65536):
                            return
                    except socket.timeout:
                        continue
                    except OSError:
                        return
            # pump client -> server in the background
            up = threading.Thread(
                target=self._pump, args=(client, upstream, "pass", 0), daemon=True
            )
            up.start()
            self._pump(upstream, client, mode, self.truncate_after)
        finally:
            for s in (client, upstream):
                try:
                    s.close()
                except OSError:
                    pass

    def _pump(self, src: socket.socket, dst: socket.socket, mode: str, cut: int) -> None:
        relayed = 0
        first = True
        src.settimeout(0.2)
        while not self._stop.is_set():
            try:
                chunk = src.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            if not chunk:
                return
            if mode == "delay" and first:
                time.sleep(self.delay)
            if mode == "corrupt" and first:
                chunk = bytes([chunk[0] ^ 0xFF]) + chunk[1:]
            if mode == "truncate":
                chunk = chunk[: max(0, cut - relayed)]
                if not chunk:
                    return
            first = False
            relayed += len(chunk)
            try:
                dst.sendall(chunk)
            except OSError:
                return
            if mode == "truncate" and relayed >= cut:
                return
