"""Differential chaos fuzzer over the scheduling surface (ROADMAP item 5).

The reference's correctness contract is its ~46.5k LoC scenario corpus
(SURVEY.md §4); the hand-ported matrices (tests/test_reference_suite.py,
tests/test_topology_matrix.py) cover the scenarios someone thought to
write. This module covers the ones nobody did: seeded, deterministic
property-based generation of cluster states + pod mixes spanning the
full scheduling surface, consumed by three harness modes that share the
SAME case:

- **parity** (`check_parity`): kernel-supported cases must make
  bit-identical decisions on `solver/oracle.py` and `solver/tpu.py` —
  across BOTH kernel paths (the runs result is re-checked through a
  forced scan solve), under relax on AND off (a preference-bearing case
  re-runs both sides with PreferencePolicy=Ignore), and through the
  claim-slot regrow path (an undersized slot pool must be N-invariant).
- **invariants** (`check_invariants`): oracle-independent checks on any
  `Results` from the production `HybridScheduler` path (so mixed
  supported/unsupported cases are exercised too): every pod lands
  exactly once or errors; no capacity overcommit on any surviving
  instance type or existing node; integer milli-units end to end
  (utils/resources.py); taints respected on every placement; host ports
  never double-booked per claim; relax-ladder completeness (a pod whose
  only constraints are preferences never fails while an untainted,
  unlimited template fits it — scheduler.go:434 relaxes all the way).
- **chaos** (`chaos_violations`): the identical case driven through a
  live `SolverServer` under the shared fault proxy
  (karpenter_tpu/testing/faults.py) — wire faults with retries, epoch
  desync storms, mid-solve server kill, admission RETRY — plus a
  fleet-window scenario with sibling lanes; every answer must be
  decision-identical to the in-process referee.

Failures shrink (`shrink`: delta-debug pod drops + per-feature strips,
monotone, bounded) and serialize into the pinned corpus at
tests/fuzz_corpus/*.json (`save_corpus_case`), which
tests/test_fuzz_differential.py replays FIRST on every run — a fuzzer
counterexample becomes a permanent regression scenario. Cases serialize
through the service wire codec (`service.encode_problem_dict` /
`_decode_problem_dict`), so a corpus file is replayable byte-for-byte
through every mode including the sidecar.

Everything here is host-side python: no jax entry points, no IR surface
(the kernels under test are the existing ones).
"""

from __future__ import annotations

import json
import os
import random
from dataclasses import dataclass, field
from typing import Callable, Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    LabelSelector,
    Node,
    NodeSelectorRequirement,
    ObjectMeta,
    Operator,
    PodAffinityTerm,
    PodPhase,
    Taint,
    TaintEffect,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
    WhenUnsatisfiable,
)
from karpenter_tpu.cloudprovider import fake
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.cloudprovider.types import Offering
from karpenter_tpu.scheduling import Requirement, Requirements, Taints
from karpenter_tpu.scheduling.hostports import HostPortUsage, get_host_ports
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.solver.oracle import Results, Scheduler, SchedulerOptions
from karpenter_tpu.solver.topology import ClusterSource, Topology
from karpenter_tpu.testing import fixtures
from karpenter_tpu.utils import resources as res

# every scheduling family the generator can emit; the distribution test
# (tests/test_fuzz_machinery.py) asserts each one actually appears in a
# seeded batch — a silent generator gap would fake coverage
FAMILIES = (
    "generic",
    "gt_lt",
    "zone_in",
    "zone_notin",
    "exists",
    "selector",
    "taints",
    "spread_zone",
    "spread_hostname",
    "schedule_anyway",
    "affinity",
    "anti_affinity",
    "preferences",
    "host_ports",
    "volumes",
    "daemonsets",
    "existing_nodes",
    "bound_pods",
    "limits",
    "weights",
    "min_values",
    "reserved",
    "bucket_edge",
    "tight_slots",
    "ignore_preferences",
)

_KWOK_ZONES = ["test-zone-a", "test-zone-b", "test-zone-c", "test-zone-d"]
_FAKE_ZONES = ["test-zone-1", "test-zone-2", "test-zone-3"]
_CPU_CHOICES = [100, 250, 500, 1000, 1500]
_MEM_CHOICES = [100, 256, 512, 1024, 2048]
_PORT_CHOICES = [80, 443, 8080]


@dataclass
class FuzzCase:
    """One seeded case in its canonical (corpus/wire) form. `problem` is
    a `service.encode_problem_dict` payload; every consumer re-decodes it
    through `service._decode_problem_dict` — the same path a sidecar
    request takes — so parity, invariant, chaos, and corpus replays all
    see byte-identical worlds by construction."""

    seed: int
    families: list[str] = field(default_factory=list)
    problem: dict = field(default_factory=dict)

    def materialize(self):
        """Fresh (pools, its_by_pool, pods, views, daemons, options,
        cluster) — new objects every call, so one case can feed several
        mutating solvers."""
        from karpenter_tpu.solver.service import _decode_problem_dict

        pools, ibp, pods, views, daemons, options, _force, source = (
            _decode_problem_dict(self.problem)
        )
        return pools, ibp, pods, views, daemons, options, source


def encode_case_problem(
    pools, ibp, pods, views, daemons, options, cluster
) -> dict:
    """The canonical problem dict (service wire schema) for a case."""
    from karpenter_tpu.solver.service import encode_problem_dict

    return encode_problem_dict(
        pools,
        ibp,
        pods,
        views,
        daemons,
        options,
        False,
        cluster.namespace_labels if cluster is not None else None,
        cluster,
    )


# ---------------------------------------------------------------------------
# seeded generation


def fuzz_seed_base(default: int = 7000) -> int:
    """The batch base seed; the FUZZ_SEED env var overrides it so a CI
    failure's printed repro command replays the exact batch."""
    raw = os.environ.get("FUZZ_SEED")
    return int(raw) if raw else default


def repro_command(seed: int, mode: str = "parity") -> str:
    """What a human (or the failing test's assertion message) runs to
    replay one case deterministically. Chaos-mode failures live in the
    SERVICE layer, so their repro selects the chaos tests (the parity/
    invariant selector would replay the case in-process and pass green);
    a pinned corpus entry is always replayed exactly by the corpus test
    regardless of mode."""
    sel = "chaos_smoke" if mode.startswith("chaos") else "seeded_smoke"
    return (
        f"FUZZ_SEED={seed} FUZZ_CASES=1 JAX_PLATFORMS=cpu "
        "python -m pytest tests/test_fuzz_differential.py -m fuzz "
        f"-k {sel} -q"
    )


def _group_requests(rng: random.Random) -> dict:
    return {
        "cpu": f"{rng.choice(_CPU_CHOICES)}m",
        "memory": f"{rng.choice(_MEM_CHOICES)}Mi",
    }


def generate_case(seed: int) -> FuzzCase:
    """Deterministic case from one integer seed. Pods are emitted in
    class GROUPS (shared labels/requirements) so the class-dedup encode
    and the bulk/run kernel phases are exercised, with per-group family
    toggles spanning FAMILIES. Names, uids, and timestamps are pinned
    from the seed — the FFD tiebreak sorts on uid, so reproducibility
    requires owning identity end to end."""
    rng = random.Random(seed)
    used: set[str] = set()

    # -- universe ---------------------------------------------------------
    fake_universe = rng.random() < 0.25
    if fake_universe:
        its = fake.default_instance_types()
        zones = list(_FAKE_ZONES)
    else:
        sizes = rng.choice([[2], [2, 8], [2, 4], [4, 16], [2, 8, 32]])
        its = construct_instance_types(sizes=sizes)
        zones = list(_KWOK_ZONES)

    # -- reserved offerings (non-strict rides the kernel) -----------------
    options = SchedulerOptions()
    options.tpu_min_pods = 0  # fuzz always exercises the kernel route
    if rng.random() < 0.08:
        used.add("reserved")
        it0 = its[rng.randrange(len(its))]
        it0.offerings.append(
            Offering(
                requirements=Requirements(
                    [
                        Requirement(
                            well_known.TOPOLOGY_ZONE_LABEL_KEY,
                            Operator.IN,
                            [zones[0]],
                        ),
                        Requirement(
                            well_known.CAPACITY_TYPE_LABEL_KEY,
                            Operator.IN,
                            ["reserved"],
                        ),
                        Requirement(
                            well_known.RESERVATION_ID_LABEL_KEY,
                            Operator.IN,
                            [f"res-{seed % 97}"],
                        ),
                    ]
                ),
                price=0.01,
                available=True,
                reservation_capacity=rng.randint(1, 4),
            )
        )
        options.reserved_capacity_enabled = True
        if rng.random() < 0.2:
            options.reserved_offering_strict = True

    # -- node pools -------------------------------------------------------
    pool_kw: dict = {}
    if rng.random() < 0.2:
        used.add("zone_in")
        pool_kw["requirements"] = [
            NodeSelectorRequirement(
                well_known.TOPOLOGY_ZONE_LABEL_KEY,
                Operator.IN,
                sorted(rng.sample(zones, rng.randint(1, min(2, len(zones))))),
            )
        ]
    if rng.random() < 0.12:
        used.add("limits")
        pool_kw["limits"] = {"cpu": str(rng.choice([8, 16, 30]))}
    if rng.random() < 0.08:
        used.add("min_values")
        pool_kw.setdefault("requirements", []).append(
            NodeSelectorRequirement(
                well_known.INSTANCE_TYPE_LABEL_KEY,
                Operator.EXISTS,
                min_values=rng.randint(2, 6),
            )
        )
        if rng.random() < 0.5:
            options.min_values_best_effort = True
    pools = [fixtures.node_pool(name="default", **pool_kw)]
    taint = None
    if rng.random() < 0.3:
        used.update(("taints", "weights"))
        taint = Taint(
            "fuzz.io/team",
            rng.choice(
                [
                    TaintEffect.NO_SCHEDULE,
                    TaintEffect.NO_EXECUTE,
                    TaintEffect.PREFER_NO_SCHEDULE,
                ]
            ),
            "a",
        )
        pools.append(
            fixtures.node_pool(name="dedicated", weight=10, taints=[taint])
        )
    elif rng.random() < 0.15:
        used.add("weights")
        pools.append(fixtures.node_pool(name="fallback", weight=1))
    ibp = {np_.name: its for np_ in pools}

    # -- existing nodes ---------------------------------------------------
    views: Optional[list[StateNodeView]] = None
    if rng.random() < 0.3:
        used.add("existing_nodes")
        views = []
        for vi in range(rng.randint(1, 3)):
            it = its[rng.randrange(len(its))]
            zone = rng.choice(zones)
            name = f"fz-{seed}-node-{vi}"
            labels = {
                well_known.TOPOLOGY_ZONE_LABEL_KEY: zone,
                well_known.HOSTNAME_LABEL_KEY: name,
                well_known.INSTANCE_TYPE_LABEL_KEY: it.name,
                well_known.CAPACITY_TYPE_LABEL_KEY: "on-demand",
                well_known.OS_LABEL_KEY: "linux",
                well_known.ARCH_LABEL_KEY: "amd64",
                well_known.NODEPOOL_LABEL_KEY: pools[0].name,
            }
            alloc = dict(it.allocatable())
            # leave 25-100% of each resource available (integer math)
            frac = rng.choice([4, 2, 4, 1])
            avail = {k: v // frac if frac > 1 else v for k, v in alloc.items()}
            v = StateNodeView(
                name=name,
                node_labels={well_known.TOPOLOGY_ZONE_LABEL_KEY: zone},
                labels=labels,
                available=avail,
                capacity=dict(it.capacity),
                initialized=rng.random() < 0.9,
            )
            if rng.random() < 0.3:
                used.add("host_ports")
                squatter = fixtures.pod(name=f"fz-{seed}-squat-{vi}")
                squatter.metadata.uid = f"fz-{seed}-squat-{vi}"
                v.host_port_usage.add(squatter, [("0.0.0.0", "TCP", 443)])
            views.append(v)

    # -- daemonsets -------------------------------------------------------
    daemons = None
    if rng.random() < 0.2:
        used.add("daemonsets")
        daemons = []
        for di in range(rng.randint(1, 2)):
            d = fixtures.pod(
                name=f"fz-{seed}-ds-{di}", requests={"cpu": "100m"}
            )
            d.metadata.uid = f"fz-{seed}-ds-{di}"
            if rng.random() < 0.3:
                used.add("host_ports")
                d.host_ports = [("0.0.0.0", "TCP", 10250 + di)]
            daemons.append(d)

    # -- pending pods, in class groups ------------------------------------
    if rng.random() < 0.15:
        used.add("bucket_edge")
        n = rng.choice([15, 16, 17, 31, 32, 33, 63, 64, 65])
    else:
        n = rng.randint(4, 28)
    n_groups = rng.randint(1, min(4, n))
    counts = [n // n_groups] * n_groups
    counts[0] += n - sum(counts)
    pods = []
    pod_i = 0
    for gi, cnt in enumerate(counts):
        group_labels = {"fuzz-group": f"g{gi}", "app": rng.choice("xyz")}
        requests = _group_requests(rng)
        kw: dict = {}
        family = rng.choice(
            [
                "generic",
                "generic",
                "spread_zone",
                "spread_hostname",
                "affinity",
                "anti_affinity",
                "preferences",
                "selector",
                "zone_in",
                "zone_notin",
                "exists",
                "gt_lt",
                "host_ports",
                "volumes",
            ]
        )
        if family == "gt_lt" and not fake_universe:
            family = "zone_in"
        used.add(family)
        if family == "selector":
            sel_zone = rng.choice(zones + ["no-such-zone"])
            kw["node_selector"] = {well_known.TOPOLOGY_ZONE_LABEL_KEY: sel_zone}
        elif family == "zone_in":
            kw["node_requirements"] = [
                NodeSelectorRequirement(
                    well_known.TOPOLOGY_ZONE_LABEL_KEY,
                    Operator.IN,
                    sorted(rng.sample(zones, rng.randint(1, 2))),
                )
            ]
        elif family == "zone_notin":
            kw["node_requirements"] = [
                NodeSelectorRequirement(
                    well_known.TOPOLOGY_ZONE_LABEL_KEY,
                    Operator.NOT_IN,
                    sorted(rng.sample(zones, rng.randint(1, len(zones) - 1))),
                )
            ]
        elif family == "exists":
            kw["node_requirements"] = [
                NodeSelectorRequirement(
                    well_known.TOPOLOGY_ZONE_LABEL_KEY, Operator.EXISTS
                )
            ]
        elif family == "gt_lt":
            kw["node_requirements"] = [
                NodeSelectorRequirement(
                    fake.INTEGER_INSTANCE_LABEL_KEY,
                    rng.choice([Operator.GT, Operator.LT]),
                    [str(rng.choice([2, 4, 8]))],
                )
            ]
        elif family in ("spread_zone", "spread_hostname"):
            anyway = rng.random() < 0.3
            if anyway:
                used.add("schedule_anyway")
            key = (
                well_known.TOPOLOGY_ZONE_LABEL_KEY
                if family == "spread_zone"
                else well_known.HOSTNAME_LABEL_KEY
            )
            kw["topology_spread_constraints"] = [
                TopologySpreadConstraint(
                    max_skew=rng.randint(1, 2),
                    topology_key=key,
                    when_unsatisfiable=(
                        WhenUnsatisfiable.SCHEDULE_ANYWAY
                        if anyway
                        else WhenUnsatisfiable.DO_NOT_SCHEDULE
                    ),
                    label_selector=LabelSelector(
                        match_labels=dict(group_labels)
                    ),
                    min_domains=(
                        rng.randint(2, 3)
                        if family == "spread_zone" and rng.random() < 0.2
                        else None
                    ),
                )
            ]
        elif family == "affinity":
            kw["pod_requirements"] = [
                PodAffinityTerm(
                    topology_key=well_known.TOPOLOGY_ZONE_LABEL_KEY,
                    label_selector=LabelSelector(
                        match_labels=dict(group_labels)
                    ),
                )
            ]
        elif family == "anti_affinity":
            kw["pod_anti_requirements"] = [
                PodAffinityTerm(
                    topology_key=rng.choice(
                        [
                            well_known.HOSTNAME_LABEL_KEY,
                            well_known.TOPOLOGY_ZONE_LABEL_KEY,
                        ]
                    ),
                    label_selector=LabelSelector(
                        match_labels=dict(group_labels)
                    ),
                )
            ]
        elif family == "preferences":
            kw["node_preferences"] = [
                NodeSelectorRequirement(
                    well_known.TOPOLOGY_ZONE_LABEL_KEY,
                    Operator.IN,
                    [rng.choice(zones + ["no-such-zone"])],
                )
            ]
            if rng.random() < 0.5:
                kw["pod_anti_preferences"] = [
                    WeightedPodAffinityTerm(
                        weight=10,
                        term=PodAffinityTerm(
                            topology_key=well_known.TOPOLOGY_ZONE_LABEL_KEY,
                            label_selector=LabelSelector(
                                match_labels=dict(group_labels)
                            ),
                        ),
                    )
                ]
        if taint is not None and rng.random() < 0.6:
            kw["tolerations"] = [
                Toleration(
                    key=taint.key,
                    operator="Equal",
                    value=taint.value,
                    effect=taint.effect,
                )
            ]
        for i in range(cnt):
            p = fixtures.pod(
                name=f"fz-{seed}-p{pod_i}",
                labels=dict(group_labels),
                requests=dict(requests),
                creation_timestamp=float(pod_i),
                **kw,
            )
            p.metadata.uid = f"fz-{seed}-{pod_i:04d}"
            if family == "host_ports" and i % 2 == 0:
                p.host_ports = [
                    (
                        rng.choice(["", "0.0.0.0", "10.1.1.1"]),
                        "TCP",
                        rng.choice(_PORT_CHOICES),
                    )
                ]
            if family == "volumes" and i % 2 == 0:
                p.volume_claims = [f"pvc-{seed}-{gi}"]
            pods.append(p)
            pod_i += 1

    # -- bound cluster pods (existing anti-affinity/spread state) ---------
    cluster = ClusterSource()
    if views and rng.random() < 0.4:
        used.add("bound_pods")
        nodes_by_name = {
            v.name: Node(
                metadata=ObjectMeta(name=v.name, labels=dict(v.labels))
            )
            for v in views
        }
        bound = []
        for bi in range(rng.randint(1, 3)):
            b = fixtures.pod(
                name=f"fz-{seed}-bound-{bi}",
                labels={"fuzz-group": f"g{rng.randrange(n_groups)}"},
                requests={"cpu": "50m"},
            )
            b.metadata.uid = f"fz-{seed}-bound-{bi}"
            b.node_name = views[bi % len(views)].name
            b.phase = PodPhase.RUNNING
            bound.append(b)
        cluster = ClusterSource(
            pods_by_namespace={"default": bound}, nodes_by_name=nodes_by_name
        )

    # -- options tail -----------------------------------------------------
    if rng.random() < 0.1:
        used.add("tight_slots")
        options.claim_slot_div = 10_000  # floor-64 slot pool: regrow path
    if rng.random() < 0.08:
        used.add("ignore_preferences")
        options.ignore_preferences = True
    used.add("generic")

    # identity is part of the case: pool uids ride the wire codec, and a
    # random uid would make the same seed encode two different corpora
    for pi, np_ in enumerate(pools):
        np_.metadata.uid = f"fz-{seed}-pool-{pi}"

    problem = encode_case_problem(
        pools, ibp, pods, views, daemons, options, cluster
    )
    return FuzzCase(seed=seed, families=sorted(used), problem=problem)


# ---------------------------------------------------------------------------
# shared solve plumbing


def results_snapshot(r: Results, pods) -> tuple:
    """The full decision picture two solvers must agree on: the node
    partition with surviving instance types + accumulated requests, the
    existing-node placements, the failed-pod set, and the timeout flag
    (pods compared by NAME — each solve materializes its own objects)."""
    name = {p.uid: p.name for p in pods}
    claims = sorted(
        (
            tuple(sorted(name[p.uid] for p in c.pods)),
            c.template.nodepool_name,
            tuple(sorted(it.name for it in c.instance_type_options)),
            tuple(sorted(c.requests.items())),
        )
        for c in r.new_node_claims
        if c.pods
    )
    existing = sorted(
        (n.view.name, tuple(sorted(name[p.uid] for p in n.pods)))
        for n in r.existing_nodes
        if n.pods
    )
    errors = tuple(sorted(name[u] for u in r.pod_errors))
    return claims, existing, errors, bool(r.timed_out)


def solve_oracle(case: FuzzCase, ignore_preferences=None):
    pools, ibp, pods, views, daemons, options, source = case.materialize()
    if ignore_preferences is not None:
        options.ignore_preferences = ignore_preferences
    topo = Topology(
        pools,
        ibp,
        pods,
        cluster=source,
        state_node_views=views,
        ignore_preferences=options.ignore_preferences,
    )
    s = Scheduler(pools, ibp, topo, views, daemons, options)
    return s.solve(pods), pods


def solve_tpu(
    case: FuzzCase,
    force_scan: bool = False,
    claim_slot_div: Optional[int] = None,
    ignore_preferences=None,
):
    from karpenter_tpu.solver.tpu import TpuScheduler

    pools, ibp, pods, views, daemons, options, source = case.materialize()
    if claim_slot_div is not None:
        options.claim_slot_div = claim_slot_div
    if ignore_preferences is not None:
        options.ignore_preferences = ignore_preferences
    topo = Topology(
        pools,
        ibp,
        pods,
        cluster=source,
        state_node_views=views,
        ignore_preferences=options.ignore_preferences,
    )
    s = TpuScheduler(pools, ibp, topo, views, daemons, options)
    if force_scan:
        s.debug_force_scan = True
    return s.solve(pods), pods, s


def solve_hybrid(case: FuzzCase):
    """The production dispatch (kernel + oracle continuation for
    unsupported pods) — what the invariant mode checks, so mixed cases
    are exercised exactly as a control plane would run them."""
    from karpenter_tpu.solver.hybrid import HybridScheduler

    pools, ibp, pods, views, daemons, options, source = case.materialize()
    topo = Topology(
        pools,
        ibp,
        pods,
        cluster=source,
        state_node_views=views,
        ignore_preferences=options.ignore_preferences,
    )
    h = HybridScheduler(pools, ibp, topo, views, daemons, options)
    return h.solve(pods), pods, h


def kernel_supported(case: FuzzCase) -> bool:
    """Whether the whole case can ride TpuScheduler directly (strict
    parity applies). Mixed/unsupported cases are still covered by the
    invariant and chaos modes through the hybrid dispatch."""
    from karpenter_tpu.solver.tpu_problem import pod_unsupported_reason

    _pools, _ibp, pods, _views, _daemons, options, _src = case.materialize()
    if options.reserved_offering_strict:
        return False  # gated to the oracle before encode (CLAUDE.md)
    return all(
        pod_unsupported_reason(p, options.ignore_preferences) is None
        for p in pods
    )


# ---------------------------------------------------------------------------
# mode (a): differential parity


def check_parity(case: FuzzCase, tight_slots: bool = True) -> list[str]:
    """TPU-vs-oracle bit-parity for kernel-supported cases, across both
    kernel paths, the regrow path (`tight_slots=False` skips that extra
    device solve — the smoke tier samples it every few cases to stay
    inside tier-1's budget), and relax on/off. Returns violation strings
    (empty = clean); an UnsupportedBySolver raise on a supported-looking
    case means a whole-problem encode gate fired — legal by design, but
    the production fallback (HybridScheduler -> pristine oracle) is then
    checked differentially, so a gate that CORRUPTS instead of refusing
    still surfaces."""
    from karpenter_tpu.solver.tpu_problem import UnsupportedBySolver

    if not kernel_supported(case):
        return []
    violations: list[str] = []
    want, pods_o = solve_oracle(case)
    want_snap = results_snapshot(want, pods_o)
    try:
        got, pods_t, sched = solve_tpu(case)
    except UnsupportedBySolver as e:
        # a WHOLE-PROBLEM gate (zone-keyed inverse anti-affinity, all
        # templates filtered out, ...): per-pod taxonomy can't see these,
        # and the production contract is HybridScheduler catching the
        # raise and falling back to a pristine oracle solve. That
        # fallback path is what must stay oracle-identical — check it
        # differentially instead of calling a designed gate a bug (the
        # seed7013 corpus pin replays exactly this shape).
        hr, hpods, _h = solve_hybrid(case)
        if results_snapshot(hr, hpods) != want_snap:
            return [
                f"hybrid fallback diverged after kernel gate ({e}): "
                f"hybrid={results_snapshot(hr, hpods)} oracle={want_snap}"
            ]
        return []
    got_snap = results_snapshot(got, pods_t)
    if got_snap != want_snap:
        violations.append(
            f"parity[{'runs' if sched.last_used_runs else 'scan'}]: "
            f"tpu={got_snap} oracle={want_snap}"
        )
    if sched.last_used_runs:
        scan_got, scan_pods, _ = solve_tpu(case, force_scan=True)
        if results_snapshot(scan_got, scan_pods) != want_snap:
            violations.append(
                f"parity[forced-scan]: "
                f"tpu={results_snapshot(scan_got, scan_pods)} "
                f"oracle={want_snap}"
            )
    # claim-slot regrow N-invariance: an undersized slot pool may only
    # change iteration structure, never decisions
    if tight_slots:
        tight_got, tight_pods, _ = solve_tpu(case, claim_slot_div=10_000)
        if results_snapshot(tight_got, tight_pods) != want_snap:
            violations.append(
                f"parity[tight-slots]: "
                f"tpu={results_snapshot(tight_got, tight_pods)} "
                f"oracle={want_snap}"
            )
    # relax off: PreferencePolicy=Ignore must agree too (the ladder
    # collapses identically on both sides)
    _pools, _ibp, pods, *_rest = case.materialize()
    has_prefs = any(
        (p.node_affinity is not None and p.node_affinity.preferred)
        or p.pod_affinity_preferred
        or p.pod_anti_affinity_preferred
        or any(
            t.when_unsatisfiable == WhenUnsatisfiable.SCHEDULE_ANYWAY
            for t in p.topology_spread_constraints
        )
        for p in pods
    )
    if has_prefs:
        want_ni, pods_ni = solve_oracle(case, ignore_preferences=True)
        try:
            got_ni, pods_tni, _ = solve_tpu(case, ignore_preferences=True)
        except UnsupportedBySolver:
            return violations
        if results_snapshot(got_ni, pods_tni) != results_snapshot(
            want_ni, pods_ni
        ):
            violations.append(
                f"parity[relax-off]: "
                f"tpu={results_snapshot(got_ni, pods_tni)} "
                f"oracle={results_snapshot(want_ni, pods_ni)}"
            )
    return violations


# ---------------------------------------------------------------------------
# mode (b): oracle-independent invariants


def _hard_taints(taints) -> list:
    return [
        t
        for t in taints
        if t.effect in (TaintEffect.NO_SCHEDULE, TaintEffect.NO_EXECUTE)
    ]


def invariant_violations(case: FuzzCase, r: Results, pods) -> list[str]:
    """Checks that must hold for ANY results object, with no oracle in
    the loop (the catalog docs/fuzzing.md documents)."""
    out: list[str] = []
    name = {p.uid: p.name for p in pods}

    # 1. placement accounting: every pod exactly once, or errored
    placed: dict[str, str] = {}
    for c in r.new_node_claims:
        for p in c.pods:
            if p.uid in placed:
                out.append(f"pod {name[p.uid]} placed twice")
            placed[p.uid] = "claim"
    for nd in r.existing_nodes:
        for p in nd.pods:
            if p.uid in placed:
                out.append(f"pod {name[p.uid]} placed twice (existing)")
            placed[p.uid] = "existing"
    for uid in r.pod_errors:
        if uid in placed:
            out.append(f"pod {name.get(uid, uid)} both placed and errored")
    if not r.timed_out:
        for p in pods:
            if p.uid not in placed and p.uid not in r.pod_errors:
                out.append(f"pod {p.name} vanished (neither placed nor errored)")

    # 2. integer milli-units end to end (utils/resources.py contract)
    for c in r.new_node_claims:
        for k, v in c.requests.items():
            if not isinstance(v, int):
                out.append(f"non-integer request {k}={v!r} on a claim")

    # 3. capacity: a claim's accumulated requests (incl. daemon overhead)
    # fit EVERY surviving instance type — that is what the type filter
    # guarantees — and an existing node is never overcommitted beyond its
    # declared availability
    for c in r.new_node_claims:
        if not c.pods:
            continue
        for it in c.instance_type_options:
            if not res.fits(c.requests, it.allocatable()):
                out.append(
                    f"claim {tuple(sorted(name[p.uid] for p in c.pods))} "
                    f"overcommits surviving type {it.name}: "
                    f"{c.requests} vs {it.allocatable()}"
                )
    _pools, _ibp, _pods, views, _daemons, _opts, _src = case.materialize()
    avail_by_name = {v.name: dict(v.available) for v in views or []}
    for nd in r.existing_nodes:
        if not nd.pods:
            continue
        avail = avail_by_name.get(nd.view.name)
        if avail is None:
            continue
        added = res.requests_for_pods(nd.pods)
        added.pop(res.PODS, None)  # views declare pods capacity optionally
        if not res.fits(added, res.merge(avail)):
            out.append(
                f"existing node {nd.view.name} overcommitted: +{added} "
                f"vs available {avail}"
            )

    # 4. taints: every placed pod tolerates its claim's hard taints
    for c in r.new_node_claims:
        hard = _hard_taints(c.template.taints)
        if not hard:
            continue
        for p in c.pods:
            err = Taints(hard).tolerates_pod(p)
            if err is not None:
                out.append(
                    f"pod {name[p.uid]} on tainted pool "
                    f"{c.template.nodepool_name}: {err}"
                )

    # 5. host ports: never double-booked within one claim
    for c in r.new_node_claims:
        usage = HostPortUsage()
        for p in c.pods:
            ports = get_host_ports(p)
            conflict = usage.conflicts(p, ports)
            if conflict is not None:
                out.append(
                    f"host-port clash inside one claim "
                    f"({name[p.uid]}): {conflict}"
                )
            usage.add(p, ports)

    # 6. relax-ladder completeness: a pod whose only constraints are
    # preferences must never fail while an untainted, unlimited template
    # can fit it alone (scheduler.go:434 relaxes ALL the way per attempt)
    pools, _ibp2, _p2, _v2, _d2, opts, _s2 = case.materialize()
    open_pools = [
        np_
        for np_ in pools
        if not _hard_taints(np_.template.taints)
        and not np_.limits
        # a strict minValues floor can legally error an otherwise
        # unconstrained pod once packing drops the type diversity below
        # the floor — such a pool is not "open"
        and not any(
            r_.min_values is not None for r_ in np_.template.requirements
        )
    ]
    if open_pools and not r.timed_out:
        biggest = {}
        for it in _ibp2.get(open_pools[0].name, []):
            biggest = res.max_resources(biggest, it.allocatable())
        by_uid = {p.uid: p for p in pods}
        for uid in r.pod_errors:
            p = by_uid.get(uid)
            if p is None:
                continue
            unconstrained = (
                not p.node_selector
                and (
                    p.node_affinity is None
                    or not p.node_affinity.required_terms
                )
                and not p.pod_affinity
                and not p.pod_anti_affinity
                and not p.host_ports
                and not p.volume_claims
                and not any(
                    t.when_unsatisfiable == WhenUnsatisfiable.DO_NOT_SCHEDULE
                    for t in p.topology_spread_constraints
                )
            )
            if unconstrained and res.fits(
                res.requests_for_pods([p]), biggest
            ):
                out.append(
                    f"preference-only pod {p.name} failed "
                    f"({r.pod_errors[uid]!r}) though an open template "
                    "fits it — the relax ladder did not complete"
                )
    return out


def odometer_violations(h) -> list[str]:
    """Invariant #7 (ISSUE 15, kernel odometers): a TPU-path solve must
    leave a present, self-consistent device-truth counter block. The
    inertness half of the contract is implicit and stronger: the
    odometers are CARRIED on every dispatch the parity/invariant checks
    above judge, so a counter that perturbed any decision (claims,
    placements, errors) would fail those — this catches the counters
    themselves going missing or inconsistent."""
    tpu = getattr(h, "tpu", None)
    if not getattr(h, "used_tpu", False) or tpu is None:
        return []
    odo = getattr(tpu, "last_odometer", None)
    if odo is None:
        return ["tpu-path solve left no kernel odometer"]
    out: list[str] = []
    if odo.get("dispatches", 0) < 1 or odo.get("steps", 0) < 1:
        out.append(f"odometer empty after a tpu-path solve: {odo}")
    if sum(odo.get("tier_hist", [])) != odo.get("tier_steps", 0):
        out.append(f"odometer tier histogram != tier_steps total: {odo}")
    if "claims_opened" in odo:
        if not (0 <= odo["claims_opened"] <= odo.get("claim_slots", 0)):
            out.append(f"odometer claim accounting out of range: {odo}")
        if not (0.0 <= odo.get("claim_occupancy", 0.0) <= 1.0):
            out.append(f"odometer claim occupancy out of [0,1]: {odo}")
    if odo.get("bulk_steps", 0) > odo.get("steps", 0):
        out.append(f"odometer bulk_steps exceeds steps: {odo}")
    return out


def check_invariants(case: FuzzCase) -> list[str]:
    """Invariant mode: solve through the production HybridScheduler and
    run the catalog on whatever came back."""
    r, pods, h = solve_hybrid(case)
    return invariant_violations(case, r, pods) + odometer_violations(h)


# ---------------------------------------------------------------------------
# mode (c): chaos through a live sidecar


def _decoded_parts(got: dict, pods) -> tuple:
    name = {p.uid: p.name for p in pods}
    claims = sorted(
        tuple(sorted(name[u] for u in cl["pod_uids"]))
        for cl in got["new_node_claims"]
        if cl["pod_uids"]
    )
    existing = sorted(
        (node, tuple(sorted(name[u] for u in uids)))
        for node, uids in _group_existing(got).items()
    )
    errors = tuple(sorted(name.get(u, u) for u in got["pod_errors"]))
    return claims, existing, errors, bool(got["timed_out"])


def _group_existing(got: dict) -> dict:
    by_node: dict[str, list] = {}
    for uid, node in got["existing_assignments"].items():
        by_node.setdefault(node, []).append(uid)
    return by_node


def _referee_parts(case: FuzzCase) -> tuple:
    """The in-process oracle referee in the wire's own comparison shape
    (chaos asserts the sidecar never diverges from it)."""
    pools, ibp, pods, views, daemons, options, source = case.materialize()
    topo = Topology(
        pools,
        ibp,
        pods,
        cluster=source,
        state_node_views=views,
        ignore_preferences=options.ignore_preferences,
    )
    s = Scheduler(pools, ibp, topo, views, daemons, options)
    r = s.solve(pods)
    name = {p.uid: p.name for p in pods}
    claims = sorted(
        tuple(sorted(name[p.uid] for p in c.pods))
        for c in r.new_node_claims
        if c.pods
    )
    existing = sorted(
        (n.view.name, tuple(sorted(name[p.uid] for p in n.pods)))
        for n in r.existing_nodes
        if n.pods
    )
    errors = tuple(sorted(name[u] for u in r.pod_errors))
    return claims, existing, errors, bool(r.timed_out)


def chaos_violations(case: FuzzCase, scenario: str, tmp_path: str) -> list[str]:
    """Drive the case through a live SolverServer under `scenario` and
    compare every answer to the in-process oracle referee. Scenarios:

    - "wire":   truncate, corrupt, and delay faults through the shared
                FaultyProxy, with client retries funding recovery;
    - "desync": an epoch-desync storm (the server's store evicted before
                every delta) — one resync hop per solve, identical answers;
    - "kill":   the server dies between solves; its replacement (empty
                epoch store) must answer a full resync identically;
    - "retry":  an admission gate that refuses everything — the
                ResilientSolver must answer from the in-process ladder,
                decision-identically, without tripping the breaker.

    Solves run force_oracle=True (the referee is the oracle; the kernel's
    own parity has its own mode), so chaos isolates the SERVICE layer:
    codec, epochs, admission, transport recovery."""
    from karpenter_tpu.solver.service import SolverClient, SolverServer
    from karpenter_tpu.testing.faults import FaultyProxy

    want = _referee_parts(case)
    out: list[str] = []
    sock = os.path.join(tmp_path, f"fz-{case.seed}-{scenario}.sock")
    server = SolverServer(sock)
    server.start()
    proxy = None
    replacement = None
    try:
        pools, ibp, pods, views, daemons, options, source = case.materialize()

        def solve_once(c):
            got = c.solve(
                pools,
                ibp,
                pods,
                views,
                daemons,
                options,
                True,  # force_oracle: referee-identical by construction
                None,
                timeout=120.0,
                cluster=source,
            )
            return _decoded_parts(got, pods)

        if scenario == "wire":
            proxy_path = os.path.join(tmp_path, f"fz-{case.seed}-px.sock")
            proxy = FaultyProxy(proxy_path, sock)
            for mode, kw in (
                ("truncate", {"truncate_after": 12}),
                ("corrupt", {}),
                ("delay", {"delay": 0.2}),
            ):
                # a FRESH client per round: the proxy fixes its fault
                # mode per-connection at ACCEPT time, so a client kept
                # alive from the previous round's recovery would ride an
                # unfaulted relay and this round's armed fault would
                # never fire
                proxy.set_fault(mode, once=True, **kw)
                c = SolverClient(
                    proxy_path, request_timeout=120.0, max_retries=3
                )
                c.backoff_base = 0.01
                try:
                    try:
                        got = solve_once(c)
                    except Exception:
                        # corrupt poisons the connection (no silent
                        # resync — the resilience contract); the retry
                        # must land
                        got = None
                        try:
                            got = solve_once(c)
                        except Exception as e2:
                            out.append(f"wire[{mode}] never recovered: {e2}")
                            continue
                    if got != want:
                        out.append(f"wire[{mode}] diverged: {got} != {want}")
                finally:
                    c.close()
        elif scenario == "desync":
            c = SolverClient(sock, request_timeout=120.0)
            if solve_once(c) != want:
                out.append("desync[establish] diverged")
            for i in range(3):
                server.epochs.clear()
                if solve_once(c) != want:
                    out.append(f"desync[storm {i}] diverged")
            if c.resyncs != 3:
                out.append(
                    f"desync storm cost {c.resyncs} resyncs (want exactly 3 "
                    "— one hop per solve, never a loop)"
                )
            c.close()
        elif scenario == "kill":
            c = SolverClient(sock, request_timeout=120.0)
            c.backoff_base = 0.01
            if solve_once(c) != want:
                out.append("kill[before] diverged")
            server.stop()
            replacement = SolverServer(sock)
            replacement.start()
            if solve_once(c) != want:
                out.append("kill[replacement resync] diverged")
            if solve_once(c) != want:
                out.append("kill[post-resync delta] diverged")
            c.close()
        elif scenario == "retry":
            from karpenter_tpu.solver import epochs as epochs_mod
            from karpenter_tpu.solver.hybrid import ResilientSolver

            server.admission = epochs_mod.AdmissionGate(max_inflight=0)
            rs = ResilientSolver(sock, request_timeout_seconds=120.0)
            r = rs.solve(
                pools, ibp, pods, views, daemons, options,
                cluster=source, force_oracle=True,
            )
            if rs.last_used == "sidecar":
                out.append("retry: admission gate admitted at max_inflight=0")
            if rs.breaker.state != "closed":
                out.append(
                    f"retry: RETRY frame tripped the breaker "
                    f"({rs.breaker.state}) — backpressure is not a fault"
                )
            name = {p.uid: p.name for p in pods}
            claims = sorted(
                tuple(sorted(name[p.uid] for p in c2.pods))
                for c2 in r.new_node_claims
                if c2.pods
            )
            if claims != want[0]:
                out.append(f"retry diverged: {claims} != {want[0]}")
        else:
            raise ValueError(f"unknown chaos scenario {scenario!r}")
    finally:
        if proxy is not None:
            proxy.stop()
        server.stop()
        if replacement is not None:
            replacement.stop()
    return out


# ---------------------------------------------------------------------------
# shrinking


def case_size(case: FuzzCase) -> int:
    """The shrink objective: pods + views + daemons + pools + per-pod
    feature count. Monotonically non-increasing across shrink steps
    (tests/test_fuzz_machinery.py pins it)."""
    pools, _ibp, pods, views, daemons, _opts, _src = case.materialize()
    n = len(pods) + len(views or []) + len(daemons or []) + len(pools)
    for p in pods:
        n += len(p.topology_spread_constraints)
        n += len(p.pod_affinity) + len(p.pod_anti_affinity)
        n += len(p.pod_affinity_preferred) + len(p.pod_anti_affinity_preferred)
        n += len(p.tolerations) + len(p.host_ports) + len(p.volume_claims)
        n += len(p.node_selector)
        if p.node_affinity is not None:
            n += len(p.node_affinity.required_terms)
            n += len(p.node_affinity.preferred)
    return n


def _rebuild(case: FuzzCase, pools, ibp, pods, views, daemons, options, src):
    return FuzzCase(
        seed=case.seed,
        families=list(case.families),
        problem=encode_case_problem(
            pools, ibp, pods, views, daemons, options, src
        ),
    )


# (label, has(pod), strip(pod)) — strip must be followed by a class-key
# cache drop (solver/ordering.py memoizes _ktpu_* on the pod; a stripped
# copy re-encoding through the stale key would silently keep the feature)
_POD_STRIPS: tuple[tuple[str, Callable, Callable], ...] = (
    (
        "spread",
        lambda p: bool(p.topology_spread_constraints),
        lambda p: p.topology_spread_constraints.clear(),
    ),
    ("affinity", lambda p: bool(p.pod_affinity), lambda p: p.pod_affinity.clear()),
    (
        "anti-affinity",
        lambda p: bool(p.pod_anti_affinity),
        lambda p: p.pod_anti_affinity.clear(),
    ),
    (
        "pref-affinity",
        lambda p: bool(p.pod_affinity_preferred),
        lambda p: p.pod_affinity_preferred.clear(),
    ),
    (
        "pref-anti",
        lambda p: bool(p.pod_anti_affinity_preferred),
        lambda p: p.pod_anti_affinity_preferred.clear(),
    ),
    ("tolerations", lambda p: bool(p.tolerations), lambda p: p.tolerations.clear()),
    ("host-ports", lambda p: bool(p.host_ports), lambda p: p.host_ports.clear()),
    ("volumes", lambda p: bool(p.volume_claims), lambda p: p.volume_claims.clear()),
    ("selector", lambda p: bool(p.node_selector), lambda p: p.node_selector.clear()),
    (
        "node-affinity",
        lambda p: p.node_affinity is not None,
        lambda p: setattr(p, "node_affinity", None),
    ),
)


def _strip(p, strip_fn) -> None:
    from karpenter_tpu.solver.oracle import Preferences

    strip_fn(p)
    Preferences._invalidate_class_caches(p)


def shrink(
    case: FuzzCase,
    failing: Callable[[FuzzCase], bool],
    max_evals: int = 200,
) -> FuzzCase:
    """Greedy structure-dropping shrink: delta-debug chunked pod removal,
    then view/daemon/pool drops, then per-feature strips (all pods at
    once, then pod by pod), repeated to a fixpoint under an evaluation
    budget. `failing` returns True while the original violation still
    reproduces; a predicate ERROR counts as not-reproducing, so the
    shrinker can never wander onto a different bug. The result is always
    <= the input under `case_size` (monotone by construction: only
    accepted, reproducing candidates replace the incumbent)."""
    evals = [0]

    def still_fails(candidate: FuzzCase) -> bool:
        if evals[0] >= max_evals:
            return False
        evals[0] += 1
        try:
            return bool(failing(candidate))
        except Exception:
            return False

    best = case
    improved = True
    while improved and evals[0] < max_evals:
        improved = False
        pools, ibp, pods, views, daemons, options, src = best.materialize()

        # 1. delta-debug the pod list (chunks of n/2, n/4, ... 1)
        chunk = max(1, len(pods) // 2)
        while chunk >= 1 and len(pods) > 1:
            i = 0
            while i < len(pods):
                trial = pods[:i] + pods[i + chunk :]
                cand = _rebuild(
                    best, pools, ibp, trial, views, daemons, options, src
                )
                if still_fails(cand):
                    pods = trial
                    best = cand
                    improved = True
                else:
                    i += chunk
            chunk //= 2

        # 2. drop cluster structure
        for attr in ("views", "daemons"):
            seq = views if attr == "views" else daemons
            if not seq:
                continue
            kept = list(seq)
            i = 0
            while i < len(kept):
                trial = kept[:i] + kept[i + 1 :]
                v2 = trial if attr == "views" else views
                d2 = trial if attr == "daemons" else daemons
                if attr == "views" and not trial:
                    trial = None  # type: ignore[assignment]
                    v2 = None
                cand = _rebuild(best, pools, ibp, pods, v2, d2, options, src)
                if still_fails(cand):
                    kept = list(trial or [])
                    best = cand
                    improved = True
                    if attr == "views":
                        views = v2
                    else:
                        daemons = d2
                else:
                    i += 1
        if len(pools) > 1:
            for drop in list(pools[1:]):
                trial_pools = [np_ for np_ in pools if np_ is not drop]
                trial_ibp = {np_.name: ibp[np_.name] for np_ in trial_pools}
                cand = _rebuild(
                    best, trial_pools, trial_ibp, pods, views, daemons,
                    options, src,
                )
                if still_fails(cand):
                    pools, ibp = trial_pools, trial_ibp
                    best = cand
                    improved = True

        # 3. strip pod features: all pods at once, then one at a time
        import copy as copy_mod

        for _label, has, strip_fn in _POD_STRIPS:
            if not any(has(p) for p in pods):
                continue
            trial = [copy_mod.deepcopy(p) for p in pods]
            for p in trial:
                if has(p):
                    _strip(p, strip_fn)
            cand = _rebuild(
                best, pools, ibp, trial, views, daemons, options, src
            )
            if still_fails(cand):
                pods = trial
                best = cand
                improved = True
            else:
                for i in range(len(pods)):
                    if not has(pods[i]):
                        continue
                    trial = [copy_mod.deepcopy(p) for p in pods]
                    _strip(trial[i], strip_fn)
                    cand = _rebuild(
                        best, pools, ibp, trial, views, daemons, options, src
                    )
                    if still_fails(cand):
                        pods = trial
                        best = cand
                        improved = True
    return best


# ---------------------------------------------------------------------------
# the pinned corpus


CORPUS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "tests",
    "fuzz_corpus",
)


def save_corpus_case(
    case: FuzzCase, mode: str, violation: str, dirpath: Optional[str] = None
) -> str:
    """Serialize a (shrunk) counterexample into the pinned corpus. The
    filename carries the seed so `repro_command` is readable from `ls`."""
    dirpath = dirpath or CORPUS_DIR
    os.makedirs(dirpath, exist_ok=True)
    # chaos modes are "chaos:<scenario>" — keep filenames portable
    path = os.path.join(
        dirpath, f"seed{case.seed}-{mode.replace(':', '-')}.json"
    )
    with open(path, "w") as f:
        json.dump(
            {
                "schema": 1,
                "seed": case.seed,
                "mode": mode,
                "families": case.families,
                "violation": violation,
                "repro": repro_command(case.seed, mode),
                "problem": case.problem,
            },
            f,
            indent=1,
            sort_keys=True,
        )
        f.write("\n")
    return path


def load_corpus(dirpath: Optional[str] = None) -> list[tuple[str, dict]]:
    dirpath = dirpath or CORPUS_DIR
    if not os.path.isdir(dirpath):
        return []
    out = []
    for fn in sorted(os.listdir(dirpath)):
        if fn.endswith(".json"):
            with open(os.path.join(dirpath, fn)) as f:
                out.append((fn, json.load(f)))
    return out


def corpus_case(entry: dict) -> FuzzCase:
    return FuzzCase(
        seed=int(entry["seed"]),
        families=list(entry.get("families", [])),
        problem=entry["problem"],
    )
