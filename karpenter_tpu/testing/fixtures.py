"""Object factories and benchmark workload generators.

The factories mirror the reference's test fixture package
(/root/reference/pkg/test/pods.go et al.); the pod-mix generators replicate the
scheduling benchmark harness exactly — same five pod classes, same discrete
CPU/memory/label-value distributions — so throughput numbers are comparable
with the reference benchmark
(/root/reference/pkg/controllers/provisioning/scheduling/
scheduling_benchmark_test.go:257-453).
"""

from __future__ import annotations

import random
from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    Budget,
    Container,
    Disruption,
    LabelSelector,
    NodeAffinity,
    NodeClaimTemplateSpec,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    Operator,
    Pod,
    PodAffinityTerm,
    PreferredSchedulingTerm,
    NodePool,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
    WhenUnsatisfiable,
)
from karpenter_tpu.utils import resources as res

# Seeded like the reference benchmark (scheduling_benchmark_test.go:62)
_rng = random.Random(42)


def reset_rng(seed: int = 42) -> None:
    global _rng
    _rng = random.Random(seed)


# ---------------------------------------------------------------------------
# factories


def pod(
    name: str = "",
    namespace: str = "default",
    labels: Optional[dict[str, str]] = None,
    requests: Optional[dict[str, str | int]] = None,
    node_selector: Optional[dict[str, str]] = None,
    node_requirements: Optional[list[NodeSelectorRequirement]] = None,
    node_preferences: Optional[list[NodeSelectorRequirement]] = None,
    pod_requirements: Optional[list[PodAffinityTerm]] = None,
    pod_preferences: Optional[list[WeightedPodAffinityTerm]] = None,
    pod_anti_requirements: Optional[list[PodAffinityTerm]] = None,
    pod_anti_preferences: Optional[list[WeightedPodAffinityTerm]] = None,
    topology_spread_constraints: Optional[list[TopologySpreadConstraint]] = None,
    tolerations: Optional[list[Toleration]] = None,
    creation_timestamp: float = 0.0,
    init_containers: Optional[list[Container]] = None,
    overhead: Optional[dict[str, str | int]] = None,
) -> Pod:
    """test.Pod(test.PodOptions{...}) equivalent (reference pkg/test/pods.go).

    `requests` are the MAIN container's requests; when `init_containers`
    or `overhead` are given, the pod's effective requests resolve via the
    Ceiling rule at construction (reference test.UnschedulablePod with
    InitContainers/Overhead options, suite_test.go:1515)."""
    meta = ObjectMeta(
        name=name or f"pod-{ObjectMeta().uid[:8]}",
        namespace=namespace,
        labels=dict(labels or {}),
        creation_timestamp=creation_timestamp,
    )
    node_affinity = None
    if node_requirements or node_preferences:
        node_affinity = NodeAffinity(
            required_terms=(
                [NodeSelectorTerm(list(node_requirements))] if node_requirements else []
            ),
            preferred=(
                [
                    PreferredSchedulingTerm(weight=10, preference=NodeSelectorTerm([p]))
                    for p in node_preferences
                ]
                if node_preferences
                else []
            ),
        )
    parsed_requests = res.parse_list(requests or {})
    containers: list[Container] = []
    if init_containers or overhead:
        # route through the Ceiling path: the main requests become the
        # single app container, Pod.__post_init__ resolves the effective
        # pod-level requests
        containers = [Container(requests=parsed_requests)] if parsed_requests else []
        parsed_requests = {}
    return Pod(
        metadata=meta,
        requests=parsed_requests,
        containers=containers,
        init_containers=list(init_containers or []),
        overhead=res.parse_list(overhead or {}),
        node_selector=dict(node_selector or {}),
        node_affinity=node_affinity,
        pod_affinity=list(pod_requirements or []),
        pod_affinity_preferred=list(pod_preferences or []),
        pod_anti_affinity=list(pod_anti_requirements or []),
        pod_anti_affinity_preferred=list(pod_anti_preferences or []),
        tolerations=list(tolerations or []),
        topology_spread_constraints=list(topology_spread_constraints or []),
    )


def container(
    requests: Optional[dict[str, str | int]] = None,
    limits: Optional[dict[str, str | int]] = None,
    restart_policy: Optional[str] = None,
) -> Container:
    """v1.Container fixture for init-container/sidecar binpacking tests."""
    return Container(
        requests=res.parse_list(requests or {}),
        limits=res.parse_list(limits or {}),
        restart_policy=restart_policy,
    )


def node_pool(
    name: str = "default",
    requirements: Optional[list[NodeSelectorRequirement]] = None,
    labels: Optional[dict[str, str]] = None,
    taints: Optional[list[Taint]] = None,
    startup_taints: Optional[list[Taint]] = None,
    limits: Optional[dict[str, str | int]] = None,
    weight: int = 0,
    consolidate_after_seconds: float = 0.0,
    budgets: Optional[list[Budget]] = None,
    replicas: Optional[int] = None,
) -> NodePool:
    """test.NodePool equivalent: defaults mirror pkg/test/nodepool.go (default
    requirements allow linux + amd64/arm64 + on-demand/spot)."""
    reqs = requirements if requirements is not None else []
    return NodePool(
        metadata=ObjectMeta(name=name),
        template=NodeClaimTemplateSpec(
            requirements=list(reqs),
            labels=dict(labels or {}),
            taints=list(taints or []),
            startup_taints=list(startup_taints or []),
        ),
        disruption=Disruption(
            consolidate_after_seconds=consolidate_after_seconds,
            budgets=budgets if budgets is not None else [Budget(nodes="10%")],
        ),
        limits=res.parse_list(limits or {}),
        weight=weight,
        replicas=replicas,
    )


# ---------------------------------------------------------------------------
# benchmark pod mixes (scheduling_benchmark_test.go:257-453)

_LABEL_VALUES = ["a", "b", "c", "d", "e", "f", "g"]
_MEM_CHOICES = [100, 256, 512, 1024, 2048, 4096]  # Mi
_CPU_CHOICES = [100, 250, 500, 1000, 1500]  # m


def _random_labels() -> dict[str, str]:
    return {"my-label": _rng.choice(_LABEL_VALUES)}


def _random_affinity_labels() -> dict[str, str]:
    return {"my-affininity": _rng.choice(_LABEL_VALUES)}  # [sic] reference typo


def _random_requests() -> dict[str, str]:
    return {
        res.CPU: f"{_rng.choice(_CPU_CHOICES)}m",
        res.MEMORY: f"{_rng.choice(_MEM_CHOICES)}Mi",
    }


def make_generic_pods(count: int) -> list[Pod]:
    return [
        pod(name=f"generic-{i}", labels=_random_labels(), requests=_random_requests())
        for i in range(count)
    ]


def make_topology_spread_pods(count: int, key: str) -> list[Pod]:
    return [
        pod(
            name=f"tsc-{key.rsplit('/', 1)[-1]}-{i}",
            labels=_random_labels(),
            requests=_random_requests(),
            topology_spread_constraints=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=key,
                    when_unsatisfiable=WhenUnsatisfiable.DO_NOT_SCHEDULE,
                    label_selector=LabelSelector(match_labels=_random_labels()),
                )
            ],
        )
        for i in range(count)
    ]


def make_self_spread_pods(count: int, cpu: str = "100m") -> list[Pod]:
    """Self-selecting zone-spread pods: every pod carries a DO_NOT_
    SCHEDULE zone spread whose selector matches its own (shared) labels.
    This is the dynamic-topology shape that forces the exact per-pod
    SCAN path (tpu.py _bulk_class_flags: self-selecting zone-family
    spread counts move mid-run), which is the only path the fleet
    coalescer serves — the ONE fixture behind tests/test_fleet.py,
    the fault suite's fleet lanes, analysis/ir.py's fleet[runtime]
    kit, and bench.py --fleet, so what forces the scan path is defined
    in exactly one place. `cpu` varies the request profile per lane
    WITHOUT touching the requirement classes (keep it a multiple of
    100m: request granularity feeds the resource-table scale, which is
    shared-Tables content the fleet fingerprint correctly refuses to
    stack across)."""
    labels = {"app": "fleet"}
    return [
        pod(
            name=f"sp-{i}",
            labels=dict(labels),
            requests={"cpu": cpu},
            topology_spread_constraints=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=well_known.TOPOLOGY_ZONE_LABEL_KEY,
                    when_unsatisfiable=WhenUnsatisfiable.DO_NOT_SCHEDULE,
                    label_selector=LabelSelector(match_labels=dict(labels)),
                )
            ],
        )
        for i in range(count)
    ]


def make_pod_affinity_pods(count: int, key: str) -> list[Pod]:
    out = []
    for i in range(count):
        # self-affinity, as in the reference (benchmark_test.go:300-327)
        labels = _random_affinity_labels()
        out.append(
            pod(
                name=f"aff-{i}",
                labels=labels,
                requests=_random_requests(),
                pod_requirements=[
                    PodAffinityTerm(
                        topology_key=key,
                        label_selector=LabelSelector(match_labels=dict(labels)),
                    )
                ],
            )
        )
    return out


def make_pod_anti_affinity_pods(count: int, key: str) -> list[Pod]:
    # all of these pods have anti-affinity to each other
    labels = {"app": "nginx"}
    return [
        pod(
            name=f"anti-{i}",
            labels=dict(labels),
            requests=_random_requests(),
            pod_anti_requirements=[
                PodAffinityTerm(
                    topology_key=key,
                    label_selector=LabelSelector(match_labels=dict(labels)),
                )
            ],
        )
        for i in range(count)
    ]


def make_diverse_pods(count: int) -> list[Pod]:
    """makeDiversePods: five equal classes — generic, zonal TSC, hostname TSC,
    zonal self-affinity, hostname anti-affinity — padded with generics."""
    n = count // 5
    pods: list[Pod] = []
    pods += make_generic_pods(n)
    pods += make_topology_spread_pods(n, well_known.TOPOLOGY_ZONE_LABEL_KEY)
    pods += make_topology_spread_pods(n, well_known.HOSTNAME_LABEL_KEY)
    pods += make_pod_affinity_pods(n, well_known.TOPOLOGY_ZONE_LABEL_KEY)
    pods += make_pod_anti_affinity_pods(n, well_known.HOSTNAME_LABEL_KEY)
    pods += make_generic_pods(count - len(pods))
    return pods


def make_preference_pods(count: int) -> list[Pod]:
    """makePreferencePods: one satisfiable node preference + one unsatisfiable
    and one satisfiable pod-anti preference (benchmark_test.go:378-426)."""
    out = []
    for i in range(count):
        out.append(
            pod(
                name=f"pref-{i}",
                labels={"app": "nginx"},
                requests=_random_requests(),
                node_preferences=[
                    NodeSelectorRequirement(
                        well_known.TOPOLOGY_ZONE_LABEL_KEY, Operator.IN, ["test-zone-1"]
                    )
                ],
                pod_anti_preferences=[
                    WeightedPodAffinityTerm(
                        weight=10,
                        term=PodAffinityTerm(
                            topology_key=well_known.TOPOLOGY_ZONE_LABEL_KEY,
                            label_selector=LabelSelector(match_labels={"app": "nginx"}),
                        ),
                    ),
                    WeightedPodAffinityTerm(
                        weight=1,
                        term=PodAffinityTerm(
                            topology_key=well_known.HOSTNAME_LABEL_KEY,
                            label_selector=LabelSelector(match_labels={"app": "nginx"}),
                        ),
                    ),
                ],
            )
        )
    return out


def make_underutilized_fleet(op, n_nodes: int, rider_requests=None, max_ticks=200, seed_requests=None):
    """Provision `n_nodes` one-pod nodes through the real control plane
    (hostname anti-affinity forces one node per seed pod), then swap each
    seed for a small bound RUNNING rider — the classic multi-node
    consolidation setup (an under-utilized fleet a fraction of one big node
    could absorb)."""
    from karpenter_tpu.api import labels as well_known
    from karpenter_tpu.api.objects import PodPhase

    seeds = []
    for i in range(n_nodes):
        p = pod(
            name=f"seed-{i}",
            labels={"fleet": "seed"},
            requests=dict(seed_requests or {"cpu": "700m", "memory": "512Mi"}),
            pod_anti_requirements=[
                PodAffinityTerm(
                    topology_key=well_known.HOSTNAME_LABEL_KEY,
                    label_selector=LabelSelector(match_labels={"fleet": "seed"}),
                )
            ],
        )
        seeds.append(p)
        op.kube.create("Pod", p)
    op.run_until_settled(max_ticks=max_ticks, advance_seconds=2.0)
    nodes = op.kube.list("Node")
    assert len(nodes) >= n_nodes, f"fleet setup made {len(nodes)} nodes"
    # swap seeds for small bound riders (no anti-affinity -> consolidatable)
    for i, p in enumerate(seeds):
        node_name = op.kube.get("Pod", p.name).node_name
        op.kube.delete("Pod", p.name)
        rider = pod(
            name=f"rider-{i}",
            labels={"fleet": "rider"},
            requests=dict(rider_requests or {"cpu": "100m", "memory": "128Mi"}),
        )
        rider.node_name = node_name
        rider.phase = PodPhase.RUNNING
        op.kube.create("Pod", rider)
    return op


def underutilized_operator(
    n_nodes: int,
    *,
    seed: int = 7,
    sizes: Optional[list[int]] = None,
    rider_requests=None,
    seed_requests=None,
    force_oracle: bool = True,
    max_ticks: int = 200,
    options=None,
):
    """The shared consolidation-fleet bootstrap: an Operator with a
    default NodePool (100% disruption budget), an under-utilized fleet
    provisioned through the real control plane, and the
    consolidatable-condition reconcile already run. One copy serves the
    sweep benchmarks (disruption/setsweep.py), the IR runtime budgets
    (analysis/ir.py), and the disruption tests — the multi-step recipe
    must not drift between them."""
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.controllers.operator import Operator as KOperator

    op = KOperator(clock=FakeClock(), force_oracle=force_oracle, options=options)
    if sizes is not None:
        op.raw_cloud.types = construct_instance_types(sizes=sizes)
        op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    reset_rng(seed)
    op.kube.create(
        "NodePool", node_pool(name="default", budgets=[Budget(nodes="100%")])
    )
    make_underutilized_fleet(
        op,
        n_nodes,
        rider_requests=rider_requests,
        max_ticks=max_ticks,
        seed_requests=seed_requests,
    )
    op.clock.advance(30.0)
    op.pod_events.reconcile_all()
    op.claim_conditions.reconcile_all()
    return op
