from karpenter_tpu.testing.fixtures import (
    reset_rng,
    make_diverse_pods,
    make_generic_pods,
    make_pod_affinity_pods,
    make_pod_anti_affinity_pods,
    make_preference_pods,
    make_topology_spread_pods,
    node_pool,
    pod,
)

__all__ = [
    "reset_rng",
    "make_diverse_pods",
    "make_generic_pods",
    "make_pod_affinity_pods",
    "make_pod_anti_affinity_pods",
    "make_preference_pods",
    "make_topology_spread_pods",
    "node_pool",
    "pod",
]
