"""CloudProvider SPI: the pluggable boundary between the control plane and a
cloud (reference /root/reference/pkg/cloudprovider/types.go:72-585).

InstanceType/Offering are the *data* contract the solver consumes: every
scheduling decision reduces to (requirements, offerings, capacity) tensors
built from these objects by karpenter_tpu.ops.encode.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import NodeClaim, NodePool
from karpenter_tpu.scheduling import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    Requirements,
)
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.resources import ResourceList

MAX_FLOAT = float("inf")


# ---------------------------------------------------------------------------
# typed errors (types.go:477-585)


class InsufficientCapacityError(Exception):
    """The cloud cannot fulfill the requested capacity right now."""


class NodeClaimNotFoundError(Exception):
    """The instance backing a NodeClaim no longer exists."""


class NodeClassNotReadyError(Exception):
    """The NodeClass referenced by a NodeClaim isn't ready for launches."""


class CreateError(Exception):
    """Create failed; carries a condition reason for NodeRegistrationHealthy."""

    def __init__(self, message: str, reason: str = "LaunchFailed"):
        super().__init__(message)
        self.reason = reason


# ---------------------------------------------------------------------------
# Offering


@dataclass
class Offering:
    """A sellable variant of an instance type: (zone x capacity-type [x
    reservation]) with a price and availability (types.go:355-405)."""

    requirements: Requirements
    price: float
    available: bool = True
    # remaining capacity for `reserved` offerings
    reservation_capacity: int = 0

    def capacity_type(self) -> str:
        return self.requirements.get(well_known.CAPACITY_TYPE_LABEL_KEY).any_value()

    def zone(self) -> str:
        return self.requirements.get(well_known.TOPOLOGY_ZONE_LABEL_KEY).any_value()

    def reservation_id(self) -> str:
        return self.requirements.get(well_known.RESERVATION_ID_LABEL_KEY).any_value()


class Offerings(list):
    """Decorated list of Offering (types.go:407-475)."""

    def available(self) -> "Offerings":
        return Offerings(o for o in self if o.available)

    def compatible(self, reqs: Requirements) -> "Offerings":
        return Offerings(
            o
            for o in self
            if reqs.is_compatible(o.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)
        )

    def has_compatible(self, reqs: Requirements) -> bool:
        return any(
            reqs.is_compatible(o.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS) for o in self
        )

    def cheapest_launch_price(self, reqs: Requirements) -> float:
        return min(
            (o.price for o in self.compatible(reqs)),
            default=MAX_FLOAT,
        )

    def worst_launch_price(self, reqs: Requirements) -> float:
        """Most expensive compatible offering — the pessimistic launch price
        used by consolidation (types.go WorstLaunchPrice)."""
        return max(
            (o.price for o in self.compatible(reqs)),
            default=MAX_FLOAT,
        )


# ---------------------------------------------------------------------------
# InstanceType


@dataclass
class InstanceTypeOverhead:
    """Resources consumed before pods can use the node (types.go:340-353)."""

    kube_reserved: ResourceList = field(default_factory=dict)
    system_reserved: ResourceList = field(default_factory=dict)
    eviction_threshold: ResourceList = field(default_factory=dict)

    def total(self) -> ResourceList:
        return res.merge(self.kube_reserved, self.system_reserved, self.eviction_threshold)


@dataclass
class InstanceType:
    """name + requirements + offerings + capacity + overhead
    (types.go:105-179)."""

    name: str
    requirements: Requirements
    offerings: Offerings
    capacity: ResourceList
    overhead: InstanceTypeOverhead = field(default_factory=InstanceTypeOverhead)
    _allocatable: Optional[ResourceList] = field(default=None, repr=False, compare=False)

    def allocatable(self) -> ResourceList:
        """capacity - overhead, with hugepage reservations deducted from
        memory (types.go:181-199 precompute); memoized."""
        if self._allocatable is None:
            alloc = res.subtract(self.capacity, self.overhead.total())
            for name, qty in self.capacity.items():
                if name.startswith(res.HUGEPAGES_PREFIX):
                    alloc[res.MEMORY] = max(alloc.get(res.MEMORY, 0) - qty, 0)
            self._allocatable = alloc
        return self._allocatable


class InstanceTypes(list):
    """Decorated list of InstanceType (types.go:221-334)."""

    def order_by_price(self, reqs: Requirements) -> "InstanceTypes":
        """Sort by cheapest available+compatible offering price
        (types.go:221 OrderByPrice). Stable, in-place like the reference."""

        def launch_price(it: InstanceType) -> float:
            return min(
                (
                    o.price
                    for o in it.offerings
                    if o.available
                    and reqs.is_compatible(o.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)
                ),
                default=MAX_FLOAT,
            )

        self.sort(key=launch_price)
        return self

    def compatible(self, reqs: Requirements) -> "InstanceTypes":
        return InstanceTypes(
            it for it in self if it.offerings.available().has_compatible(reqs)
        )

    def satisfies_min_values(
        self, reqs: Requirements
    ) -> tuple[int, dict[str, int], Optional[str]]:
        """Walk the (pre-sorted) list accumulating distinct values per
        min-values key; returns (min needed instance types, unsatisfiable
        keys -> distinct count, error) (types.go:284 SatisfiesMinValues)."""
        if not reqs.has_min_values():
            return 0, {}, None
        incompatible: dict[str, int] = {}
        values_for_key: dict[str, set[str]] = {}
        min_keys = [r.key for r in reqs.values() if r.min_values is not None]
        for i, it in enumerate(self):
            for key in min_keys:
                values_for_key.setdefault(key, set()).update(it.requirements.get(key).values)
            for key, vals in values_for_key.items():
                needed = reqs.get(key).min_values or 0
                if len(vals) < needed:
                    incompatible[key] = len(vals)
                else:
                    incompatible.pop(key, None)
            if not incompatible:
                return i + 1, {}, None
        if incompatible:
            return (
                len(self),
                incompatible,
                f"minValues requirement is not met for label(s) {sorted(incompatible)}",
            )
        return len(self), {}, None

    def truncate(
        self, reqs: Requirements, max_items: int, best_effort_min_values: bool = False
    ) -> tuple["InstanceTypes", Optional[str]]:
        """Order by price and cap at max_items, refusing if that would violate
        minValues (types.go:322 Truncate)."""
        truncated = InstanceTypes(self.order_by_price(reqs)[:max_items])
        if reqs.has_min_values() and not best_effort_min_values:
            _, _, err = truncated.satisfies_min_values(reqs)
            if err is not None:
                return InstanceTypes(self), f"validating minValues, {err}"
        return truncated, None


# ---------------------------------------------------------------------------
# repair policies + SPI


@dataclass
class RepairPolicy:
    """An unhealthy-node condition the provider wants remediated
    (types.go RepairPolicy)."""

    condition_type: str
    condition_status: str = "False"
    toleration_seconds: float = 30 * 60


class CloudProvider(abc.ABC):
    """The provider SPI (types.go:72-100)."""

    @abc.abstractmethod
    def create(self, node_claim: NodeClaim) -> NodeClaim:
        """Launch an instance fulfilling the NodeClaim; returns the claim with
        provider_id/capacity/allocatable status populated."""

    @abc.abstractmethod
    def delete(self, node_claim: NodeClaim) -> None:
        """Terminate the backing instance; NodeClaimNotFoundError if gone."""

    @abc.abstractmethod
    def get(self, provider_id: str) -> NodeClaim:
        """Fetch the claim-shaped view of a live instance."""

    @abc.abstractmethod
    def list(self) -> list[NodeClaim]:
        """All live instances owned by this provider."""

    @abc.abstractmethod
    def get_instance_types(self, node_pool: NodePool) -> InstanceTypes:
        """Instance types launchable for the given NodePool."""

    @abc.abstractmethod
    def is_drifted(self, node_claim: NodeClaim) -> str:
        """Non-empty drift reason if the instance no longer matches its spec."""

    def repair_policies(self) -> list[RepairPolicy]:
        return []

    @abc.abstractmethod
    def name(self) -> str: ...


# A decorator provider that records SPI call latency/counts lives in
# karpenter_tpu.cloudprovider.metrics (reference pkg/cloudprovider/metrics).
