"""KWOK-style simulated cloud: a generated instance-type universe and (in
karpenter_tpu.controllers) a provider that fabricates Node objects directly —
no kubelet, no cloud API — so the full provision->schedule->consolidate loop
runs self-contained (reference /root/reference/kwok/ and
designs/kwok-provider.md).

Universe: 12 sizes x 3 families x 2 OS x 2 arch = 288 instance types, each
offered in 4 zones x {spot, on-demand} (kwok/tools/gen_instance_types.go:70-110).
Pricing: base = vCPU*0.025 + GiB*0.001, spot = 0.7x (designs/kwok-provider.md:44-56).
"""

from __future__ import annotations

import itertools
from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import Operator
from karpenter_tpu.cloudprovider.types import (
    InstanceType,
    InstanceTypeOverhead,
    InstanceTypes,
    Offering,
    Offerings,
)
from karpenter_tpu.scheduling import Requirement, Requirements
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.quantity import parse as q

KWOK_GROUP = "karpenter.kwok.sh"
INSTANCE_SIZE_LABEL_KEY = f"{KWOK_GROUP}/instance-size"
INSTANCE_FAMILY_LABEL_KEY = f"{KWOK_GROUP}/instance-family"
INSTANCE_MEMORY_LABEL_KEY = f"{KWOK_GROUP}/instance-memory"
INSTANCE_CPU_LABEL_KEY = f"{KWOK_GROUP}/instance-cpu"

well_known.WELL_KNOWN_LABELS.update(
    {
        INSTANCE_SIZE_LABEL_KEY,
        INSTANCE_FAMILY_LABEL_KEY,
        INSTANCE_MEMORY_LABEL_KEY,
        INSTANCE_CPU_LABEL_KEY,
    }
)

KWOK_ZONES = ["test-zone-a", "test-zone-b", "test-zone-c", "test-zone-d"]
KWOK_SIZES = [1, 2, 4, 8, 16, 32, 48, 64, 96, 128, 192, 256]
# family -> GiB per vCPU (designs/kwok-provider.md:19-23)
KWOK_FAMILIES = {"c": 2, "s": 4, "m": 8}

# The partition label KWOK nodes are spread over (kwok provider adds
# kwok-partition labels for simulated topology).
PARTITION_LABEL_KEY = f"{KWOK_GROUP}/partition"


def price_from_resources(resources: res.ResourceList) -> float:
    """kwok/tools/gen_instance_types.go:54 priceFromResources."""
    price = 0.0
    for name, millis in resources.items():
        if name == res.CPU:
            price += 0.025 * millis / 1000
        elif name == res.MEMORY:
            price += 0.001 * (millis / 1000) / 1e9
    return price


def construct_instance_types(
    zones: Optional[list[str]] = None,
    sizes: Optional[list[int]] = None,
    families: Optional[dict[str, int]] = None,
    oses: tuple[str, ...] = ("linux", "windows"),
    arches: tuple[str, ...] = ("amd64", "arm64"),
) -> InstanceTypes:
    """The KWOK instance universe (kwok/tools/gen_instance_types.go:69-110 +
    kwok/cloudprovider/helpers.go:120-200 newInstanceType)."""
    zones = zones if zones is not None else KWOK_ZONES
    sizes = sizes if sizes is not None else KWOK_SIZES
    families = families if families is not None else KWOK_FAMILIES
    out = InstanceTypes()
    for cpu, (family, mem_factor), os_, arch in itertools.product(
        sizes, families.items(), oses, arches
    ):
        mem = cpu * mem_factor
        pods = min(cpu * 16, 1024)
        name = f"{family}-{cpu}x-{arch}-{os_}"
        resources = {
            res.CPU: q(str(cpu)),
            res.MEMORY: q(f"{mem}Gi"),
            res.PODS: q(str(pods)),
            res.EPHEMERAL_STORAGE: q("20Gi"),
        }
        price = price_from_resources(resources)
        offerings = Offerings(
            Offering(
                requirements=Requirements.from_labels(
                    {
                        well_known.CAPACITY_TYPE_LABEL_KEY: ct,
                        well_known.TOPOLOGY_ZONE_LABEL_KEY: zone,
                    }
                ),
                price=price * 0.7 if ct == "spot" else price,
                available=True,
            )
            for zone in zones
            for ct in ("spot", "on-demand")
        )
        requirements = Requirements(
            [
                Requirement(well_known.INSTANCE_TYPE_LABEL_KEY, Operator.IN, [name]),
                Requirement(well_known.ARCH_LABEL_KEY, Operator.IN, [arch]),
                Requirement(well_known.OS_LABEL_KEY, Operator.IN, [os_]),
                Requirement(well_known.TOPOLOGY_ZONE_LABEL_KEY, Operator.IN, zones),
                Requirement(
                    well_known.CAPACITY_TYPE_LABEL_KEY, Operator.IN, ["spot", "on-demand"]
                ),
                Requirement(INSTANCE_SIZE_LABEL_KEY, Operator.IN, [f"{cpu}x"]),
                Requirement(INSTANCE_FAMILY_LABEL_KEY, Operator.IN, [family]),
                Requirement(INSTANCE_CPU_LABEL_KEY, Operator.IN, [str(cpu)]),
                Requirement(INSTANCE_MEMORY_LABEL_KEY, Operator.IN, [str(mem * 1024)]),
            ]
        )
        out.append(
            InstanceType(
                name=name,
                requirements=requirements,
                offerings=offerings,
                capacity=resources,
                overhead=InstanceTypeOverhead(
                    kube_reserved=res.parse_list({res.CPU: "100m", res.MEMORY: "120Mi"})
                ),
            )
        )
    return out


# ---------------------------------------------------------------------------
# The KWOK cloud provider: fabricates Node objects directly (no kubelet, no
# cloud API), with an async registration delay — reference
# kwok/cloudprovider/cloudprovider.go:58-86 (Create), :185-236 (toNode).


class KwokCloudProvider:
    """CloudProvider whose instances are simulated Nodes in the API store.

    Create() records the instance immediately and queues the Node object to
    appear after `registration_delay` seconds (the reference launches a
    goroutine sleeping NodeRegistrationDelay; with a step clock the queue is
    flushed by reconcile(), which the operator loop and tests drive)."""

    def __init__(
        self,
        kube,
        clock,
        instance_types=None,
        registration_delay_seconds: float = 2.0,
    ):
        from karpenter_tpu.cloudprovider.types import CloudProvider  # noqa: F401

        self.kube = kube
        self.clock = clock
        self.types = (
            instance_types if instance_types is not None else construct_instance_types()
        )
        self._by_name = {it.name: it for it in self.types}
        self.registration_delay = registration_delay_seconds
        self.instances: dict[str, object] = {}  # provider id -> NodeClaim view
        self._pending_nodes: list[tuple[float, object]] = []
        # boot-taint clearing state (reconcile): claim names whose startup
        # taints still need their one-shot removal, and node names already
        # cleared (pruned when the instance is deleted)
        self._boot_pending: set[str] = set()
        self._boot_cleared: set[str] = set()
        self.next_create_error: Optional[Exception] = None
        self.created: list[object] = []
        self.deleted: list[str] = []

    # -- SPI --------------------------------------------------------------

    def create(self, node_claim):
        """Pick the cheapest compatible offering and fabricate the node
        (kwok cloudprovider.go:58,198)."""
        import copy as copy_mod

        from karpenter_tpu.api import labels as wk
        from karpenter_tpu.api.objects import Node, ObjectMeta, Taint
        from karpenter_tpu.cloudprovider.types import CreateError
        from karpenter_tpu.scheduling import Requirements as Reqs_

        if self.next_create_error is not None:
            err, self.next_create_error = self.next_create_error, None
            raise err

        from karpenter_tpu.scheduling import ALLOW_UNDEFINED_WELL_KNOWN_LABELS

        reqs = Reqs_.from_node_selector_requirements(node_claim.requirements)
        best = None  # (price, it, offering)
        for it in self.types:
            if not reqs.is_compatible(
                it.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
            ):
                continue
            for o in it.offerings:
                if not o.available:
                    continue
                if not reqs.is_compatible(
                    o.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
                ):
                    continue
                if best is None or o.price < best[0]:
                    best = (o.price, it, o)
        if best is None:
            raise CreateError(
                "no instance type offering satisfies the claim requirements",
                reason="NoCompatibleOffering",
            )
        _, it, offering = best

        claim = copy_mod.deepcopy(node_claim)
        provider_id = f"kwok://{claim.name}"
        claim.status.provider_id = provider_id
        claim.status.node_name = claim.name
        claim.status.capacity = dict(it.capacity)
        claim.status.allocatable = dict(it.allocatable())
        claim.status.image_id = "kwok-image"
        self.instances[provider_id] = claim
        self.created.append(claim)

        labels = dict(claim.metadata.labels)
        for r in claim.requirements:
            if r.operator == Operator.IN and len(r.values) == 1:
                labels.setdefault(r.key, r.values[0])
        for r in it.requirements.values():
            vals = r.values
            if not r.complement and len(vals) == 1:
                labels[r.key] = next(iter(vals))
        labels[wk.INSTANCE_TYPE_LABEL_KEY] = it.name
        labels[wk.TOPOLOGY_ZONE_LABEL_KEY] = offering.zone()
        labels[wk.CAPACITY_TYPE_LABEL_KEY] = offering.capacity_type()
        labels[wk.HOSTNAME_LABEL_KEY] = claim.name
        labels[PARTITION_LABEL_KEY] = offering.zone()
        # the returned claim carries the resolved labels like the reference
        # kwok provider's toNodeClaim(node) (kwok cloudprovider.go:84) —
        # lifecycle's PopulateNodeClaimDetails merges them onto the stored
        # claim, which RequirementsDrifted later diffs against the nodepool
        claim.metadata.labels = dict(labels)

        node = Node(
            metadata=ObjectMeta(
                name=claim.name,
                labels=labels,
                finalizers=[wk.TERMINATION_FINALIZER],
                owner_uid=claim.metadata.uid,
            ),
            provider_id=provider_id,
            capacity=dict(it.capacity),
            allocatable=dict(it.allocatable()),
            taints=list(claim.taints)
            + list(claim.startup_taints)
            + [Taint(key="karpenter.sh/unregistered", effect="NoExecute")],
            ready=True,
        )
        self._pending_nodes.append(
            (self.clock.now() + self.registration_delay, node)
        )
        if claim.startup_taints:
            self._boot_pending.add(claim.name)
        return claim

    def reconcile(self) -> int:
        """Flush nodes whose registration delay elapsed into the store,
        and clear each node's STARTUP taints exactly once after it joins —
        the fabricated analog of the boot daemonset that tolerates and
        then removes them (nodepool.go:190 startupTaints "expected to be
        removed automatically within a short period of time"). One-shot:
        a startup-keyed taint applied LATER sticks, so initialized-node
        scenarios keep reference semantics (suite_test.go:2145).
        Returns how many nodes joined."""
        from karpenter_tpu.controllers.kube import AlreadyExists, Conflict, NotFound

        now = self.clock.now()
        due = [n for t, n in self._pending_nodes if t <= now]
        self._pending_nodes = [(t, n) for t, n in self._pending_nodes if t > now]
        joined = 0
        for node in due:
            if node.provider_id not in self.instances:
                continue  # deleted before it registered
            try:
                self.kube.create("Node", node)
                joined += 1
            except AlreadyExists:
                pass
        # boot-taint clearing pass — only while some boot is pending, so
        # the common zero-startup-taint path pays nothing per tick
        if self._boot_pending:
            for claim in self.kube.list("NodeClaim"):
                if not claim.startup_taints or not claim.status.node_name:
                    continue
                name = claim.status.node_name
                if name in self._boot_cleared:
                    continue
                node = self.kube.try_get("Node", name)
                if node is None:
                    continue
                self._boot_cleared.add(name)
                self._boot_pending.discard(claim.name)
                boot = {(t.key, t.effect) for t in claim.startup_taints}
                kept = [t for t in node.taints if (t.key, t.effect) not in boot]
                if len(kept) != len(node.taints):
                    node.taints = kept
                    try:
                        self.kube.update("Node", node)
                    except (Conflict, NotFound):
                        # retry next tick
                        self._boot_cleared.discard(name)
                        self._boot_pending.add(claim.name)
        return joined

    def delete(self, node_claim) -> None:
        from karpenter_tpu.cloudprovider.types import NodeClaimNotFoundError
        from karpenter_tpu.controllers.kube import NotFound

        pid = node_claim.status.provider_id or f"kwok://{node_claim.name}"
        if pid not in self.instances:
            raise NodeClaimNotFoundError(pid)
        del self.instances[pid]
        self.deleted.append(pid)
        self._boot_pending.discard(node_claim.name)
        self._boot_cleared.discard(node_claim.status.node_name or node_claim.name)

    def get(self, provider_id: str):
        from karpenter_tpu.cloudprovider.types import NodeClaimNotFoundError

        claim = self.instances.get(provider_id)
        if claim is None:
            raise NodeClaimNotFoundError(provider_id)
        return claim

    def list(self):
        return list(self.instances.values())

    def get_instance_types(self, node_pool):
        return self.types

    def get_instance_types_by_name(self, node_claim):
        from karpenter_tpu.cloudprovider.types import InstanceTypes as ITs

        return ITs(
            it
            for r in node_claim.requirements
            if r.key == well_known.INSTANCE_TYPE_LABEL_KEY
            for name in r.values
            for it in [self._by_name.get(name)]
            if it is not None
        )

    def is_drifted(self, node_claim) -> str:
        return ""  # hash-based drift is detected by the drift controller

    def repair_policies(self):
        from karpenter_tpu.cloudprovider.types import RepairPolicy

        return [
            RepairPolicy(
                condition_type="Ready",
                condition_status="False",
                toleration_seconds=120.0,
            )
        ]

    def name(self) -> str:
        return "kwok"
