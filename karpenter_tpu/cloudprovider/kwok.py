"""KWOK-style simulated cloud: a generated instance-type universe and (in
karpenter_tpu.controllers) a provider that fabricates Node objects directly —
no kubelet, no cloud API — so the full provision->schedule->consolidate loop
runs self-contained (reference /root/reference/kwok/ and
designs/kwok-provider.md).

Universe: 12 sizes x 3 families x 2 OS x 2 arch = 288 instance types, each
offered in 4 zones x {spot, on-demand} (kwok/tools/gen_instance_types.go:70-110).
Pricing: base = vCPU*0.025 + GiB*0.001, spot = 0.7x (designs/kwok-provider.md:44-56).
"""

from __future__ import annotations

import itertools
from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import Operator
from karpenter_tpu.cloudprovider.types import (
    InstanceType,
    InstanceTypeOverhead,
    InstanceTypes,
    Offering,
    Offerings,
)
from karpenter_tpu.scheduling import Requirement, Requirements
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.quantity import parse as q

KWOK_GROUP = "karpenter.kwok.sh"
INSTANCE_SIZE_LABEL_KEY = f"{KWOK_GROUP}/instance-size"
INSTANCE_FAMILY_LABEL_KEY = f"{KWOK_GROUP}/instance-family"
INSTANCE_MEMORY_LABEL_KEY = f"{KWOK_GROUP}/instance-memory"
INSTANCE_CPU_LABEL_KEY = f"{KWOK_GROUP}/instance-cpu"

well_known.WELL_KNOWN_LABELS.update(
    {
        INSTANCE_SIZE_LABEL_KEY,
        INSTANCE_FAMILY_LABEL_KEY,
        INSTANCE_MEMORY_LABEL_KEY,
        INSTANCE_CPU_LABEL_KEY,
    }
)

KWOK_ZONES = ["test-zone-a", "test-zone-b", "test-zone-c", "test-zone-d"]
KWOK_SIZES = [1, 2, 4, 8, 16, 32, 48, 64, 96, 128, 192, 256]
# family -> GiB per vCPU (designs/kwok-provider.md:19-23)
KWOK_FAMILIES = {"c": 2, "s": 4, "m": 8}

# The partition label KWOK nodes are spread over (kwok provider adds
# kwok-partition labels for simulated topology).
PARTITION_LABEL_KEY = f"{KWOK_GROUP}/partition"


def price_from_resources(resources: res.ResourceList) -> float:
    """kwok/tools/gen_instance_types.go:54 priceFromResources."""
    price = 0.0
    for name, millis in resources.items():
        if name == res.CPU:
            price += 0.025 * millis / 1000
        elif name == res.MEMORY:
            price += 0.001 * (millis / 1000) / 1e9
    return price


def construct_instance_types(
    zones: Optional[list[str]] = None,
    sizes: Optional[list[int]] = None,
    families: Optional[dict[str, int]] = None,
    oses: tuple[str, ...] = ("linux", "windows"),
    arches: tuple[str, ...] = ("amd64", "arm64"),
) -> InstanceTypes:
    """The KWOK instance universe (kwok/tools/gen_instance_types.go:69-110 +
    kwok/cloudprovider/helpers.go:120-200 newInstanceType)."""
    zones = zones if zones is not None else KWOK_ZONES
    sizes = sizes if sizes is not None else KWOK_SIZES
    families = families if families is not None else KWOK_FAMILIES
    out = InstanceTypes()
    for cpu, (family, mem_factor), os_, arch in itertools.product(
        sizes, families.items(), oses, arches
    ):
        mem = cpu * mem_factor
        pods = min(cpu * 16, 1024)
        name = f"{family}-{cpu}x-{arch}-{os_}"
        resources = {
            res.CPU: q(str(cpu)),
            res.MEMORY: q(f"{mem}Gi"),
            res.PODS: q(str(pods)),
            res.EPHEMERAL_STORAGE: q("20Gi"),
        }
        price = price_from_resources(resources)
        offerings = Offerings(
            Offering(
                requirements=Requirements.from_labels(
                    {
                        well_known.CAPACITY_TYPE_LABEL_KEY: ct,
                        well_known.TOPOLOGY_ZONE_LABEL_KEY: zone,
                    }
                ),
                price=price * 0.7 if ct == "spot" else price,
                available=True,
            )
            for zone in zones
            for ct in ("spot", "on-demand")
        )
        requirements = Requirements(
            [
                Requirement(well_known.INSTANCE_TYPE_LABEL_KEY, Operator.IN, [name]),
                Requirement(well_known.ARCH_LABEL_KEY, Operator.IN, [arch]),
                Requirement(well_known.OS_LABEL_KEY, Operator.IN, [os_]),
                Requirement(well_known.TOPOLOGY_ZONE_LABEL_KEY, Operator.IN, zones),
                Requirement(
                    well_known.CAPACITY_TYPE_LABEL_KEY, Operator.IN, ["spot", "on-demand"]
                ),
                Requirement(INSTANCE_SIZE_LABEL_KEY, Operator.IN, [f"{cpu}x"]),
                Requirement(INSTANCE_FAMILY_LABEL_KEY, Operator.IN, [family]),
                Requirement(INSTANCE_CPU_LABEL_KEY, Operator.IN, [str(cpu)]),
                Requirement(INSTANCE_MEMORY_LABEL_KEY, Operator.IN, [str(mem * 1024)]),
            ]
        )
        out.append(
            InstanceType(
                name=name,
                requirements=requirements,
                offerings=offerings,
                capacity=resources,
                overhead=InstanceTypeOverhead(
                    kube_reserved=res.parse_list({res.CPU: "100m", res.MEMORY: "120Mi"})
                ),
            )
        )
    return out
