"""In-memory fake cloud provider + instance-type factories for tests and
benchmarks (reference /root/reference/pkg/cloudprovider/fake/{cloudprovider,
instancetype}.go).

The `instance_types(n)` factory replicates the reference's fake.InstanceTypes
exactly — n types with incrementing resources (i+1 vCPU, 2(i+1) Gi, 10(i+1)
pods), five offerings each across 3 zones x {spot, on-demand} — because the
reference's scheduling benchmark (scheduling_benchmark_test.go:229) is defined
against that universe and our BASELINE comparisons must share it.
"""

from __future__ import annotations

import copy
import itertools
import threading
from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    NodeClaim,
    NodeClaimStatus,
    NodePool,
    Operator,
)
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    InstanceTypeOverhead,
    InstanceTypes,
    NodeClaimNotFoundError,
    Offering,
    Offerings,
)
from karpenter_tpu.scheduling import Requirement, Requirements
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.quantity import parse as q

# Fake well-known labels (reference fake/instancetype.go:33-47)
LABEL_INSTANCE_SIZE = "size"
EXOTIC_INSTANCE_LABEL_KEY = "special"
INTEGER_INSTANCE_LABEL_KEY = "integer"
RESOURCE_GPU_VENDOR_A = "fake.com/vendor-a"
RESOURCE_GPU_VENDOR_B = "fake.com/vendor-b"

well_known.WELL_KNOWN_LABELS.update(
    {LABEL_INSTANCE_SIZE, EXOTIC_INSTANCE_LABEL_KEY, INTEGER_INSTANCE_LABEL_KEY}
)


def price_from_resources(resources: res.ResourceList) -> float:
    """fake/instancetype.go:223 PriceFromResources."""
    price = 0.0
    for name, millis in resources.items():
        if name == res.CPU:
            price += 0.1 * millis / 1000
        elif name == res.MEMORY:
            price += 0.1 * (millis / 1000) / 1e9
        elif name in (RESOURCE_GPU_VENDOR_A, RESOURCE_GPU_VENDOR_B):
            price += 1.0
    return price


def new_instance_type(
    name: str,
    resources: Optional[res.ResourceList] = None,
    offerings: Optional[Offerings] = None,
    architecture: str = "amd64",
    operating_systems: Optional[set[str]] = None,
    custom_requirements: Optional[list[Requirement]] = None,
) -> InstanceType:
    """Replicates fake.NewInstanceType (fake/instancetype.go:49-153)."""
    resources = dict(resources or {})
    resources.setdefault(res.CPU, q("4"))
    resources.setdefault(res.MEMORY, q("4Gi"))
    resources.setdefault(res.PODS, q("5"))
    operating_systems = operating_systems or {"linux", "windows", "darwin"}
    if offerings is None:
        price = price_from_resources(resources)
        offerings = Offerings(
            Offering(
                requirements=Requirements.from_labels(
                    {
                        well_known.CAPACITY_TYPE_LABEL_KEY: ct,
                        well_known.TOPOLOGY_ZONE_LABEL_KEY: zone,
                    }
                ),
                price=price,
                available=True,
            )
            for ct, zone in [
                ("spot", "test-zone-1"),
                ("spot", "test-zone-2"),
                ("on-demand", "test-zone-1"),
                ("on-demand", "test-zone-2"),
                ("on-demand", "test-zone-3"),
            ]
        )
    available = Offerings(o for o in offerings if o.available)
    zones = sorted({o.zone() for o in available})
    capacity_types = sorted({o.capacity_type() for o in available})
    requirements = Requirements(
        [
            Requirement(well_known.INSTANCE_TYPE_LABEL_KEY, Operator.IN, [name]),
            Requirement(well_known.ARCH_LABEL_KEY, Operator.IN, [architecture]),
            Requirement(well_known.OS_LABEL_KEY, Operator.IN, sorted(operating_systems)),
            Requirement(well_known.TOPOLOGY_ZONE_LABEL_KEY, Operator.IN, zones),
            Requirement(well_known.CAPACITY_TYPE_LABEL_KEY, Operator.IN, capacity_types),
            Requirement(INTEGER_INSTANCE_LABEL_KEY, Operator.IN, [str(resources[res.CPU] // 1000)]),
        ]
    )
    # large instances carry size=large + special=optional; small carry size=small
    # (fake/instancetype.go:126-139)
    if resources[res.CPU] > q("4") and resources[res.MEMORY] > q("8Gi"):
        requirements.add(Requirement(LABEL_INSTANCE_SIZE, Operator.IN, ["large"]))
        requirements.add(Requirement(EXOTIC_INSTANCE_LABEL_KEY, Operator.IN, ["optional"]))
    else:
        requirements.add(Requirement(LABEL_INSTANCE_SIZE, Operator.IN, ["small"]))
        requirements.add(Requirement(EXOTIC_INSTANCE_LABEL_KEY, Operator.DOES_NOT_EXIST))
    for cr in custom_requirements or []:
        requirements.add(cr)
    return InstanceType(
        name=name,
        requirements=requirements,
        offerings=offerings,
        capacity=resources,
        overhead=InstanceTypeOverhead(
            kube_reserved=res.parse_list({res.CPU: "100m", res.MEMORY: "10Mi"})
        ),
    )


def default_instance_types() -> InstanceTypes:
    """The reference fake provider's DEFAULT universe
    (fake/cloudprovider.go:234-271): default, small, two gpu vendors, an
    arm type with exotic operating systems, and a single-pod type. The
    scheduling suite's instance-type-compatibility scenarios are written
    against exactly this set."""
    return InstanceTypes(
        [
            new_instance_type(name="default-instance-type"),
            new_instance_type(
                name="small-instance-type",
                resources={res.CPU: q("2"), res.MEMORY: q("2Gi")},
            ),
            new_instance_type(
                name="gpu-vendor-instance-type",
                resources={RESOURCE_GPU_VENDOR_A: q("2")},
            ),
            new_instance_type(
                name="gpu-vendor-b-instance-type",
                resources={RESOURCE_GPU_VENDOR_B: q("2")},
            ),
            new_instance_type(
                name="arm-instance-type",
                architecture="arm64",
                operating_systems={"ios", "linux", "windows", "darwin"},
                resources={res.CPU: q("16"), res.MEMORY: q("128Gi")},
            ),
            new_instance_type(
                name="single-pod-instance-type",
                resources={res.PODS: q("1")},
            ),
        ]
    )


def instance_types(total: int) -> InstanceTypes:
    """fake.InstanceTypes(total): incrementing 1..total vCPU, 2..2*total Gi,
    10..10*total pods (fake/instancetype.go:200)."""
    return InstanceTypes(
        new_instance_type(
            name=f"fake-it-{i}",
            resources={
                res.CPU: q(str(i + 1)),
                res.MEMORY: q(f"{(i + 1) * 2}Gi"),
                res.PODS: q(str((i + 1) * 10)),
            },
        )
        for i in range(total)
    )


def instance_types_assorted() -> InstanceTypes:
    """fake.InstanceTypesAssorted: cartesian product over cpu x mem x zone x
    capacity-type x os x arch (fake/instancetype.go:156)."""
    out = InstanceTypes()
    for cpu, mem, zone, ct, os_, arch in itertools.product(
        [1, 2, 4, 8, 16, 32, 64],
        [1, 2, 4, 8, 16, 32, 64, 128],
        ["test-zone-1", "test-zone-2", "test-zone-3"],
        ["spot", "on-demand"],
        ["linux", "windows"],
        ["amd64", "arm64"],
    ):
        resources = {res.CPU: q(str(cpu)), res.MEMORY: q(f"{mem}Gi")}
        out.append(
            new_instance_type(
                name=f"{cpu}-cpu-{mem}-mem-{arch}-{os_}-{zone}-{ct}",
                architecture=arch,
                operating_systems={os_},
                resources=resources,
                offerings=Offerings(
                    [
                        Offering(
                            requirements=Requirements.from_labels(
                                {
                                    well_known.CAPACITY_TYPE_LABEL_KEY: ct,
                                    well_known.TOPOLOGY_ZONE_LABEL_KEY: zone,
                                }
                            ),
                            price=price_from_resources(resources),
                            available=True,
                        )
                    ]
                ),
            )
        )
    return out


class FakeCloudProvider(CloudProvider):
    """Records SPI calls, supports injected errors and per-NodePool instance
    types (reference fake/cloudprovider.go:52-546)."""

    def __init__(self, types: Optional[InstanceTypes] = None):
        self.instance_types_list = types if types is not None else instance_types(5)
        self.instance_types_for_nodepool: dict[str, InstanceTypes] = {}
        self.created: dict[str, NodeClaim] = {}  # provider_id -> claim
        self.create_calls: list[NodeClaim] = []
        self.delete_calls: list[NodeClaim] = []
        self.next_create_err: Optional[Exception] = None
        self.next_delete_err: Optional[Exception] = None
        self.next_get_err: Optional[Exception] = None
        self.drifted: str = ""
        self.repair_policy_list = []
        self.allow_insufficient_capacity = False
        self._seq = itertools.count(1)
        self._lock = threading.Lock()

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        with self._lock:
            self.create_calls.append(node_claim)
            if self.next_create_err is not None:
                err, self.next_create_err = self.next_create_err, None
                raise err
            reqs = Requirements.from_node_selector_requirements(node_claim.requirements)
            # pick the cheapest compatible instance type the way the KWOK
            # provider does (kwok/cloudprovider/cloudprovider.go:198-215)
            its = InstanceTypes(
                it
                for it in self.get_instance_types_by_name(node_claim)
                if reqs.intersects(it.requirements) is None
                and it.offerings.available().has_compatible(reqs)
            )
            if not its:
                from karpenter_tpu.cloudprovider.types import InsufficientCapacityError

                raise InsufficientCapacityError(
                    f"no instance type satisfies {node_claim.name}"
                )
            its.order_by_price(reqs)
            it = its[0]
            offering = min(
                (o for o in it.offerings.available().compatible(reqs)),
                key=lambda o: o.price,
            )
            provider_id = f"fake:///{it.name}/{next(self._seq):06d}"
            created = NodeClaim(
                metadata=copy.deepcopy(node_claim.metadata),
                requirements=node_claim.requirements,
                taints=node_claim.taints,
                startup_taints=node_claim.startup_taints,
                node_class_ref=node_claim.node_class_ref,
                status=NodeClaimStatus(
                    provider_id=provider_id,
                    capacity=dict(it.capacity),
                    allocatable=dict(it.allocatable()),
                ),
            )
            created.metadata.labels = dict(node_claim.metadata.labels)
            created.metadata.labels[well_known.INSTANCE_TYPE_LABEL_KEY] = it.name
            created.metadata.labels[well_known.TOPOLOGY_ZONE_LABEL_KEY] = offering.zone()
            created.metadata.labels[well_known.CAPACITY_TYPE_LABEL_KEY] = offering.capacity_type()
            self.created[provider_id] = created
            return created

    def delete(self, node_claim: NodeClaim) -> None:
        with self._lock:
            self.delete_calls.append(node_claim)
            if self.next_delete_err is not None:
                err, self.next_delete_err = self.next_delete_err, None
                raise err
            if node_claim.status.provider_id not in self.created:
                raise NodeClaimNotFoundError(node_claim.status.provider_id)
            del self.created[node_claim.status.provider_id]

    def get(self, provider_id: str) -> NodeClaim:
        with self._lock:
            if self.next_get_err is not None:
                err, self.next_get_err = self.next_get_err, None
                raise err
            if provider_id not in self.created:
                raise NodeClaimNotFoundError(provider_id)
            return self.created[provider_id]

    def list(self) -> list[NodeClaim]:
        with self._lock:
            return list(self.created.values())

    def get_instance_types(self, node_pool: NodePool) -> InstanceTypes:
        return self.instance_types_for_nodepool.get(
            node_pool.name, self.instance_types_list
        )

    def get_instance_types_by_name(self, node_claim: NodeClaim) -> InstanceTypes:
        pool = node_claim.nodepool_name
        if pool and pool in self.instance_types_for_nodepool:
            return self.instance_types_for_nodepool[pool]
        return self.instance_types_list

    def is_drifted(self, node_claim: NodeClaim) -> str:
        return self.drifted

    def repair_policies(self):
        return self.repair_policy_list

    def name(self) -> str:
        return "fake"

    def reset(self) -> None:
        with self._lock:
            self.created.clear()
            self.create_calls.clear()
            self.delete_calls.clear()
            self.next_create_err = None
            self.next_delete_err = None
            self.drifted = ""
