from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    CreateError,
    InstanceType,
    InstanceTypeOverhead,
    InstanceTypes,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    NodeClassNotReadyError,
    Offering,
    Offerings,
    RepairPolicy,
)

__all__ = [
    "CloudProvider",
    "CreateError",
    "InstanceType",
    "InstanceTypeOverhead",
    "InstanceTypes",
    "InsufficientCapacityError",
    "NodeClaimNotFoundError",
    "NodeClassNotReadyError",
    "Offering",
    "Offerings",
    "RepairPolicy",
]
