"""CloudProvider decorators: metrics and node-overlay.

Reference:
- metrics decorator /root/reference/pkg/cloudprovider/metrics/cloudprovider.go
  (times and counts every SPI method)
- overlay decorator /root/reference/pkg/cloudprovider/overlay/cloudprovider.go
  (applies NodeOverlay price/capacity patches to GetInstanceTypes results via
  a swap-on-write InstanceTypeStore)
"""

from __future__ import annotations

import copy
from typing import Optional

from karpenter_tpu import metrics

SPI_DURATION = metrics.REGISTRY.histogram(
    "karpenter_cloudprovider_duration_seconds",
    "Duration of cloud provider method calls.",
    ("controller", "method", "provider"),
)
SPI_ERRORS = metrics.REGISTRY.counter(
    "karpenter_cloudprovider_errors_total",
    "Cloud provider method errors.",
    ("controller", "method", "provider"),
)


class MetricsCloudProvider:
    """Wraps any provider; every SPI call is timed and error-counted."""

    _methods = (
        "create",
        "delete",
        "get",
        "list",
        "get_instance_types",
        "is_drifted",
    )

    def __init__(self, inner, controller: str = ""):
        self.inner = inner
        self.controller = controller

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        if name not in self._methods or not callable(attr):
            return attr
        provider = self.inner.name()

        def wrapped(*args, **kwargs):
            labels = {
                "controller": self.controller,
                "method": name,
                "provider": provider,
            }
            with SPI_DURATION.measure(labels):
                try:
                    return attr(*args, **kwargs)
                except Exception:
                    SPI_ERRORS.inc(labels)
                    raise

        return wrapped

    def name(self) -> str:
        return self.inner.name()

    def repair_policies(self):
        return self.inner.repair_policies()


class InstanceTypeStore:
    """overlay/store.go:47: overlays evaluated in order into a snapshot that
    swaps atomically; readers never see a half-applied overlay set."""

    def __init__(self):
        self._snapshot: dict[str, list] = {}  # nodepool -> patched types

    def update(self, nodepool_name: str, patched_types: list) -> None:
        self._snapshot[nodepool_name] = patched_types

    def get(self, nodepool_name: str) -> Optional[list]:
        return self._snapshot.get(nodepool_name)

    def clear(self) -> None:
        self._snapshot.clear()


class OverlayCloudProvider:
    """overlay/cloudprovider.go:54: GetInstanceTypes consults the overlay
    store; everything else passes through."""

    def __init__(self, inner, store: InstanceTypeStore):
        self.inner = inner
        self.store = store

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def get_instance_types(self, node_pool):
        patched = self.store.get(node_pool.name)
        if patched is not None:
            return patched
        return self.inner.get_instance_types(node_pool)

    def name(self) -> str:
        return self.inner.name()
