from karpenter_tpu.scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    Requirement,
    Requirements,
)
from karpenter_tpu.scheduling.taints import Taints

__all__ = [
    "ALLOW_UNDEFINED_WELL_KNOWN_LABELS",
    "Requirement",
    "Requirements",
    "Taints",
]
