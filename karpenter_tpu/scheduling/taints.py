"""Taint/toleration matching (reference /root/reference/pkg/scheduling/taints.go)."""

from __future__ import annotations

from typing import Iterable, Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import Pod, Taint, TaintEffect, Toleration

# Taints expected on a node while it's initializing; ignored for uninitialized
# managed nodes (reference taints.go:37 KnownEphemeralTaints).
KNOWN_EPHEMERAL_TAINTS: list[Taint] = [
    Taint("node.kubernetes.io/not-ready", TaintEffect.NO_SCHEDULE),
    Taint("node.kubernetes.io/not-ready", TaintEffect.NO_EXECUTE),
    Taint("node.kubernetes.io/unreachable", TaintEffect.NO_SCHEDULE),
    Taint("node.cloudprovider.kubernetes.io/uninitialized", TaintEffect.NO_SCHEDULE, "true"),
]

# The taint a provisioned-but-unregistered node carries (reference apis/v1).
UNREGISTERED_TAINT = Taint(f"{well_known.GROUP}/unregistered", TaintEffect.NO_EXECUTE)

# The taint the disruption machinery applies before draining (reference
# apis/v1 DisruptedNoScheduleTaint).
DISRUPTED_TAINT = Taint(f"{well_known.GROUP}/disrupted", TaintEffect.NO_SCHEDULE)


class Taints(list):
    """Decorated list of Taint (reference taints.go:45)."""

    def tolerates_pod(self, pod: Pod) -> Optional[str]:
        return self.tolerates(pod.tolerations)

    def tolerates(self, tolerations: Iterable[Toleration]) -> Optional[str]:
        """Every taint (of any effect, including PreferNoSchedule — softness is
        handled by the relaxation ladder, preferences.go:140) must be tolerated.
        Returns an error string or None (reference taints.go:53)."""
        tolerations = list(tolerations)
        errs = []
        for taint in self:
            if not any(t.tolerates(taint) for t in tolerations):
                errs.append(
                    f"did not tolerate taint {taint.key}={taint.value}:{taint.effect.value}"
                )
        return "; ".join(errs) if errs else None

    def merge(self, other: Iterable[Taint]) -> "Taints":
        """Union keyed by (key, effect) (reference taints.go:68 Merge)."""
        result = Taints(self)
        for taint in other:
            if not any(t.key == taint.key and t.effect == taint.effect for t in result):
                result.append(taint)
        return result
