"""Host-port conflict tracking (reference /root/reference/pkg/scheduling/
hostportusage.go:35)."""

from __future__ import annotations

from typing import Iterable, Optional

from karpenter_tpu.api.objects import Pod

# a host port is (ip, protocol, port)
HostPort = tuple[str, str, int]

_WILDCARD = ("0.0.0.0", "")


def get_host_ports(pod: Pod) -> list[HostPort]:
    return [(ip or "0.0.0.0", proto or "TCP", port) for ip, proto, port in pod.host_ports]


def _conflicts(a: HostPort, b: HostPort) -> bool:
    if a[2] != b[2] or a[1] != b[1]:
        return False
    return a[0] == b[0] or a[0] in _WILDCARD or b[0] in _WILDCARD


class HostPortUsage:
    def __init__(self) -> None:
        self._by_pod: dict[str, list[HostPort]] = {}

    def conflicts(self, pod: Pod, ports: Iterable[HostPort]) -> Optional[str]:
        for port in ports:
            for uid, existing in self._by_pod.items():
                if uid == pod.uid:
                    continue
                for e in existing:
                    if _conflicts(port, e):
                        return f"host port {port} conflicts with existing usage {e}"
        return None

    def add(self, pod: Pod, ports: Iterable[HostPort]) -> None:
        self._by_pod[pod.uid] = list(ports)

    def remove(self, pod) -> None:
        uid = pod if isinstance(pod, str) else pod.uid
        self._by_pod.pop(uid, None)

    def copy(self) -> "HostPortUsage":
        c = HostPortUsage()
        c._by_pod = {k: list(v) for k, v in self._by_pod.items()}
        return c
