"""The constraint algebra: Requirement / Requirements.

This is the inner loop of the whole framework — every compatibility decision in
the scheduler reduces to set algebra over label-value constraints. Semantics
follow the reference exactly:

- Requirement: /root/reference/pkg/scheduling/requirement.go:36-231
  A (possibly complemented) set of label values with optional integer bounds
  (Gt/Lt) and a MinValues flexibility floor. `In` is a concrete set; `NotIn`,
  `Exists`, `Gt`, `Lt` are complements; `DoesNotExist` is the empty concrete set.
- Requirements: /root/reference/pkg/scheduling/requirements.go:36-268
  A key->Requirement map with auto-intersection on Add, `Exists` as the default
  for absent keys, and the asymmetric Compatible() rule: custom labels must be
  *defined* on the target, well-known labels may be undefined.

The TPU solver does not execute this Python code in its hot path — it encodes
the same semantics into bitmask tensors (karpenter_tpu.ops.encode) — but this
class is the source of truth, the oracle the tensors are tested against.
"""

from __future__ import annotations

import random
import sys

# Seeded module-level RNG so representative values for complement requirements
# (any_value on NotIn/Exists/Gt/Lt) are deterministic across identical runs —
# required for bit-identical oracle-vs-TPU comparisons.
_any_rng = random.Random(0x5EED)
from typing import Iterable, Iterator, Mapping, Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    NodeSelectorRequirement,
    Operator,
    Pod,
)

_MAX_LEN = sys.maxsize


def _parse_int(value: str) -> Optional[int]:
    try:
        return int(value)
    except ValueError:
        return None


def _within_bounds(value: str, greater_than: Optional[int], less_than: Optional[int]) -> bool:
    """reference requirement.go:311 withinIntPtrs — non-integer values are
    invalid when bounds are set."""
    if greater_than is None and less_than is None:
        return True
    iv = _parse_int(value)
    if iv is None:
        return False
    if greater_than is not None and greater_than >= iv:
        return False
    if less_than is not None and less_than <= iv:
        return False
    return True


def _max_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def _min_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


class Requirement:
    """An efficient representation of a NodeSelectorRequirement
    (reference requirement.go:36)."""

    __slots__ = ("key", "complement", "values", "greater_than", "less_than", "min_values")

    def __init__(
        self,
        key: str,
        operator: Operator | str,
        values: Iterable[str] = (),
        min_values: Optional[int] = None,
    ):
        key = well_known.NORMALIZED_LABELS.get(key, key)
        operator = Operator(operator)
        self.key = key
        self.min_values = min_values
        self.greater_than: Optional[int] = None
        self.less_than: Optional[int] = None
        values = list(values)
        if operator == Operator.IN:
            self.complement = False
            self.values: set[str] = set(values)
        elif operator == Operator.DOES_NOT_EXIST:
            self.complement = False
            self.values = set()
        else:
            self.complement = True
            self.values = set()
            if operator == Operator.NOT_IN:
                self.values.update(values)
            elif operator == Operator.GT:
                self.greater_than = int(values[0])
            elif operator == Operator.LT:
                self.less_than = int(values[0])

    @classmethod
    def _raw(
        cls,
        key: str,
        complement: bool,
        values: set[str],
        greater_than: Optional[int] = None,
        less_than: Optional[int] = None,
        min_values: Optional[int] = None,
    ) -> "Requirement":
        r = cls.__new__(cls)
        r.key = key
        r.complement = complement
        r.values = values
        r.greater_than = greater_than
        r.less_than = less_than
        r.min_values = min_values
        return r

    @classmethod
    def from_node_selector_requirement(cls, nsr: NodeSelectorRequirement) -> "Requirement":
        return cls(nsr.key, nsr.operator, nsr.values, nsr.min_values)

    # -- algebra ---------------------------------------------------------

    def intersection(self, other: "Requirement") -> "Requirement":
        """reference requirement.go:158 Intersection."""
        complement = self.complement and other.complement
        greater_than = _max_opt(self.greater_than, other.greater_than)
        less_than = _min_opt(self.less_than, other.less_than)
        min_values = _max_opt(self.min_values, other.min_values)
        if greater_than is not None and less_than is not None and greater_than >= less_than:
            return Requirement(self.key, Operator.DOES_NOT_EXIST, min_values=min_values)

        if self.complement and other.complement:
            values = self.values | other.values
        elif self.complement and not other.complement:
            values = other.values - self.values
        elif not self.complement and other.complement:
            values = self.values - other.values
        else:
            values = self.values & other.values
        values = {v for v in values if _within_bounds(v, greater_than, less_than)}
        if not complement:
            greater_than, less_than = None, None
        return Requirement._raw(self.key, complement, values, greater_than, less_than, min_values)

    def has_intersection(self, other: "Requirement") -> bool:
        """Zero-allocation intersection test (reference requirement.go:197)."""
        greater_than = _max_opt(self.greater_than, other.greater_than)
        less_than = _min_opt(self.less_than, other.less_than)
        if greater_than is not None and less_than is not None and greater_than >= less_than:
            return False
        if self.complement and other.complement:
            return True
        if self.complement and not other.complement:
            return any(
                v not in self.values and _within_bounds(v, greater_than, less_than)
                for v in other.values
            )
        if not self.complement and other.complement:
            return any(
                v not in other.values and _within_bounds(v, greater_than, less_than)
                for v in self.values
            )
        return any(
            v in other.values and _within_bounds(v, greater_than, less_than)
            for v in self.values
        )

    def has(self, value: str) -> bool:
        """True if the requirement allows the value (requirement.go:252)."""
        if self.complement:
            return value not in self.values and _within_bounds(
                value, self.greater_than, self.less_than
            )
        return value in self.values and _within_bounds(value, self.greater_than, self.less_than)

    def any_value(self) -> str:
        """A representative allowed value (requirement.go:233 Any)."""
        op = self.operator()
        if op == Operator.IN:
            return min(self.values)  # deterministic, unlike the reference's map order
        if op in (Operator.NOT_IN, Operator.EXISTS):
            lo = 0 if self.greater_than is None else self.greater_than + 1
            hi = (1 << 63) if self.less_than is None else self.less_than
            if lo >= hi:
                return ""
            for _ in range(100):
                candidate = str(_any_rng.randrange(lo, hi))
                if candidate not in self.values:
                    return candidate
        return ""

    def operator(self) -> Operator:
        """requirement.go:267 Operator (Gt/Lt render as Exists-with-bounds)."""
        if self.complement:
            return Operator.NOT_IN if self.values else Operator.EXISTS
        return Operator.IN if self.values else Operator.DOES_NOT_EXIST

    def __len__(self) -> int:
        if self.complement:
            return _MAX_LEN - len(self.values)
        return len(self.values)

    def to_node_selector_requirement(self) -> NodeSelectorRequirement:
        """requirement.go:93 NodeSelectorRequirement."""
        if self.greater_than is not None:
            return NodeSelectorRequirement(
                self.key, Operator.GT, [str(self.greater_than)], self.min_values
            )
        if self.less_than is not None:
            return NodeSelectorRequirement(
                self.key, Operator.LT, [str(self.less_than)], self.min_values
            )
        return NodeSelectorRequirement(
            self.key, self.operator(), sorted(self.values), self.min_values
        )

    def copy(self) -> "Requirement":
        return Requirement._raw(
            self.key,
            self.complement,
            set(self.values),
            self.greater_than,
            self.less_than,
            self.min_values,
        )

    def __repr__(self) -> str:
        op = self.operator()
        if op in (Operator.EXISTS, Operator.DOES_NOT_EXIST):
            s = f"{self.key} {op.value}"
        else:
            values = sorted(self.values)
            if len(values) > 5:
                values = values[:5] + [f"and {len(values) - 5} others"]
            s = f"{self.key} {op.value} {values}"
        if self.greater_than is not None:
            s += f" >{self.greater_than}"
        if self.less_than is not None:
            s += f" <{self.less_than}"
        if self.min_values is not None:
            s += f" minValues {self.min_values}"
        return s


# Sentinel option mirroring the reference's scheduling.AllowUndefinedWellKnownLabels
# (requirements.go:166): pass as `allow_undefined` to allow the (mutable) global
# well-known label set to be undefined on the target. Resolved identity-wise in
# compatible(), so late provider registrations into WELL_KNOWN_LABELS are seen.
ALLOW_UNDEFINED_WELL_KNOWN_LABELS = frozenset({"\x00allow-undefined-well-known-labels"})


class Requirements:
    """Key->Requirement map with intersection semantics
    (reference requirements.go:36)."""

    __slots__ = ("_reqs",)

    def __init__(self, requirements: Iterable[Requirement] = ()):
        self._reqs: dict[str, Requirement] = {}
        self.add(*requirements)

    # -- constructors ----------------------------------------------------

    @classmethod
    def from_labels(cls, labels: Mapping[str, str]) -> "Requirements":
        return cls(Requirement(k, Operator.IN, [v]) for k, v in labels.items())

    @classmethod
    def from_node_selector_requirements(
        cls, nsrs: Iterable[NodeSelectorRequirement]
    ) -> "Requirements":
        return cls(Requirement.from_node_selector_requirement(n) for n in nsrs)

    @classmethod
    def from_pod(cls, pod: Pod) -> "Requirements":
        """NewPodRequirements: node selector + heaviest node-affinity preference
        + first required term (requirements.go:90)."""
        return cls._from_pod(pod, include_preferred=True)

    @classmethod
    def strict_from_pod(cls, pod: Pod) -> "Requirements":
        """NewStrictPodRequirements: required constraints only."""
        return cls._from_pod(pod, include_preferred=False)

    @classmethod
    def _from_pod(cls, pod: Pod, include_preferred: bool) -> "Requirements":
        requirements = cls.from_labels(pod.node_selector)
        affinity = pod.node_affinity
        if affinity is None:
            return requirements
        if include_preferred and affinity.preferred:
            heaviest = max(affinity.preferred, key=lambda t: t.weight)
            requirements.add(
                *(
                    Requirement.from_node_selector_requirement(e)
                    for e in heaviest.preference.match_expressions
                )
            )
        if affinity.required_terms:
            requirements.add(
                *(
                    Requirement.from_node_selector_requirement(e)
                    for e in affinity.required_terms[0].match_expressions
                )
            )
        return requirements

    # -- map behavior ----------------------------------------------------

    def add(self, *requirements: Requirement) -> None:
        """Add with auto-intersection on key collision (requirements.go:127)."""
        for requirement in requirements:
            existing = self._reqs.get(requirement.key)
            if existing is not None:
                requirement = requirement.intersection(existing)
            self._reqs[requirement.key] = requirement

    def get(self, key: str) -> Requirement:
        """Absent keys default to Exists (requirements.go:154)."""
        r = self._reqs.get(key)
        if r is None:
            return Requirement(key, Operator.EXISTS)
        return r

    def has(self, key: str) -> bool:
        return key in self._reqs

    def keys(self) -> set[str]:
        return set(self._reqs)

    def values(self) -> list[Requirement]:
        return list(self._reqs.values())

    def pop(self, key: str) -> None:
        self._reqs.pop(key, None)

    def __iter__(self) -> Iterator[str]:
        return iter(self._reqs)

    def __len__(self) -> int:
        return len(self._reqs)

    def __contains__(self, key: str) -> bool:
        return key in self._reqs

    def copy(self) -> "Requirements":
        c = Requirements.__new__(Requirements)
        c._reqs = {k: v.copy() for k, v in self._reqs.items()}
        return c

    # -- compatibility ---------------------------------------------------

    def compatible(
        self, requirements: "Requirements", allow_undefined: Optional[set[str]] = None
    ) -> Optional[str]:
        """Ensure the incoming requirements can loosely be met
        (requirements.go:175 Compatible). Returns an error string or None.

        Custom labels must be *defined* on self; labels in `allow_undefined`
        (usually the well-known set) may be undefined.
        """
        if allow_undefined is ALLOW_UNDEFINED_WELL_KNOWN_LABELS:
            allow_undefined = well_known.WELL_KNOWN_LABELS
        allow = allow_undefined or set()
        for key in requirements:
            if key in allow:
                continue
            op = requirements.get(key).operator()
            if self.has(key) or op in (Operator.NOT_IN, Operator.DOES_NOT_EXIST):
                continue
            return f'label "{key}" does not have known values'
        return self.intersects(requirements)

    def is_compatible(
        self, requirements: "Requirements", allow_undefined: Optional[set[str]] = None
    ) -> bool:
        return self.compatible(requirements, allow_undefined) is None

    def intersects(self, requirements: "Requirements") -> Optional[str]:
        """Error if shared keys have no overlapping values (requirements.go:248).
        Undefined keys are allowed. NotIn/DoesNotExist-vs-NotIn/DoesNotExist
        disagreements are tolerated."""
        small, large = (
            (self, requirements) if len(self._reqs) <= len(requirements._reqs) else (requirements, self)
        )
        errs = []
        for key in small._reqs:
            if key not in large._reqs:
                continue
            existing = self.get(key)
            incoming = requirements.get(key)
            if not existing.has_intersection(incoming):
                in_op = incoming.operator()
                if in_op in (Operator.NOT_IN, Operator.DOES_NOT_EXIST):
                    ex_op = existing.operator()
                    if ex_op in (Operator.NOT_IN, Operator.DOES_NOT_EXIST):
                        continue
                errs.append(f"key {key}, {incoming!r} not in {existing!r}")
        return "; ".join(errs) if errs else None

    def labels(self) -> dict[str, str]:
        """Representative node labels (requirements.go:270 Labels)."""
        out = {}
        for key, requirement in self._reqs.items():
            if not well_known.is_restricted_node_label(key):
                value = requirement.any_value()
                if value:
                    out[key] = value
        return out

    def has_min_values(self) -> bool:
        return any(r.min_values is not None for r in self._reqs.values())

    def to_node_selector_requirements(self) -> list[NodeSelectorRequirement]:
        return [r.to_node_selector_requirement() for r in self._reqs.values()]

    def __repr__(self) -> str:
        parts = sorted(
            repr(r)
            for r in self._reqs.values()
            if r.key not in well_known.RESTRICTED_LABELS
        )
        return ", ".join(parts)
