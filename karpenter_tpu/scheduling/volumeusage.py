"""Per-node volume attachment tracking and limits.

Reference /root/reference/pkg/scheduling/volumeusage.go:187: the scheduler
tracks which persistent volumes each node would mount and refuses placements
that exceed the node's attachable-volume limit (derived from CSINode
allocatable in the reference; expressed here as a per-node limit surfaced by
the cloud provider / node labels — see VOLUME_LIMIT_LABEL_KEY).
"""

from __future__ import annotations

from typing import Iterable, Optional

from karpenter_tpu.api.objects import Pod

# Node label carrying the attachable-volume limit (the reference reads CSINode
# allocatable; the in-tree providers publish the same number as a label).
VOLUME_LIMIT_LABEL_KEY = "karpenter.sh/volume-attach-limit"


def volume_limit(labels: dict[str, str]) -> Optional[int]:
    raw = labels.get(VOLUME_LIMIT_LABEL_KEY)
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


class VolumeUsage:
    """Tracks the distinct volumes mounted per node, attributed per CSI
    DRIVER (reference volumeusage.go:187: CSINode publishes an attachable
    count per driver; a node can run several drivers with separate
    budgets). Volumes are (driver, claim) pairs; claims without a resolved
    driver land in the default "" bucket."""

    def __init__(self) -> None:
        self._by_pod: dict[str, set[tuple[str, str]]] = {}

    def add(self, pod: Pod) -> None:
        if pod.volume_claims:
            drivers = getattr(pod, "volume_drivers", {}) or {}
            self._by_pod[pod.uid] = {
                (drivers.get(c, ""), c) for c in pod.volume_claims
            }

    def remove(self, pod) -> None:
        uid = pod if isinstance(pod, str) else pod.uid
        self._by_pod.pop(uid, None)

    def distinct_volumes(self) -> set[tuple[str, str]]:
        out: set[tuple[str, str]] = set()
        for vols in self._by_pod.values():
            out |= vols
        return out

    def exceeds_limit(
        self,
        pod: Pod,
        limits,
    ) -> Optional[str]:
        """volumeusage.go ExceedsLimits: would mounting the pod's volumes
        push any involved DRIVER past its attachable count? `limits` is a
        dict driver -> count ("" = the label-derived default applied to
        unattributed volumes and drivers without a CSINode entry); a plain
        int is accepted as {"": int} for backward compatibility."""
        if limits is None or not pod.volume_claims:
            return None
        if isinstance(limits, int):
            limits = {"": limits}
        drivers = getattr(pod, "volume_drivers", {}) or {}
        total = self.distinct_volumes() | {
            (drivers.get(c, ""), c) for c in pod.volume_claims
        }
        per_driver: dict[str, int] = {}
        for d, _ in total:
            per_driver[d] = per_driver.get(d, 0) + 1
        for d, n in per_driver.items():
            limit = limits.get(d, limits.get(""))
            if limit is not None and n > limit:
                label = d or "default"
                return (
                    f"would exceed node volume limit for driver "
                    f"{label!r}: {n} > {limit} volumes"
                )
        return None

    def copy(self) -> "VolumeUsage":
        c = VolumeUsage()
        c._by_pod = {k: set(v) for k, v in self._by_pod.items()}
        return c
