"""Per-node volume attachment tracking and limits.

Reference /root/reference/pkg/scheduling/volumeusage.go:187: the scheduler
tracks which persistent volumes each node would mount and refuses placements
that exceed the node's attachable-volume limit (derived from CSINode
allocatable in the reference; expressed here as a per-node limit surfaced by
the cloud provider / node labels — see VOLUME_LIMIT_LABEL_KEY).
"""

from __future__ import annotations

from typing import Iterable, Optional

from karpenter_tpu.api.objects import Pod

# Node label carrying the attachable-volume limit (the reference reads CSINode
# allocatable; the in-tree providers publish the same number as a label).
VOLUME_LIMIT_LABEL_KEY = "karpenter.sh/volume-attach-limit"


def volume_limit(labels: dict[str, str]) -> Optional[int]:
    raw = labels.get(VOLUME_LIMIT_LABEL_KEY)
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


class VolumeUsage:
    """Tracks the distinct volumes (PVC names) mounted per node."""

    def __init__(self) -> None:
        self._by_pod: dict[str, set[str]] = {}

    def add(self, pod: Pod) -> None:
        if pod.volume_claims:
            self._by_pod[pod.uid] = set(pod.volume_claims)

    def remove(self, pod) -> None:
        uid = pod if isinstance(pod, str) else pod.uid
        self._by_pod.pop(uid, None)

    def distinct_volumes(self) -> set[str]:
        out: set[str] = set()
        for vols in self._by_pod.values():
            out |= vols
        return out

    def exceeds_limit(self, pod: Pod, limit: Optional[int]) -> Optional[str]:
        """volumeusage.go ExceedsLimits: would mounting the pod's volumes
        push the node past its attachable limit?"""
        if limit is None or not pod.volume_claims:
            return None
        total = self.distinct_volumes() | set(pod.volume_claims)
        if len(total) > limit:
            return (
                f"would exceed node volume limit: {len(total)} > {limit} volumes"
            )
        return None

    def copy(self) -> "VolumeUsage":
        c = VolumeUsage()
        c._by_pod = {k: set(v) for k, v in self._by_pod.items()}
        return c
