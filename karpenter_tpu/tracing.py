"""End-to-end solve telemetry: phase-span tracing from the controller to
the kernel drivers, wired through the sidecar protocol.

The reference treats per-phase timing as a first-class operator surface —
the `Measure` defer-timer (pkg/metrics/constants.go:63) and the pprof gate
(pkg/operator/operator.go:183-199). This module is that surface for the
solve path: ONE trace follows a provisioning round from
`Provisioner.schedule` through `ResilientSolver`, the wire client, the
`SolverServer`, and the host phases of the kernel drivers
(encode / order / upload / dispatch / regrow / decode) plus the
consolidation sweep rounds (disruption/sweep.py, setsweep.py).

Design constraints (CLAUDE.md performance invariants):

- **Explicit context objects, no contextvars.** A `Trace` is created at
  the top of a solve and passed DOWN the call chain as an ordinary
  argument. Nothing here ever runs inside jitted code — every span is a
  host-side `time.monotonic()` pair, so instrumentation can never add a
  retrace (the `same_bucket_solve_{traces,compiles}=0` IR budgets stay
  exact).
- **Wire correlation ids ARE trace ids.** The v2 frame header's req_id
  (solver/service.py) becomes the trace id on both sides of the socket
  (`Trace.set_wire_id`), so a client-side trace and the sidecar's
  server-side trace of the same solve join into one logical trace in the
  ring — no new protocol field.
- **Bounded by construction.** Completed traces land in a fixed-capacity
  ring (`RING`); each trace caps its span list (`MAX_SPANS`) and beyond
  the cap only aggregates per-phase totals. Per-span *detail* (the
  pod_xs/kernel/fetch sub-phases of each dispatch) is recorded only
  behind the profiling gate (`set_detail`, flipped by
  ProbeServer(enable_profiling=True)) — the default cost per solve is a
  few dozen monotonic() pairs and one histogram observe per phase name.

The ring is exposed by controllers/probes.ProbeServer as `/debug/solves`
(recent-trace summaries) and `/debug/solves/<id>` (the per-trace phase
waterfall), mirroring the pprof endpoints. Every span also feeds the
labeled Prometheus metrics below; docs/observability.md is the catalog
(a drift test pins it against the registered names).

This module also owns the jax.monitoring compile/retrace counters
(`trace_events`, promoted here from analysis/ir.py so runtime solves and
the graftlint IR tier share one accounting): the listener feeds both the
context-manager counters and the `karpenter_jax_compilation_events_total`
metric, so steady-state traffic surfaces backend compiles / cache hits
without running graftlint. Import of this module stays stdlib-only —
jax is imported lazily inside the listener installer.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Any, Iterator, Optional

from karpenter_tpu import metrics

# -- solve telemetry metrics -------------------------------------------------

SOLVE_PHASE_SECONDS = metrics.REGISTRY.histogram(
    "karpenter_solve_phase_duration_seconds",
    "Wall-clock seconds per solve phase (one observation per phase per trace).",
    ("phase",),
)
SOLVE_DISPATCHES = metrics.REGISTRY.counter(
    "karpenter_solve_dispatches_total",
    "Device kernel dispatches, by path (runs/scan/sweep/setsweep, plus "
    "fleet = one coalesced vmapped dispatch per batch-window round).",
    ("path",),
)
SOLVE_REGROWS = metrics.REGISTRY.counter(
    "karpenter_solve_claim_regrows_total",
    "Mid-solve claim-slot pool growth events (runs-path overflow continuations).",
)
SOLVE_RELAX_TIERS = metrics.REGISTRY.counter(
    "karpenter_solve_relax_tiers_total",
    "Relaxation-ladder tiers beyond tier 0 carried by compiled solve steps.",
)
SOLVE_UPLOAD_BYTES = metrics.REGISTRY.counter(
    "karpenter_solve_upload_bytes_total",
    "Host->device bytes uploaded for per-solve tables (the tunnel charges per byte).",
)
SOLVE_FALLBACKS = metrics.REGISTRY.counter(
    "karpenter_solve_oracle_fallback_total",
    "Solves (or solve partitions) that ran on the oracle, by reason.",
    ("reason",),
)
SOLVE_TRACES = metrics.REGISTRY.counter(
    "karpenter_solve_traces_total",
    "Completed solve traces, by kind and outcome.",
    ("kind", "outcome"),
)
SWEEP_SET_LANES = metrics.REGISTRY.counter(
    "karpenter_sweep_set_lanes_total",
    "Removal-set lanes evaluated by consolidation sweep dispatches.",
)
JAX_COMPILE_EVENTS = metrics.REGISTRY.counter(
    "karpenter_jax_compilation_events_total",
    "jax.monitoring compile events (traces/compiles/cache_hits); real "
    "backend builds = compiles - cache_hits.",
    ("event",),
)

# -- kernel odometers (device-truth counters returned by each dispatch) ------

KERNEL_ITERATIONS = metrics.REGISTRY.counter(
    "karpenter_kernel_iterations_total",
    "Device loop iterations executed inside kernel dispatches, by path "
    "(runs/scan while-loop and scan steps, fleet = per-lane scan steps, "
    "sweep/setsweep class-scan trips) — the odometer wave packing must "
    "shrink.",
    ("path",),
)
KERNEL_TIER_STEPS = metrics.REGISTRY.counter(
    "karpenter_kernel_relax_tier_steps_total",
    "Relax tier-loop body trips by tier index (each trip runs one full "
    "kernel step; tier 7 aggregates deeper rungs).",
    ("tier",),
)
KERNEL_CLAIMS_OPENED = metrics.REGISTRY.counter(
    "karpenter_kernel_claims_opened_total",
    "Fresh claim slots the kernel committed (device n_claims at decode).",
)
KERNEL_CLAIM_OCCUPANCY = metrics.REGISTRY.histogram(
    "karpenter_kernel_claim_slot_occupancy",
    "High-water claim-slot occupancy per solve (n_claims / padded slot "
    "pool N after any regrows) — how tight claim_slot_div started.",
    buckets=[0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0],
)
KERNEL_OVERFLOWS = metrics.REGISTRY.counter(
    "karpenter_kernel_overflow_signals_total",
    "Claim-slot overflow signals returned by kernel dispatches, by path "
    "(runs: pad-and-continue regrow; scan: full re-solve at 2N).",
    ("path",),
)

# spans recorded per trace before degrading to aggregate-only totals
MAX_SPANS = 256
# completed traces retained for /debug/solves
RING_CAPACITY = 128

RING_TRACES = metrics.REGISTRY.gauge(
    "karpenter_trace_ring_traces",
    "Completed traces currently held by the /debug/solves ring "
    "(capacity RING_CAPACITY=128; pegged at capacity = oldest traces "
    "are being evicted).",
)

# profiling gate: when off, detail=True spans fold into the per-phase
# totals without an individual Span entry (ProbeServer flips this with
# enable_profiling, the pprof-gate analog)
_DETAIL = False


def set_detail(on: bool) -> None:
    global _DETAIL
    _DETAIL = bool(on)


def detail_enabled() -> bool:
    return _DETAIL


class Span:
    """One timed phase inside a trace. `t0` is seconds since trace start;
    `depth` is the nesting level at entry (0 = top-level phase)."""

    __slots__ = ("name", "t0", "dur", "depth", "attrs")

    def __init__(self, name: str, t0: float, dur: float, depth: int, attrs: dict):
        self.name = name
        self.t0 = t0
        self.dur = dur
        self.depth = depth
        self.attrs = attrs

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "t0": round(self.t0, 6),
            "seconds": round(self.dur, 6),
            "depth": self.depth,
        }
        if self.attrs:
            d["attrs"] = self.attrs
        return d


_seq_lock = threading.Lock()
_seq = [0]


def _next_seq() -> int:
    with _seq_lock:
        _seq[0] += 1
        return _seq[0]


class Trace:
    """One solve's span record. NOT thread-safe by design: a trace belongs
    to the single thread driving its solve (server handler threads each
    own their trace); only the finished ring is shared."""

    def __init__(self, kind: str, side: str = "local", trace_id: Optional[str] = None):
        self.kind = kind
        self.side = side
        self.seq = _next_seq()
        self.trace_id = trace_id or f"t{self.seq}"
        self.started_at = time.time()
        self._t0 = time.monotonic()
        self.spans: list[Span] = []
        self.counts: dict[str, int] = {}
        self.attrs: dict[str, Any] = {}
        self.outcome: Optional[str] = None
        self.total_seconds = 0.0
        self.truncated = False
        # per-phase totals: name -> [seconds, min depth seen]
        self._phase_totals: dict[str, list] = {}
        self._depth = 0

    # -- recording -------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, detail: bool = False, **attrs: Any) -> Iterator[dict]:
        """Time the enclosed block as a phase. detail=True spans (the
        per-dispatch pod_xs/kernel/fetch sub-phases) still accumulate in
        the phase totals but only get an individual Span entry when the
        profiling gate is on.

        Yields the span's (mutable) attrs dict, so blocks whose facts
        only exist at exit can attach them — the dispatch spans put the
        fetched kernel-odometer block here (`attrs["kernel"] = {...}`)
        and the /debug/solves waterfall shows device work per dispatch,
        not just host wall-clock."""
        attrs = dict(attrs)
        depth = self._depth
        self._depth = depth + 1
        start = time.monotonic()
        try:
            yield attrs
        finally:
            self._depth = depth
            dur = time.monotonic() - start
            tot = self._phase_totals.setdefault(name, [0.0, depth])
            tot[0] += dur
            tot[1] = min(tot[1], depth)
            if (not detail) or _DETAIL:
                if len(self.spans) < MAX_SPANS:
                    self.spans.append(
                        Span(name, start - self._t0, dur, depth, attrs)
                    )
                else:
                    self.truncated = True

    def event(self, name: str, **attrs: Any) -> None:
        """A zero-duration marker span (e.g. an oracle-fallback reason)."""
        if len(self.spans) < MAX_SPANS:
            self.spans.append(
                Span(name, time.monotonic() - self._t0, 0.0, self._depth, dict(attrs))
            )
        else:
            self.truncated = True

    def count(self, name: str, by: int = 1) -> None:
        self.counts[name] = self.counts.get(name, 0) + by

    def annotate(self, **kw: Any) -> None:
        self.attrs.update(kw)

    def set_wire_id(self, req_id: int) -> None:
        """Adopt the v2 frame correlation id as the trace id, joining this
        trace with its peer across the sidecar socket."""
        self.trace_id = f"w{int(req_id)}"

    # -- completion ------------------------------------------------------

    def finish(self, outcome: str = "ok") -> None:
        """Idempotent: push to the ring and emit the per-phase histogram
        observations (aggregated — one observe per phase name, not per
        span, so metric cost is bounded by the phase vocabulary).

        outcome="unsupported" marks expected ladder control flow (a sweep
        gate raising SweepUnsupported on every reconcile of a gated
        fleet): counted in the traces metric, but kept OUT of the ring so
        a permanently-gated fleet cannot crowd real solve traces out of
        /debug/solves."""
        if self.outcome is not None:
            return
        self.outcome = outcome
        self.total_seconds = time.monotonic() - self._t0
        # spans append at EXIT (children before parents); the waterfall
        # reads start-ordered
        self.spans.sort(key=lambda s: (s.t0, s.depth))
        if outcome != "unsupported":
            RING.push(self)
        for name, (secs, _depth) in self._phase_totals.items():
            SOLVE_PHASE_SECONDS.observe(secs, {"phase": name})
        SOLVE_TRACES.inc({"kind": self.kind, "outcome": outcome})

    # -- introspection ---------------------------------------------------

    @property
    def phases(self) -> dict[str, float]:
        """Per-phase wall-clock totals (every name, including nested
        sub-phases — nested names overlap their parents, so do not sum
        this dict; see top_phases)."""
        return {k: v[0] for k, v in self._phase_totals.items()}

    def top_phases(self) -> dict[str, float]:
        """Totals for depth-0 phases only — disjoint spans that partition
        the solve (encode/order/upload/dispatch/regrow/decode for a
        kernel solve); safe to sum for share-of-solve breakdowns."""
        return {k: v[0] for k, v in self._phase_totals.items() if v[1] == 0}

    def to_dict(self, summary: bool = False) -> dict:
        d = {
            "id": self.trace_id,
            "seq": self.seq,
            "kind": self.kind,
            "side": self.side,
            "started_at": self.started_at,
            "total_seconds": round(self.total_seconds, 6),
            "outcome": self.outcome,
            "attrs": dict(self.attrs),
            "counts": dict(self.counts),
            "truncated": self.truncated,
        }
        if not summary:
            d["phases"] = {k: round(v, 6) for k, v in self.phases.items()}
            d["spans"] = [s.to_dict() for s in self.spans]
        return d

    def render(self) -> str:
        """Phase table, largest first (SolveProfile.render analog)."""
        total = self.total_seconds or sum(self.top_phases().values()) or 1.0
        return "\n".join(
            f"{name:12s} {dt:8.3f}s {100.0 * dt / total:5.1f}%"
            for name, dt in sorted(self.phases.items(), key=lambda kv: -kv[1])
        )


class TraceRing:
    """Bounded ring of completed traces, newest last. The only shared
    telemetry structure: pushes come from solver/handler threads while
    /debug/solves snapshots concurrently, so membership mutates under a
    lock (metric observes happen outside it — the ring lock is a leaf in
    the program's lock graph, same discipline as SolverServer's)."""

    def __init__(self, capacity: int = RING_CAPACITY):
        self._items: deque[Trace] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def push(self, trace: Trace) -> None:
        with self._lock:
            self._items.append(trace)
            n = len(self._items)
        # observe outside the lock (leaf-lock discipline, class docstring)
        RING_TRACES.set(float(n))

    def snapshot(self) -> list[Trace]:
        with self._lock:
            return list(self._items)

    def find(self, ident: str) -> list[Trace]:
        """Traces whose trace_id or seq matches `ident` — a wire id may
        match one client-side and one server-side trace (that pair IS the
        joined trace)."""
        with self._lock:
            return [
                t
                for t in self._items
                if t.trace_id == ident or str(t.seq) == ident
            ]

    def clear(self) -> None:
        with self._lock:
            self._items.clear()
        RING_TRACES.set(0.0)


RING = TraceRing()


def new_trace(kind: str, side: str = "local") -> Trace:
    return Trace(kind, side=side)


@contextlib.contextmanager
def maybe_trace(trace: Optional[Trace], kind: str, side: str = "local") -> Iterator[Trace]:
    """Join the caller's trace, or own a fresh one: when `trace` is None a
    new trace is created and FINISHED on exit (outcome from the exception
    state); a passed-in trace is yielded untouched — its creator finishes
    it. This is how every solve layer accepts an optional trace without
    double-counting completions."""
    if trace is not None:
        yield trace
        return
    t = new_trace(kind, side=side)
    try:
        yield t
    except BaseException:
        t.finish("error")
        raise
    else:
        t.finish("ok")


def span_of(trace: Optional[Trace], name: str, detail: bool = False, **attrs: Any):
    """trace.span(...) or a no-op context when no trace rides the call
    (the no-op still yields a throwaway dict so `as attrs` writes work)."""
    if trace is None:
        return contextlib.nullcontext({})
    return trace.span(name, detail=detail, **attrs)


def record_fallback(trace: Optional[Trace], reason: str, detail: str = "") -> None:
    """An oracle-degrade decision: a labeled counter bump plus a marker
    span on the trace (the ISSUE's 'fallback reason recorded as a span +
    labeled counter'). `reason` is a low-cardinality class (unsupported /
    small_batch / forced / tpu_error / partition_continuation /
    prewarm_degraded); the free-text detail stays on the trace only."""
    SOLVE_FALLBACKS.inc({"reason": reason})
    if trace is not None:
        trace.event("oracle_fallback", reason=reason, detail=detail)


# ---------------------------------------------------------------------------
# jax.monitoring compile/retrace accounting (promoted from analysis/ir.py
# so runtime solves and the graftlint IR tier share one counter)

_COUNTS = {"traces": 0, "compiles": 0, "cache_hits": 0}
_LISTENER_INSTALLED = False


def install_compile_listener() -> None:
    """Register the jax.monitoring listeners once per process. There is no
    unregister API, so one module-level listener feeds the global counters
    (and the karpenter_jax_compilation_events_total metric) for the whole
    process lifetime. Call sites: trace_events.__enter__ and the solver
    package import — anywhere jax is already loaded."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    import jax

    def _on_duration(name: str, secs: float, **kw: Any) -> None:
        if name == "/jax/core/compile/jaxpr_trace_duration":
            _COUNTS["traces"] += 1
            JAX_COMPILE_EVENTS.inc({"event": "traces"})
        elif name == "/jax/core/compile/backend_compile_duration":
            _COUNTS["compiles"] += 1
            JAX_COMPILE_EVENTS.inc({"event": "compiles"})

    def _on_event(name: str, **kw: Any) -> None:
        if name == "/jax/compilation_cache/cache_hits":
            _COUNTS["cache_hits"] += 1
            JAX_COMPILE_EVENTS.inc({"event": "cache_hits"})

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    jax.monitoring.register_event_listener(_on_event)
    _LISTENER_INSTALLED = True


class trace_events(contextlib.AbstractContextManager):
    """Counts jaxpr traces and backend compiles inside the block.

        with trace_events() as ev:
            solve()
        assert ev.traces == 0

    Properties read live, so mid-block checkpoints work too. There is no
    listener-unregister API in jax.monitoring — one module-level listener
    feeds a global counter and contexts snapshot it.

    `compiles` counts the backend_compile_duration event, which fires per
    compile_or_get_cached call — INCLUDING persistent-cache hits (the
    event wraps the whole fetch-or-build step). `backend_compiles`
    subtracts the cache-hit events, so it is the number of programs XLA
    actually built: the metric the zero-compile cold-start contract pins
    (a fresh process with a warm disk cache must show 0)."""

    def __enter__(self) -> "trace_events":
        install_compile_listener()
        self._t0 = _COUNTS["traces"]
        self._c0 = _COUNTS["compiles"]
        self._h0 = _COUNTS["cache_hits"]
        return self

    def __exit__(self, *exc: Any) -> None:
        return None

    @property
    def traces(self) -> int:
        return _COUNTS["traces"] - self._t0

    @property
    def compiles(self) -> int:
        return _COUNTS["compiles"] - self._c0

    @property
    def cache_hits(self) -> int:
        return _COUNTS["cache_hits"] - self._h0

    @property
    def backend_compiles(self) -> int:
        return max(0, self.compiles - self.cache_hits)
