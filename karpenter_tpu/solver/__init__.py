"""The scheduling core (L4): the first-fit-decreasing oracle and the batched
TPU solver behind a common interface.

- `oracle`: sequential reference implementation replicating the Go scheduler
  (/root/reference/pkg/controllers/provisioning/scheduling/scheduler.go). It is
  the correctness referee for the TPU kernels and the CPU baseline for
  benchmarks.
- `topology`: topology-spread / pod-affinity / anti-affinity tracking.
- `tpu`: the batched JAX solver (see karpenter_tpu.ops for the kernels).
- `hybrid`: the HybridScheduler dispatch — TPU path with oracle fallback on
  UnsupportedBySolver; the entry point for controllers and benchmarks. Also
  the resilient sidecar boundary: ResilientSolver + CircuitBreaker
  (docs/resilience.md failure ladder).
- `buckets`/`aot`: pow-2 shape buckets outside jit + the ahead-of-time
  compile pipeline that persists the bucket ladder's executables
  (docs/compile.md).

Importing the package configures the persistent XLA compilation cache
exactly once (jaxsetup.ensure_compilation_cache) — every solver entry
point (TpuScheduler, the sweep kernels, graftlint --ir, the service)
reaches the device through this package, so this is THE call site; do
not re-add per-module calls.
"""

from karpenter_tpu.jaxsetup import ensure_compilation_cache

ensure_compilation_cache()

# jax.monitoring compile/retrace events -> karpenter_jax_compilation_
# events_total: every runtime solve surfaces backend compiles / cache
# hits as metrics, not only graftlint --ir runs (karpenter_tpu.tracing
# owns the shared listener; importing this package implies jax loads)
from karpenter_tpu.tracing import install_compile_listener

install_compile_listener()

from karpenter_tpu.solver.hybrid import (
    CircuitBreaker,
    HybridScheduler,
    ResilientSolver,
)
from karpenter_tpu.solver.oracle import Results, Scheduler, SchedulerOptions
from karpenter_tpu.solver.topology import Topology

__all__ = [
    "CircuitBreaker",
    "HybridScheduler",
    "ResilientSolver",
    "Results",
    "Scheduler",
    "SchedulerOptions",
    "Topology",
]
