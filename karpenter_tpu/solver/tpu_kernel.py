"""The jitted greedy-packing kernel: one `lax.scan` step per pod, all
candidate evaluation vectorized.

Reproduces the oracle's decision sequence exactly (scheduler.go:488 add):
existing nodes in fixed order, then in-flight claims in stable-sorted
(pod-count, attainment-order) rank, then a new claim from the first feasible
template in weight order. Candidate screens are exact for requirements,
taints, and topology; the instance-type dimension (nodeclaim.go:373
filterInstanceTypesByRequirements) is screened optimistically with a
per-claim elementwise-max allocatable bound and verified exactly — in rank
order — inside a while_loop, so the chosen target always equals the oracle's
first full-pass target.

Stable-rank bookkeeping: the oracle re-sorts in-flight claims by pod count
(stable) before every attempt. A claim whose count increments moves to the
front of its new count-block; a new claim enters at the front of the
count>=2 block boundary (i.e. end of the count-1 block). Both are O(N)
rank-vector updates — see _rank_after_increment / _rank_after_create.

Topology state is two count tensors: value-keyed groups count per vocab
value id ("zone family", [Gv, VMAX]); hostname groups count per node slot
([Gh, S], slots = existing nodes then claim slots), because a node IS its
hostname domain. Spread max-skew argmin, affinity viable-set, anti empty-set
and the inverse anti-affinity index mirror topologygroup.go:226-459 (with
ties determinized to sorted order on both sides).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from karpenter_tpu.ops.encode import Reqs
from karpenter_tpu.ops.kernels import (
    VocabArrays,
    compat,
    intersect,
    intersects_only,
    seg_any,
    seg_popcount,
)
from karpenter_tpu.solver.tpu_problem import (
    MAX_OWNED_TOPOLOGIES,
    TOPO_AFFINITY_H,
    TOPO_AFFINITY_V,
    TOPO_ANTI_H,
    TOPO_ANTI_V,
    TOPO_NONE,
    TOPO_SPREAD_H,
    TOPO_SPREAD_V,
)

INF_I = jnp.int32(1 << 30)
INF_F = jnp.float32(1 << 30)

KIND_EXISTING = 0
KIND_CLAIM = 1
KIND_NEW = 2
KIND_FAIL = 3

# relax-tier odometer bins: tier-loop trips at tier t land in bin
# min(t, ODO_TIER_BINS - 1) — the last bin aggregates deeper rungs so the
# counter block stays a fixed shape for every problem's ladder depth
ODO_TIER_BINS = 8


class Odometer(NamedTuple):
    """Device-truth counters carried through the kernels and returned
    alongside results (ISSUE 15). Strictly write-only inside the compiled
    program: no decision ever reads a counter, so enabling/fetching them
    cannot perturb parity (tests/test_tpu_parity.py odometer-inertness +
    the fuzz invariant catalog pin this). All int32 — the totals are
    bounded by pod counts x tier depth, far under 2^31.

    - ``steps``: device loop iterations executed — lax.scan steps on the
      scan path (pad positions included: padding costs real iterations),
      while-loop body trips on the runs path. THE number wave packing
      (ROADMAP item 1) must shrink; cross-checked against the IR tier's
      static ``scan_total_length`` budget by the perf smoke test.
    - ``bulk_steps``: runs-path bulk-phase trips (subset of ``steps``;
      always 0 on the scan path).
    - ``tier_steps``: relax tier-loop body trips (each trip runs one full
      ``_step``) — the work multiplier relaxable batches pay; 0 when the
      problem compiled the plain step.
    - ``tier_hist``: [ODO_TIER_BINS] tier-loop trips by tier index;
      sums to ``tier_steps``.
    """

    steps: jax.Array
    bulk_steps: jax.Array
    tier_steps: jax.Array
    tier_hist: jax.Array


def odometer_zero() -> Odometer:
    return Odometer(
        steps=jnp.zeros((), jnp.int32),
        bulk_steps=jnp.zeros((), jnp.int32),
        tier_steps=jnp.zeros((), jnp.int32),
        tier_hist=jnp.zeros(ODO_TIER_BINS, jnp.int32),
    )


def odo_tier_tick(odo: Odometer, tiers) -> Odometer:
    """Credit one pod's `tiers` tier-loop trips (trips at tier >=
    ODO_TIER_BINS-1 aggregate into the last bin)."""
    idx = jnp.arange(ODO_TIER_BINS, dtype=jnp.int32)
    last = ODO_TIER_BINS - 1
    inc = jnp.where(
        idx < last,
        (idx < tiers).astype(jnp.int32),
        jnp.maximum(tiers - last, 0),
    )
    return odo._replace(
        tier_steps=odo.tier_steps + tiers, tier_hist=odo.tier_hist + inc
    )


class Tables(NamedTuple):
    """Static (per-solve) device tensors."""

    va: VocabArrays
    # templates [T]
    treq: Reqs
    tdaemon: jax.Array  # [T, R]
    ttypes: jax.Array  # [T, IW] u32
    tlimit_def: jax.Array  # [T, R] bool
    thas_limits: jax.Array  # [T] bool
    # instance types [I]
    ireq: Reqs
    ialloc: jax.Array  # [I, R]
    icap: jax.Array  # [I, R]
    # offerings [O]; rows with ovalid=False are bucket padding
    # (solver/buckets.py) and must never witness "an offering exists"
    otype: jax.Array  # [O]
    oword: jax.Array  # [O, 3]
    obit: jax.Array  # [O, 3]
    ovalid: jax.Array  # [O] bool
    # reservation index per offering (-1 = not a reserved offering);
    # zero-length when the problem has no reservations — every reservation
    # op below is Python-gated on NRES so reservation-free programs are
    # byte-identical to before (round 5, reservationmanager.go:57-98)
    orid: jax.Array  # [O] i32
    # zone-family groups [Gv, VMAX]
    v_kid: jax.Array
    v_word: jax.Array
    v_bit: jax.Array
    v_reg: jax.Array
    v_skew: jax.Array
    v_mindom: jax.Array
    v_filt: jax.Array  # [Gv, 2]
    v_anti: jax.Array  # [Gv] bool
    # hostname-family groups [Gh]
    h_skew: jax.Array
    h_filt: jax.Array  # [Gh, 2]
    h_inverse: jax.Array  # [Gh] bool
    # node filters [F]
    filter_reqs: Reqs
    # template daemonset host-port seeds [T, HPW] (zero-width when the
    # problem has no host ports; every port op is Python-gated on HPW)
    thp: jax.Array
    # relaxation-tier tables per requirement class [NR, L, ...]
    # (preferences.go:38 ladder, precomputed host-side: tier 0 = the pod
    # as submitted, tier t = after t relax rungs; a pod's step attempts
    # tiers in order WITHIN its own evaluation — scheduler.go:434
    # trySchedule relaxes inline on a copy before other pods interleave)
    rt_preq: Reqs  # [NR, L, ...]
    rt_typeok: jax.Array  # [NR, L, IW] u32
    rt_tol_t: jax.Array  # [NR, L, T] bool
    rt_tol_e: jax.Array  # [NR, L, E] bool
    rt_kind: jax.Array  # [NR, L, C] i32
    rt_gid: jax.Array  # [NR, L, C] i32
    rt_sel: jax.Array  # [NR, L, C] bool


class State(NamedTuple):
    """Carried solver state."""

    # claims [N]
    active: jax.Array
    count: jax.Array
    rank: jax.Array
    tmpl: jax.Array
    creq: Reqs
    crequests: jax.Array  # [N, R]
    alive: jax.Array  # [N, IW] u32
    cmax_alloc: jax.Array  # [N, R]
    n_claims: jax.Array  # scalar i32
    # existing nodes [E]
    ereq: Reqs
    eavail: jax.Array  # [E, R]
    # per-template remaining limits [T, R]
    trem: jax.Array
    # topology counts
    v_cnt: jax.Array  # [Gv, VMAX]
    h_cnt: jax.Array  # [Gh, S]  S = E + N
    # reserved-capacity state (zero-width when NRES == 0):
    rescap: jax.Array  # [NRES] i32 remaining per reservation id
    held: jax.Array  # [N, NRESW] u32 bitmask of reservations each claim holds
    # host-port usage per slot [S, HPW] u32 (hostportusage.go:35; S = E+N)
    hp_used: jax.Array


class PodX(NamedTuple):
    """Per-pod scan inputs."""

    preq: Reqs
    prequests: jax.Array  # [R]
    typeok: jax.Array  # [IW] u32 — types whose reqs intersect the pod's
    tol_t: jax.Array  # [T]
    tol_e: jax.Array  # [E]
    topo_kind: jax.Array  # [C]
    topo_gid: jax.Array  # [C]
    topo_sel: jax.Array  # [C]
    sel_v: jax.Array  # [Gv]
    sel_h: jax.Array  # [Gh]
    inv_h: jax.Array  # [Gh]
    own_h: jax.Array  # [Gh]
    valid: jax.Array  # scalar bool
    # relaxation: this pod's row into Tables.rt_* (only meaningful when
    # ntiers > 1) and how many ladder tiers it has (1 = nothing to relax)
    rrow: jax.Array  # scalar i32
    ntiers: jax.Array  # scalar i32
    # host ports (tier-independent): own triple bits + conflict mask [HPW]
    hp_own: jax.Array
    hp_conf: jax.Array


def _row(r: Reqs, i) -> Reqs:
    return Reqs(*(a[i] for a in r))


def _reqs_where(c, a: Reqs, b: Reqs) -> Reqs:
    return Reqs(*(jnp.where(c[..., None], x, y) for x, y in zip(a, b)))


def _set_row(dst: Reqs, i, row: Reqs, pred) -> Reqs:
    return Reqs(
        *(
            a.at[i].set(jnp.where(pred, v, a[i]))
            for a, v in zip(dst, row)
        )
    )


def _gather_bits(mask: jax.Array, words: jax.Array, bits: jax.Array) -> jax.Array:
    """mask [..., TW], words/bits [G...]: -1 words gather False."""
    w = jnp.maximum(words, 0)
    got = (jnp.take(mask, w, axis=-1) >> bits.astype(jnp.uint32)) & jnp.uint32(1)
    return (got > 0) & (words >= 0)


def _unpack(words: jax.Array, n: int) -> jax.Array:
    """[IW] u32 -> [n] bool."""
    i = jnp.arange(n)
    return (words[i // 32] >> (i % 32).astype(jnp.uint32)) & jnp.uint32(1) > 0


def _pack(bits: jax.Array, nw: int) -> jax.Array:
    """[n] bool -> [nw] u32."""
    i = jnp.arange(bits.shape[0])
    vals = bits.astype(jnp.uint32) << (i % 32).astype(jnp.uint32)
    return jnp.zeros(nw, jnp.uint32).at[i // 32].add(vals)


# ---------------------------------------------------------------------------
# topology evaluation


class TopoEval(NamedTuple):
    viable: jax.Array  # [B]
    tight: jax.Array  # [B, TW] mask to AND in
    touched: jax.Array  # [K] keys tightened by zone-family constraints


def _eval_topology(
    merged: Reqs,  # [B, ...]
    slot_cnt_h: jax.Array,  # [Gh, B] hostname counts at each candidate's slot
    nonempty_h: jax.Array,  # [Gh] any nonzero count in the group row
    x: PodX,
    st: State,
    tb: Tables,
) -> TopoEval:
    B = merged.mask.shape[0]
    TW = merged.mask.shape[-1]
    Gv = tb.v_reg.shape[0]
    viable = jnp.ones(B, bool)
    tight = jnp.broadcast_to(tb.va.full_mask, (B, TW))
    touched = jnp.zeros(tb.va.well_known.shape[0], bool)

    # inverse anti-affinity applies to any selected pod (topology.go:528)
    inv_bad = jnp.any(x.inv_h[:, None] & (slot_cnt_h > 0), axis=0)
    viable &= ~inv_bad

    for c in range(x.topo_kind.shape[0]):  # sized to the problem's max
        kind = x.topo_kind[c]
        gid = x.topo_gid[c]
        selfsel = x.topo_sel[c].astype(jnp.int32)

        # ---- zone-family quantities (safe even when kind is hostname) ----
        gv = jnp.clip(gid, 0, max(Gv - 1, 0))
        words = tb.v_word[gv]
        bitsp = tb.v_bit[gv]
        reg = tb.v_reg[gv]
        cnt = st.v_cnt[gv]  # [VMAX] i32 — keep integer for exact compares
        skew = tb.v_skew[gv]
        # allowed-mask bits encode has() for concrete AND complement
        # requirements alike (complements have non-excluded vocab bits set),
        # and only vocab (registered) domains matter for counting
        node_bits = _gather_bits(merged.mask, words, bitsp)  # [B, VMAX]
        pod_dom = _gather_bits(x.preq.mask, words, bitsp)  # [VMAX]
        eff = cnt + selfsel

        vmax = words.shape[0]

        # spread (topologygroup.go:226): min over pod-supported registered
        # domains, candidates from node(merged) ∩ registered; pick the first
        # (lowest value id == sorted order) domain holding the minimum count
        # — all in exact int32
        sup = reg & pod_dom
        min_cnt = jnp.min(jnp.where(sup, cnt, INF_I))  # raw counts, no self-add
        n_sup = jnp.sum(sup.astype(jnp.int32))
        mindom = tb.v_mindom[gv]
        min_cnt = jnp.where((mindom >= 0) & (n_sup < mindom), 0, min_cnt)
        cand_s = node_bits & reg  # [B, VMAX]
        ok_s = cand_s & (eff - min_cnt <= skew)
        best_eff = jnp.min(jnp.where(ok_s, eff, INF_I), axis=-1, keepdims=True)
        spread_viable = jnp.any(ok_s, axis=-1)
        first = jnp.argmax(ok_s & (eff == best_eff), axis=-1)  # [B]
        spread_bits = (jnp.arange(vmax) == first[:, None]) & spread_viable[:, None]

        # affinity (topologygroup.go:313)
        pos = reg & (st.v_cnt[gv] > 0)
        aff_set = node_bits & pos & pod_dom  # [B, VMAX]
        aff_direct = jnp.any(aff_set, axis=-1)
        nonempty_total = jnp.any(pos)
        any_compat = jnp.any(pos & pod_dom)
        bootstrap = (selfsel > 0) & (~nonempty_total | ~any_compat)
        b_cand = reg & pod_dom & node_bits
        b_first = jnp.argmax(b_cand, axis=-1)
        b_ok = jnp.any(b_cand, axis=-1) & bootstrap
        b_bits = (jnp.arange(vmax) == b_first[:, None]) & b_ok[:, None]
        aff_viable = aff_direct | b_ok
        aff_bits = jnp.where(aff_direct[:, None], aff_set, b_bits)

        # anti (topologygroup.go:393): only empty registered domains
        anti_bits = reg & (st.v_cnt[gv] == 0) & node_bits & pod_dom
        anti_viable = jnp.any(anti_bits, axis=-1)

        # ---- hostname-family ----
        gh_cnt = slot_cnt_h[jnp.clip(gid, 0, slot_cnt_h.shape[0] - 1)]  # [B]
        h_skew = tb.h_skew[jnp.clip(gid, 0, tb.h_skew.shape[0] - 1)]
        h_ne = nonempty_h[jnp.clip(gid, 0, nonempty_h.shape[0] - 1)]
        hs_viable = gh_cnt + selfsel <= h_skew
        ha_viable = (gh_cnt > 0) | ((selfsel > 0) & ~h_ne)
        hanti_viable = gh_cnt == 0

        is_v = (kind == TOPO_SPREAD_V) | (kind == TOPO_AFFINITY_V) | (kind == TOPO_ANTI_V)
        c_viable = jnp.where(
            kind == TOPO_NONE,
            True,
            jnp.where(
                kind == TOPO_SPREAD_V,
                spread_viable,
                jnp.where(
                    kind == TOPO_AFFINITY_V,
                    aff_viable,
                    jnp.where(
                        kind == TOPO_ANTI_V,
                        anti_viable,
                        jnp.where(
                            kind == TOPO_SPREAD_H,
                            hs_viable,
                            jnp.where(kind == TOPO_AFFINITY_H, ha_viable, hanti_viable),
                        ),
                    ),
                ),
            ),
        )
        viable &= c_viable

        c_bits = jnp.where(
            kind == TOPO_SPREAD_V,
            spread_bits,
            jnp.where(kind == TOPO_AFFINITY_V, aff_bits, anti_bits),
        )  # [B, VMAX]
        # fold the allowed set into a [B, TW] word mask for the group's key
        kid = tb.v_kid[gv]
        in_seg = tb.va.word2key == kid  # [TW]
        vals = c_bits.astype(jnp.uint32) << bitsp.astype(jnp.uint32)
        delta = (
            jnp.zeros((B, TW), jnp.uint32)
            .at[:, jnp.maximum(words, 0)]
            .add(jnp.where(words >= 0, vals, 0))
        )
        seg_tight = jnp.where(in_seg & is_v, delta, jnp.uint32(0xFFFFFFFF))
        tight = tight & seg_tight
        touched = touched | (
            is_v & (jnp.arange(touched.shape[0]) == kid)
        )

    return TopoEval(viable=viable, tight=tight, touched=touched)


def _apply_tighten(merged: Reqs, te_tight: jax.Array, touched: jax.Array, va: VocabArrays) -> Reqs:
    """Intersect merged reqs with the topology domain choices (an In set per
    touched key): concrete result, defined, no bounds change."""
    touched_w = touched[..., va.word2key]
    return Reqs(
        mask=merged.mask & te_tight,
        exmask=jnp.where(touched_w, jnp.uint32(0), merged.exmask),
        other=merged.other & ~touched,
        notin=merged.notin & ~touched,
        defined=merged.defined | touched,
        gt=merged.gt,
        lt=merged.lt,
        minv=merged.minv,
    )


def _topo_nonempty_ok(final: Reqs, touched: jax.Array, va: VocabArrays) -> jax.Array:
    """The oracle's post-tighten Compatible check: every touched key must
    keep a nonempty allowed set (scheduler nodeclaim.go:147)."""
    seg = seg_any(final.mask != 0, va)
    return ~jnp.any(touched & ~seg, axis=-1)


# ---------------------------------------------------------------------------
# instance-type exact filtering


def _type_filter(
    final: Reqs,  # single row
    alive_bits: jax.Array,  # [I] bool
    total: jax.Array,  # [R]
    tb: Tables,
) -> jax.Array:
    """[I] bool — compat ∧ fits ∧ offering ∧ (alive), nodeclaim.go:373."""
    t_ok = intersects_only(tb.ireq, _broadcast_row(final, tb.ireq.mask.shape[0]), tb.va)
    fits = jnp.all(total <= tb.ialloc, axis=-1)
    ow = tb.oword
    off_bit = _gather_bits(final.mask, ow, tb.obit)  # [O, 3]
    off_ok = jnp.all(off_bit | (ow < 0), axis=-1) & tb.ovalid
    off_any = jnp.zeros(tb.ireq.mask.shape[0], bool).at[tb.otype].max(off_ok)
    return alive_bits & t_ok & fits & off_any


def _broadcast_row(r: Reqs, n: int) -> Reqs:
    return Reqs(*(jnp.broadcast_to(a, (n,) + a.shape) for a in r))


def _min_values_ok(final: Reqs, final_i: jax.Array, tb: Tables) -> jax.Array:
    # SatisfiesMinValues unions `requirement.values` per key (types.py:188):
    # concrete rows contribute their allowed set, complements their *excluded*
    # set, and undefined keys nothing — never the full Exists mask
    src = jnp.where(
        tb.ireq.other[..., tb.va.word2key], tb.ireq.exmask, tb.ireq.mask
    )
    src = jnp.where(tb.ireq.defined[..., tb.va.word2key], src, jnp.uint32(0))
    # bitwise-or across the type axis, expressed as unpack -> any -> repack:
    # an any-reduce lowers to a collective when the type axis is sharded
    # (a raw u32-or reduction does not). The [I, TW, 32] bool intermediate
    # is ~0.5MB at 512 types — negligible next to the per-step latency
    # floor, and minValues problems route through here rarely
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = ((src[..., None] >> shifts) & jnp.uint32(1)).astype(bool)  # [I, TW, 32]
    union_bits = jnp.any(bits & final_i[:, None, None], axis=0)  # [TW, 32]
    union = jnp.sum(
        union_bits.astype(jnp.uint32) * (jnp.uint32(1) << shifts)[None, :],
        axis=-1,
        dtype=jnp.uint32,
    )
    counts = seg_popcount(union, tb.va)
    return jnp.all((final.minv < 0) | (counts >= final.minv))


# ---------------------------------------------------------------------------
# stable-rank updates


def _rank_after_increment(st: State, j: jax.Array) -> tuple[jax.Array, jax.Array]:
    cnew = st.count[j] + 1
    idx = jnp.arange(st.rank.shape[0])
    geq = st.active & (st.count >= cnew) & (idx != j)
    boundary = jnp.minimum(jnp.min(jnp.where(geq, st.rank, INF_I)), st.n_claims)
    rank = st.rank - ((st.rank > st.rank[j]) & (st.rank < boundary)).astype(jnp.int32)
    rank = rank.at[j].set(boundary - 1)
    return rank, cnew


def _rank_after_create(st: State, m: jax.Array) -> jax.Array:
    geq2 = st.active & (st.count >= 2)
    boundary = jnp.minimum(jnp.min(jnp.where(geq2, st.rank, INF_I)), st.n_claims)
    rank = st.rank + (st.active & (st.rank >= boundary)).astype(jnp.int32)
    return rank.at[m].set(boundary)


# ---------------------------------------------------------------------------
# record (topology.go:197 Record)


def _eval_filters(filt: jax.Array, final: Reqs, tb: Tables, allow_wk) -> jax.Array:
    """[G] bool — node_filter.matches(final reqs) over <=2 alternatives."""
    G = filt.shape[0]
    if tb.filter_reqs.mask.shape[0] == 0:
        return jnp.ones(G, bool)
    ok = jnp.zeros(G, bool)
    trivial = jnp.all(filt < 0, axis=-1)
    for a in range(filt.shape[1]):
        alt = filt[:, a]
        rows = _row(tb.filter_reqs, jnp.clip(alt, 0, None))
        final_b = _broadcast_row(final, G)
        got_strict = compat(final_b, rows, tb.va, False)
        got_allow = compat(final_b, rows, tb.va, True)
        got = jnp.where(allow_wk, got_allow, got_strict)
        ok |= (alt >= 0) & got
    return trivial | ok


def _record(
    st_v_cnt: jax.Array,
    st_h_cnt: jax.Array,
    final: Reqs,
    slot_global: jax.Array,
    allow_wk: jax.Array,
    pred: jax.Array,
    x: PodX,
    tb: Tables,
) -> tuple[jax.Array, jax.Array]:
    # zone-family
    segbits = _gather_bits(final.mask, tb.v_word, tb.v_bit)  # [Gv, VMAX]
    exbits = _gather_bits(final.exmask, tb.v_word, tb.v_bit)
    other_k = final.other[jnp.clip(tb.v_kid, 0, None)]  # [Gv]
    popc = jnp.sum(segbits.astype(jnp.int32), axis=-1)
    single = (popc == 1) & ~other_k
    filt_ok = _eval_filters(tb.v_filt, final, tb, allow_wk)
    add = jnp.where(
        tb.v_anti[:, None],
        jnp.where(other_k[:, None], exbits, segbits),
        segbits & single[:, None],
    )
    gate_v = (pred & x.sel_v & filt_ok)[:, None]
    v_cnt = st_v_cnt + (add & gate_v).astype(jnp.int32)

    # hostname-family: forward groups count when selected + filter-matched;
    # inverse groups count for their owners (topology.go:297)
    filt_ok_h = _eval_filters(tb.h_filt, final, tb, allow_wk)
    contrib = jnp.where(tb.h_inverse, x.own_h, x.sel_h & filt_ok_h)
    h_cnt = st_h_cnt.at[:, slot_global].add((pred & contrib).astype(jnp.int32))
    return v_cnt, h_cnt


# ---------------------------------------------------------------------------
# the scan step


def _step(tb: Tables, st: State, x: PodX):
    E = st.eavail.shape[0]
    N = st.active.shape[0]
    T = tb.tdaemon.shape[0]
    I = tb.ialloc.shape[0]
    IW = st.alive.shape[1]
    HPW = st.hp_used.shape[1]

    nonempty_h = jnp.any(st.h_cnt > 0, axis=-1)  # [Gh]

    # ======== existing nodes (exact, fixed order) ========
    if E > 0:
        merged_e = intersect(st.ereq, _broadcast_row(x.preq, E), tb.va)
        compat_e = compat(st.ereq, _broadcast_row(x.preq, E), tb.va, False)
        fits_e = jnp.all(st.eavail >= 0, axis=-1) & jnp.all(
            x.prequests <= st.eavail, axis=-1
        )
        te_e = _eval_topology(merged_e, st.h_cnt[:, :E], nonempty_h, x, st, tb)
        final_e = _apply_tighten(merged_e, te_e.tight, te_e.touched, tb.va)
        cand_e = (
            x.tol_e
            & compat_e
            & fits_e
            & te_e.viable
            & _topo_nonempty_ok(final_e, te_e.touched, tb.va)
        )
        if HPW:  # host-port conflict screen (hostportusage.go:35)
            cand_e &= ~jnp.any(
                (x.hp_conf[None, :] & st.hp_used[:E]) != 0, axis=-1
            )
        found_e = jnp.any(cand_e) & x.valid
        slot_e = jnp.argmin(jnp.where(cand_e, jnp.arange(E), INF_I))
    else:
        found_e = jnp.zeros((), bool)
        slot_e = jnp.int32(0)
        final_e = None
        te_e = None

    # ======== in-flight claims (screen + exact loop in rank order) ========
    merged_c = intersect(st.creq, _broadcast_row(x.preq, N), tb.va)
    compat_c = compat(st.creq, _broadcast_row(x.preq, N), tb.va, True)
    te_c = _eval_topology(merged_c, st.h_cnt[:, E:], nonempty_h, x, st, tb)
    final_c = _apply_tighten(merged_c, te_c.tight, te_c.touched, tb.va)
    screen_fits = jnp.all(
        st.crequests + x.prequests <= st.cmax_alloc, axis=-1
    )
    # pod-vs-type pairwise compat screen: a claim with no surviving type the
    # pod could ever use is never a candidate — keeps the exact while_loop
    # below at ~1 iteration (the residual gap is three-way intersections,
    # offerings, and minValues, which the loop still verifies)
    screen_types = jnp.any((st.alive & x.typeok) != 0, axis=-1)
    cand_c = (
        st.active
        & x.tol_t[jnp.clip(st.tmpl, 0, max(T - 1, 0))]
        & compat_c
        & te_c.viable
        & _topo_nonempty_ok(final_c, te_c.touched, tb.va)
        & screen_fits
        & screen_types
    )
    if HPW:
        cand_c &= ~jnp.any(
            (x.hp_conf[None, :] & st.hp_used[E:]) != 0, axis=-1
        )

    def loop_cond(carry):
        done, excluded, _ = carry
        return ~done & jnp.any(cand_c & ~excluded)

    def loop_body(carry):
        done, excluded, _ = carry
        live = cand_c & ~excluded
        n = jnp.argmin(jnp.where(live, st.rank, INF_I))
        final_n = _row(final_c, n)
        alive_n = _unpack(st.alive[n], I)
        total = st.crequests[n] + x.prequests
        final_i = _type_filter(final_n, alive_n, total, tb)
        ok = jnp.any(final_i) & _min_values_ok(final_n, final_i, tb)
        return ok, excluded.at[n].set(~ok), jnp.where(ok, n, 0)

    init = (jnp.zeros((), bool) | found_e | ~x.valid, jnp.zeros(N, bool), jnp.int32(0))
    found_c, _, slot_c = jax.lax.while_loop(loop_cond, loop_body, init)
    found_c = found_c & ~found_e & x.valid

    # ======== new claim from a template (exact, weight order) ========
    # only evaluated when nothing earlier accepted the pod (the common case
    # at steady state is a claim hit, so skip the [T, I] filter work)
    need_new = ~found_e & ~found_c & x.valid

    def template_branch(_):
        merged_t = intersect(tb.treq, _broadcast_row(x.preq, T), tb.va)
        compat_t = compat(tb.treq, _broadcast_row(x.preq, T), tb.va, True)
        # a fresh claim's hostname counts are always zero (records only ever
        # target committed slots < n_claims); reading h_cnt at E+n_claims
        # would clamp at the array edge when slots are exhausted and corrupt
        # the overflow signal below
        te_t = _eval_topology(
            merged_t,
            jnp.zeros((st.h_cnt.shape[0], T), st.h_cnt.dtype),
            nonempty_h,
            x,
            st,
            tb,
        )
        final_t = _apply_tighten(merged_t, te_t.tight, te_t.touched, tb.va)
        # limits filter (scheduler.go:851) then exact type filter per template
        lim_ok = jnp.all(
            ~tb.tlimit_def[:, None, :] | (tb.icap[None, :, :] <= st.trem[:, None, :]),
            axis=-1,
        )  # [T, I]
        tmember = jax.vmap(lambda w: _unpack(w, I))(tb.ttypes)  # [T, I]
        talive = tmember & (lim_ok | ~tb.thas_limits[:, None])
        totals = tb.tdaemon + x.prequests  # [T, R]
        t_final_i = jax.vmap(
            lambda f, a, tot: _type_filter(f, a, tot, tb), in_axes=(0, 0, 0)
        )(final_t, talive, totals)
        t_minok = jax.vmap(lambda f, fi: _min_values_ok(f, fi, tb))(final_t, t_final_i)
        viable_nogate = (
            compat_t
            & te_t.viable
            & _topo_nonempty_ok(final_t, te_t.touched, tb.va)
            & x.tol_t
            & jnp.any(t_final_i, axis=-1)
            & t_minok
        )
        if HPW:  # pod ports vs the template's daemonset ports
            viable_nogate &= ~jnp.any((x.hp_conf[None, :] & tb.thp) != 0, axis=-1)
        viable_t = viable_nogate & (st.n_claims < N)
        slot = jnp.argmin(jnp.where(viable_t, jnp.arange(T), INF_I))
        # a viable template exists but every claim slot is taken: the host
        # must re-solve with more slots (adaptive-N overflow signal)
        overflow = jnp.any(viable_nogate) & ~jnp.any(viable_t)
        return jnp.any(viable_t), slot, _row(final_t, slot), t_final_i[slot], overflow

    def no_template(_):
        zero_req = jax.tree.map(
            lambda a: jnp.zeros(a.shape[1:], a.dtype), tb.treq
        )
        return (
            jnp.zeros((), bool),
            jnp.int32(0),
            zero_req,
            jnp.zeros(I, bool),
            jnp.zeros((), bool),
        )

    found_t, slot_t, final_tn, alive_tn, overflow = jax.lax.cond(
        need_new, template_branch, no_template, None
    )
    found_t = found_t & need_new
    overflow = overflow & need_new

    kind = jnp.where(
        found_e,
        KIND_EXISTING,
        jnp.where(found_c, KIND_CLAIM, jnp.where(found_t, KIND_NEW, KIND_FAIL)),
    )

    # ======== apply updates ========
    # --- existing ---
    if E > 0:
        pe = found_e
        eavail = st.eavail.at[slot_e].add(
            jnp.where(pe, -x.prequests, jnp.zeros_like(x.prequests))
        )
        ereq = _set_row(st.ereq, slot_e, _row(final_e, slot_e), pe)
    else:
        eavail, ereq = st.eavail, st.ereq

    # --- claim add ---
    pc = found_c
    final_cn = _row(final_c, slot_c)
    alive_cn = _type_filter(
        final_cn,
        _unpack(st.alive[slot_c], I),
        st.crequests[slot_c] + x.prequests,
        tb,
    )
    rank_inc, cnew = _rank_after_increment(st, slot_c)
    creq = _set_row(st.creq, slot_c, final_cn, pc)
    crequests = st.crequests.at[slot_c].add(
        jnp.where(pc, x.prequests, jnp.zeros_like(x.prequests))
    )
    alive = st.alive.at[slot_c].set(
        jnp.where(pc, _pack(alive_cn, IW), st.alive[slot_c])
    )
    new_max_c = jnp.max(
        jnp.where(alive_cn[:, None], tb.ialloc, -INF_I), axis=0
    )
    cmax_alloc = st.cmax_alloc.at[slot_c].set(
        jnp.where(pc, new_max_c, st.cmax_alloc[slot_c])
    )
    count = st.count.at[slot_c].set(jnp.where(pc, cnew, st.count[slot_c]))
    rank = jnp.where(pc, rank_inc, st.rank)

    # --- new claim ---
    pn = found_t
    m = st.n_claims
    creq = _set_row(creq, m, final_tn, pn)
    crequests = crequests.at[m].set(
        jnp.where(pn, tb.tdaemon[slot_t] + x.prequests, crequests[m])
    )
    alive = alive.at[m].set(jnp.where(pn, _pack(alive_tn, IW), alive[m]))
    new_max_t = jnp.max(jnp.where(alive_tn[:, None], tb.ialloc, -INF_I), axis=0)
    cmax_alloc = cmax_alloc.at[m].set(jnp.where(pn, new_max_t, cmax_alloc[m]))
    count = count.at[m].set(jnp.where(pn, 1, count[m]))
    rank = jnp.where(pn, _rank_after_create(st, m), rank)
    active = st.active.at[m].set(jnp.where(pn, True, st.active[m]))
    tmpl = st.tmpl.at[m].set(jnp.where(pn, slot_t, st.tmpl[m]))
    n_claims = st.n_claims + pn.astype(jnp.int32)
    # subtractMax (scheduler.go:831) on the chosen template's pool limits
    max_cap = jnp.max(jnp.where(alive_tn[:, None], tb.icap, 0), axis=0)
    trem = st.trem.at[slot_t].add(
        jnp.where(
            pn & tb.thas_limits[slot_t],
            -jnp.where(tb.tlimit_def[slot_t], max_cap, 0),
            jnp.zeros_like(max_cap),
        )
    )

    # --- reservation bookkeeping (reservationmanager.go:57-98) ---
    # Non-strict semantics: the committed claim's reserved-offering set is
    # recomputed from its final requirements + surviving types; newly-held
    # reservations consume capacity, dropped ones release it (idempotent
    # per claim — the bitmask IS the per-hostname held set). Python-gated
    # on NRES so reservation-free programs compile unchanged.
    NRES = st.rescap.shape[0]
    if NRES:
        upd_r = pc | pn
        slot_r = jnp.where(pc, slot_c, m)
        final_r = _reqs_where(pc, final_cn, final_tn)
        alive_r = jnp.where(pc, alive_cn, alive_tn)  # [I] bool
        alive_o = alive_r[jnp.clip(tb.otype, 0, None)]
        offb = _gather_bits(final_r.mask, tb.oword, tb.obit)  # [O, 3]
        off_ok = jnp.all(offb | (tb.oword < 0), axis=-1) & tb.ovalid
        cand_o = alive_o & off_ok & (tb.orid >= 0)
        cand_r = (
            jnp.zeros(NRES, bool).at[jnp.clip(tb.orid, 0, None)].max(cand_o)
        )
        NRESW = st.held.shape[1]
        held_old = _unpack(st.held[slot_r], NRES)
        new_held = cand_r & (held_old | (st.rescap > 0))
        delta = new_held.astype(jnp.int32) - held_old.astype(jnp.int32)
        rescap = jnp.where(upd_r, st.rescap - delta, st.rescap)
        held = st.held.at[slot_r].set(
            jnp.where(upd_r, _pack(new_held, NRESW), st.held[slot_r])
        )
    else:
        rescap, held = st.rescap, st.held

    # --- topology record ---
    if E > 0:
        final_rec = _reqs_where(
            kind == KIND_EXISTING,
            _row(final_e, slot_e),
            _reqs_where(kind == KIND_CLAIM, final_cn, final_tn),
        )
    else:
        final_rec = _reqs_where(kind == KIND_CLAIM, final_cn, final_tn)
    slot_global = jnp.where(
        kind == KIND_EXISTING, slot_e, jnp.where(kind == KIND_CLAIM, E + slot_c, E + m)
    )
    allow_wk = kind != KIND_EXISTING
    pred = (kind != KIND_FAIL) & x.valid
    v_cnt, h_cnt = _record(
        st.v_cnt, st.h_cnt, final_rec, slot_global, allow_wk, pred, x, tb
    )
    if HPW:
        # record host-port usage on the chosen slot; a fresh claim also
        # inherits its template's daemonset ports
        hp_add = x.hp_own | jnp.where(
            kind == KIND_NEW,
            tb.thp[jnp.clip(slot_t, 0, max(T - 1, 0))],
            jnp.zeros(HPW, jnp.uint32),
        )
        hp_used = st.hp_used.at[slot_global].set(
            jnp.where(
                pred,
                st.hp_used[slot_global] | hp_add,
                st.hp_used[slot_global],
            )
        )
    else:
        hp_used = st.hp_used

    new_state = State(
        active=active,
        count=count,
        rank=rank,
        tmpl=tmpl,
        creq=creq,
        crequests=crequests,
        alive=alive,
        cmax_alloc=cmax_alloc,
        n_claims=n_claims,
        ereq=ereq,
        eavail=eavail,
        trem=trem,
        v_cnt=v_cnt,
        h_cnt=h_cnt,
        rescap=rescap,
        held=held,
        hp_used=hp_used,
    )
    out_slot = jnp.where(
        kind == KIND_EXISTING,
        slot_e,
        jnp.where(kind == KIND_CLAIM, slot_c, jnp.where(kind == KIND_NEW, m, -1)),
    )
    return new_state, (kind, out_slot, overflow)


def _x_at_tier(tb: Tables, x: PodX, t) -> PodX:
    """The pod's PodX with tier-t requirement-class rows substituted where
    the pod HAS tiers (requests, selection, inverse rows are
    tier-independent). Single-tier pods keep their own rows — their rrow
    is a placeholder and must never be dereferenced as truth; the selects
    below are cheap (per-row gathers) next to the step's [N, TW]
    candidate screens."""
    ri = x.rrow
    has = x.ntiers > 1

    def sel(tier_val, own_val):
        return jnp.where(has, tier_val, own_val)

    return x._replace(
        preq=Reqs(
            *(sel(a[ri, t], b) for a, b in zip(tb.rt_preq, x.preq))
        ),
        typeok=sel(tb.rt_typeok[ri, t], x.typeok),
        tol_t=sel(tb.rt_tol_t[ri, t], x.tol_t),
        tol_e=sel(tb.rt_tol_e[ri, t], x.tol_e),
        topo_kind=sel(tb.rt_kind[ri, t], x.topo_kind),
        topo_gid=sel(tb.rt_gid[ri, t], x.topo_gid),
        topo_sel=sel(tb.rt_sel[ri, t], x.topo_sel),
    )


def _step_relax(tb: Tables, st: State, x: PodX):
    """scheduler.go:434 trySchedule: a pod attempts its relaxation tiers
    IN ORDER within its own step (the reference relaxes inline on a copy
    until the pod schedules or the ladder is exhausted — no other pod
    interleaves between tiers). ONE while_loop for every pod — a
    single-tier pod runs the body exactly once on its own rows — so the
    compiled program contains a single _step instance (the former
    cond(plain, tiers) duplicated the whole step and taxed mixed batches
    with a branch per pod; VERDICT r4 #1).

    Returns (state, out, tiers): `tiers` is the number of tier-loop body
    trips this pod took — odometer food only (the drivers fold it into
    Odometer.tier_hist); it is the loop's own counter, never a new
    carry, so the budgeted carry bytes are unchanged."""

    def cond(c):
        t, done, _, _ = c
        return (~done) & (t < x.ntiers)

    def body(c):
        t, _, _, _ = c
        st2, out = _step(tb, st, _x_at_tier(tb, x, t))
        kind, _, over = out
        done = (kind != KIND_FAIL) | over | ~x.valid
        return (t + 1, done, st2, out)

    dummy = (jnp.int32(KIND_FAIL), jnp.int32(-1), jnp.zeros((), bool))
    tiers, _, st2, out = jax.lax.while_loop(
        cond, body, (jnp.int32(0), jnp.zeros((), bool), st, dummy)
    )
    return st2, out, tiers


@functools.partial(jax.jit, static_argnames=("relax",))
def solve_scan(tb: Tables, st: State, xs: PodX, relax: bool = True):
    """Run the greedy pack over a pod batch; returns
    (state, kinds, slots, overflowed, odometer) — overflowed means some
    pod failed only because claim slots ran out (host should grow N and
    re-solve); `odometer` is this dispatch's device-truth counter block
    (write-only: decisions never read it, so it is parity-inert).

    `relax` is trace-time static: problems with no relaxable requirement
    classes (every ntiers == 1) compile the plain `_step` with no tier
    loop or branch — byte-equivalent to the pre-relaxation program (plus
    the inert odometer carry), so preference-free workloads pay nothing
    for the ladder machinery."""

    def step(carry, x):
        st, odo = carry
        if relax:
            st2, out, tiers = _step_relax(tb, st, x)
            odo = odo_tier_tick(odo, tiers)
        else:
            st2, out = _step(tb, st, x)
        odo = odo._replace(steps=odo.steps + 1)
        return (st2, odo), out

    (st, odo), (kinds, slots, overflow) = jax.lax.scan(
        step, (st, odometer_zero()), xs
    )
    return st, kinds, slots, jnp.any(overflow), odo
