"""Ahead-of-time compilation of the bucket ladder (docs/compile.md).

The shape buckets (solver/buckets.py) make the set of compiled programs a
finite, enumerable artifact; this module enumerates it. For each (entry
point, bucket rung, relax) combination it `.lower(...).compile()`s the
jitted kernel against a representative problem padded to the rung —
compilation WITHOUT execution — so every executable lands in the
persistent compilation cache (jaxsetup.ensure_compilation_cache) and a
fresh process warms from disk in seconds instead of paying the 25-57s
compile wall (BENCH_r03-r05) at traffic time.

Three consumers:

- `SolverServer(prewarm=True)` runs `prewarm()` on a background thread
  before reporting ready (solver/service.py); requests that arrive
  mid-prewarm degrade to the oracle fallback, never an uncompiled device
  path.
- `bench.py --cold` measures process-start -> first-solve against a warm
  vs cold disk cache.
- tests/test_service_faults.py kills a prewarm mid-flight and asserts the
  on-disk cache stays usable (every write here is temp-file + atomic
  rename; JAX's own cache entries are written the same way).

The ladder manifest (`aot_manifest.json` next to the cache) records every
compiled combo with its bucket signature and compile seconds, so warm-
from-disk is observable (readiness logs, tests) rather than anecdotal.

The representative problems are the same families the graftlint IR tier
budgets (analysis/ir.py): a generic zero-preference mix (compiles the
plain step) and a mixed relaxable batch (compiles the tier ladder). A
deployment whose workload departs from these families pays a one-time
compile for its own shapes — which the persistent cache then holds; pass
a workload-shaped `problem_fn` to cover it up front.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Callable, Optional

from karpenter_tpu import logging as klog
from karpenter_tpu import metrics
from karpenter_tpu.solver import buckets

MANIFEST_NAME = "aot_manifest.json"
MANIFEST_VERSION = 1

log = klog.root.named("solver.aot")

PREWARM_PROGRAMS = metrics.REGISTRY.counter(
    "karpenter_solver_prewarm_programs_total",
    "AOT-compiled programs per entry point (solver/aot.py prewarm)",
    ("entry",),
)
PREWARM_READY = metrics.REGISTRY.gauge(
    "karpenter_solver_prewarm_ready",
    "1 once the prewarm ladder is fully compiled (0 while compiling)",
)
PREWARM_SECONDS = metrics.REGISTRY.histogram(
    "karpenter_solver_prewarm_duration_seconds",
    "wall-clock seconds of one full prewarm ladder",
)


def manifest_path(cache_dir: str) -> str:
    return os.path.join(cache_dir, MANIFEST_NAME)


# cost_analysis keys worth cataloging (the rest are per-op utilization
# breakdowns whose naming churns across XLA versions)
_COST_KEYS = ("flops", "bytes accessed", "transcendentals", "optimal_seconds")
# memory_analysis attributes (XLA HLO buffer assignment totals) — the
# capacity-planning numbers ROADMAP item 4 needs: how much HBM one
# compiled program's arguments/outputs/temps pin per mesh shard
_MEMORY_ATTRS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def _cost_blocks(compiled) -> tuple[dict, dict]:
    """(cost, memory) dicts from one compiled executable. Best-effort by
    design: cost_analysis()/memory_analysis() are backend-dependent (a
    backend without them yields empty blocks, never a failed prewarm)."""
    cost: dict = {}
    memory: dict = {}
    try:
        ca = compiled.cost_analysis()
        # older jax returns [dict] per computation; newer returns a dict
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        for k in _COST_KEYS:
            v = (ca or {}).get(k)
            if isinstance(v, (int, float)):
                cost[k.replace(" ", "_")] = float(v)
    except Exception:  # pragma: no cover - backend-dependent surface
        pass
    try:
        ma = compiled.memory_analysis()
        for attr in _MEMORY_ATTRS:
            v = getattr(ma, attr, None)
            if isinstance(v, (int, float)):
                memory[attr] = int(v)
    except Exception:  # pragma: no cover - backend-dependent surface
        pass
    return cost, memory


def program_catalog(cache_dir: Optional[str] = None) -> dict:
    """The compiled-program cost catalog `/debug/programs` serves: every
    AOT-prewarmed (entry x rung x relax) combo with its bucket signature,
    compile seconds, and the XLA cost/memory analysis captured at compile
    time (flops / bytes accessed / argument+output+temp HBM bytes) —
    the capacity-planning input for sizing a mesh (ROADMAP item 4).
    Reads the manifest only; never compiles, never imports jax when the
    cache is already configured."""
    if cache_dir is None:
        from karpenter_tpu.jaxsetup import ensure_compilation_cache

        cache_dir = ensure_compilation_cache()
    manifest = load_manifest(cache_dir)
    return {
        "cache_dir": cache_dir,
        "jax": manifest.get("jax"),
        "backend": manifest.get("backend"),
        "programs": manifest.get("combos", {}),
    }


def load_manifest(cache_dir: Optional[str]) -> dict:
    """The ladder manifest, or an empty shell when absent/corrupt (a
    half-written manifest from a killed prewarm must read as 'nothing
    recorded', never poison the next process)."""
    shell = {"version": MANIFEST_VERSION, "combos": {}}
    if not cache_dir:
        return shell
    try:
        with open(manifest_path(cache_dir), encoding="utf-8") as f:
            data = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return shell
    if data.get("version") != MANIFEST_VERSION:
        return shell
    data.setdefault("combos", {})
    return data


def _write_manifest(cache_dir: str, data: dict) -> None:
    """Atomic write (temp + rename in the same directory): a kill at any
    instant leaves either the old manifest or the new one, never a torn
    file."""
    fd, tmp = tempfile.mkstemp(
        prefix=".aot_manifest.", dir=cache_dir, text=True
    )
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=True)
        os.replace(tmp, manifest_path(cache_dir))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _representative(kind: str, n_existing: int = 3):
    """(sched, problem, order) for one representative family — the same
    construction the IR tier traces (analysis/ir.py _make_sched), so the
    prewarmed programs are the budgeted ones."""
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.solver.tpu import (
        TpuScheduler,
        _bulk_class_flags,
        _bulk_gates,
    )
    from karpenter_tpu.solver.tpu_problem import encode_problem
    from karpenter_tpu.testing import fixtures

    fixtures.reset_rng(7)
    its = construct_instance_types(sizes=[2])
    pool = fixtures.node_pool(name="default")
    if kind == "generic":
        pods = fixtures.make_generic_pods(6)
    else:
        pods = fixtures.make_generic_pods(3) + fixtures.make_preference_pods(3)
    views = None
    if n_existing:
        from karpenter_tpu.api import labels as well_known
        from karpenter_tpu.solver.nodes import StateNodeView

        it = its[0]
        views = [
            StateNodeView(
                name=f"aot-existing-{i}",
                node_labels={well_known.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-a"},
                labels={
                    well_known.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-a",
                    well_known.INSTANCE_TYPE_LABEL_KEY: it.name,
                    well_known.NODEPOOL_LABEL_KEY: "default",
                },
                available=dict(it.allocatable()),
                capacity=dict(it.capacity),
                initialized=True,
            )
            for i in range(n_existing)
        ]
    topo = Topology([pool], {"default": its}, pods, state_node_views=views)
    sched = TpuScheduler([pool], {"default": its}, topo, views)
    problem = encode_problem(sched.oracle, pods)
    tb = sched._tables(problem)
    sched._upload_pod_tables(problem)
    order = sched._order_pods(problem)
    gates_ok = _bulk_gates(problem, strict_types=False)
    sched._bulk_flags_c = _bulk_class_flags(problem, gates_ok)
    sched._set_runflags_dev()
    return sched, problem, order, tb


def claim_rungs(P: int, claim_slot_div: int = 16) -> tuple[int, int]:
    """(N_runs, N_scan) — the claim-slot buckets TpuScheduler.solve pairs
    with a pod rung of P (keep in lockstep with solve()'s N formula)."""
    runs = min(
        buckets.bucket(max(64, (P + claim_slot_div - 1) // claim_slot_div)),
        buckets.bucket(P),
    )
    scan_div = min(claim_slot_div, 4)
    scan = min(
        buckets.bucket(max(64, (P + scan_div - 1) // scan_div)),
        buckets.bucket(P),
    )
    return runs, scan


def prewarm(
    max_pods: int = 1024,
    min_pods: int = 64,
    include_sweeps: bool = True,
    include_fleet: bool = False,
    fleet_lane_buckets: tuple[int, ...] = (2, 8),
    stop: Optional[threading.Event] = None,
    progress: Optional[Callable[[str, float], None]] = None,
) -> dict:
    """Compile the bucket ladder into the persistent cache; returns a
    summary {"compiled": n, "skipped": n, "seconds": s, "combos": {...}}.

    Interruption-safe: `stop` is polled between combos, every manifest
    write is atomic, and each compiled executable was already durably
    written by JAX's own cache before the manifest mentions it — a kill
    at any point loses at most the in-flight combo.
    """
    from karpenter_tpu.jaxsetup import ensure_compilation_cache

    cache_dir = ensure_compilation_cache()
    import jax

    from karpenter_tpu.solver import tpu_kernel as K
    from karpenter_tpu.solver import tpu_runs as KR

    t0 = time.monotonic()
    manifest = load_manifest(cache_dir)
    # combos recorded by a previous process are skippable only if they
    # were compiled by the same jax/backend into the same cache (the
    # manifest lives INSIDE the cache dir, so a wiped cache also wipes
    # the record — a stale manifest over an empty cache cannot happen
    # through normal cache resets)
    reusable = (
        frozenset(manifest["combos"])
        if cache_dir
        and manifest.get("jax") == jax.__version__
        and manifest.get("backend") == jax.default_backend()
        else frozenset()
    )
    manifest["jax"] = jax.__version__
    manifest["backend"] = jax.default_backend()
    combos: dict[str, dict] = manifest["combos"]
    compiled = skipped = 0
    PREWARM_READY.set(0.0)

    def record(name: str, sig, seconds: float, exe=None) -> None:
        entry = {
            "signature": [list(x) for x in sig],
            "seconds": round(seconds, 3),
        }
        # cost catalog (ISSUE 15): flops/bytes/HBM per compiled program,
        # captured at the only moment the executable object is in hand
        cost, memory = _cost_blocks(exe) if exe is not None else ({}, {})
        entry["cost"] = cost
        entry["memory"] = memory
        combos[name] = entry
        if cache_dir:
            _write_manifest(cache_dir, manifest)

    def compile_combo(name: str, sig, fn) -> None:
        nonlocal compiled, skipped
        if stop is not None and stop.is_set():
            raise InterruptedError("prewarm stopped")
        if (
            name in reusable
            and combos[name].get("signature") == [list(x) for x in sig]
            # a pre-catalog manifest entry (no cost block) recompiles
            # once so /debug/programs fills in; after that it skips again
            and "cost" in combos[name]
        ):
            # the executable is already persisted FOR THIS bucket
            # signature: skip even the trace (a warm service restart
            # prewarms in seconds, not minutes). A signature mismatch —
            # code changes moved the representative shapes — recompiles.
            skipped += 1
            return
        t = time.monotonic()
        exe = fn()
        dt = time.monotonic() - t
        compiled += 1
        PREWARM_PROGRAMS.inc({"entry": name.split("@", 1)[0]})
        record(name, sig, dt, exe=exe)
        if progress is not None:
            progress(name, dt)
        log.info("prewarmed", entry=name, seconds=round(dt, 2))

    completed = False
    try:
        for kind, relax in (("generic", False), ("mixed", True)):
            sched, problem, order, tb = _representative(kind)
            sig = buckets.signature(problem)
            div = max(1, int(sched.opts.claim_slot_div))
            for P in buckets.ladder(min_pods, max_pods, floor=64):
                if stop is not None and stop.is_set():
                    raise InterruptedError("prewarm stopped")
                idxs = [order[0]] * P
                # executing the gather/driver jits IS their prewarm (they
                # run in milliseconds and land in both jit + disk caches)
                xs, idx_d, n_d = sched._pod_xs_with_idx(problem, idxs)
                rx = sched._run_x(xs, idx_d, n_d)
                N_runs, N_scan = claim_rungs(P, div)
                jnp = jax.numpy
                st = sched._init_state(problem, N_runs)
                name = f"solve_runs[relax={relax}]@P={P},N={N_runs}"
                compile_combo(
                    name,
                    sig,
                    lambda: KR.solve_runs.lower(
                        tb, st, rx,
                        jnp.zeros(N_runs, jnp.int32),
                        jnp.zeros((), jnp.int32),
                        jnp.int32(P),
                        relax=relax,
                    ).compile(),
                )
                st_s = sched._init_state(problem, N_scan)
                name = f"solve_scan[relax={relax}]@P={P},N={N_scan}"
                compile_combo(
                    name,
                    sig,
                    lambda: K.solve_scan.lower(
                        tb, st_s, xs, relax=relax
                    ).compile(),
                )
                if include_fleet and P == buckets.bucket(min_pods, floor=64):
                    # the lane-batched entry (solver/fleet.py): the
                    # vmapped solve_scan at the pow-2 lane buckets a
                    # fleet-serving SolverServer dispatches, compiled at
                    # the smallest pod rung (a coalesced window of a
                    # different rung pays its own one-time compile and
                    # the persistent cache then holds it)
                    from karpenter_tpu.solver import fleet as fleet_mod

                    for B in fleet_lane_buckets:
                        st_list = [sched._init_state(problem, N_scan)] * B
                        st_b, xs_b = fleet_mod.stack_lanes(st_list, [xs] * B)
                        # compile the program the SERVING dispatch will
                        # actually run: shard_lanes is a no-op on one
                        # device, and on a mesh the jit/persistent-cache
                        # keys include the input shardings — prewarming
                        # only the unsharded layout would leave the first
                        # coalesced window to compile mid-serving
                        st_b, xs_b = fleet_mod.shard_lanes(st_b, xs_b)
                        name = (
                            f"fleet_solve_scan[relax={relax}]"
                            f"@B={B},P={P},N={N_scan}"
                        )
                        compile_combo(
                            name,
                            sig,
                            lambda st_b=st_b, xs_b=xs_b, relax=relax, B=B: (
                                fleet_mod.fleet_fn(
                                    relax, sharded=fleet_mod._mesh_active(B)
                                )
                                .lower(tb, st_b, xs_b)
                                .compile()
                            ),
                        )
        if include_sweeps:
            _prewarm_sweeps(compile_combo)
        completed = True
    except InterruptedError:
        log.warn("prewarm interrupted", compiled=compiled)
    seconds = time.monotonic() - t0
    PREWARM_SECONDS.observe(seconds)
    if completed:
        PREWARM_READY.set(1.0)
    return {
        "compiled": compiled,
        "skipped": skipped,
        "seconds": seconds,
        "cache_dir": cache_dir,
        "combos": combos,
    }


def _prewarm_sweeps(compile_combo) -> None:
    """The consolidation kernels at the IR tier's CONTRACT shapes
    (analysis/ir.py entry builders: the tiny representative fleet at 4 /
    1024 lanes). This warms the kernels' structure, NOT a production
    fleet's shapes — lane/node counts are cluster-sized and unknowable
    ahead of time, so a disruption pass over a real fleet still pays a
    one-time compile for its own bucket (then holds it via the
    persistent cache). Point the service's prewarm_fn at a fleet
    snapshot to cover it up front."""
    import functools

    import jax

    from karpenter_tpu.analysis import ir

    for ep_name in ("_fast_sweep_kernel", "_set_sweep_kernel"):
        ep = next(e for e in ir.ENTRY_POINTS if e.name == ep_name)
        kit = ir.build_kit(ep.kit)
        fn, args = ep.build(kit)
        static = (
            {"static_argnames": ("singleton",)}
            if ep_name == "_fast_sweep_kernel"
            else {}
        )
        if isinstance(fn, functools.partial):
            fn = fn.func
            jitted = jax.jit(fn, **static)
            compile_combo(
                f"{ep_name}@contract",
                (("kit", ep.kit),),
                lambda: jitted.lower(*args, singleton=False).compile(),
            )
        else:
            jitted = jax.jit(fn)
            compile_combo(
                f"{ep_name}@contract",
                (("kit", ep.kit),),
                lambda: jitted.lower(*args).compile(),
            )
