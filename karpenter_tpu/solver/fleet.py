"""Fleet-axis serving: coalesce concurrent solves into one vmapped
mesh dispatch (ROADMAP open item 2).

`dryrun_multichip` phase 4 proved the shape: independent solve lanes —
distinct request profiles against one cluster — batched on a leading
`fleet` axis and executed by ONE `jax.vmap(solve_scan)` dispatch, every
lane bit-identical to its solo run, with zero cross-device collectives
(the batch axis shards cleanly over a mesh). This module promotes that
dry-run into the production serving path:

- **The shared lane-stack/dispatch core** (`stack_lanes`,
  `shard_lanes`, `fleet_dispatch`, `fleet_fn`): the ONE implementation
  both `__graft_entry__.dryrun_multichip` phase 4 and the live
  coalescer drive, so the dry-run and production paths cannot drift.
- **`FleetCoalescer`**: a batch window in front of the scan-path solve
  loop. Concurrent solves (many control planes / simulation lanes
  against one `SolverServer`) that share a TABLE fingerprint
  (`epochs.table_fingerprint` — the cluster tables, topology groups,
  relax-tier tables; NOT the per-pod columns, which ride each lane's
  own PodX) wait up to `window_seconds` for siblings, then stack onto a
  pow-2 lane bucket (`solver/buckets.py` ladder, so the AOT prewarm in
  `solver/aot.py` covers the vmapped entry and steady state stays
  zero-compile) and run their requeue rounds through shared dispatches.
  Same-epoch solves share one device-table materialization: the epoch
  machinery makes their encodings byte-equal, so the server's
  `DeviceTableCache` hit hands every lane the SAME resident tables and
  the window re-uploads nothing.

Eligibility and isolation contract:

- Only SCAN-path solves coalesce (`TpuScheduler` gates on
  `use_runs=False`): the runs path grows claim slots mid-solve
  (host-driven regrow), which cannot be shared across lanes. Runs-path
  solves, strict-reserved problems (oracle-gated before encode), and
  lanes whose table fingerprints differ (mixed relax shapes that won't
  stack simply land in different windows) fall through to the existing
  solo path untouched.
- Per-lane deadline/poison semantics survive coalescing: a lane past
  its deadline finishes `timed_out` with exactly the partial decisions
  the solo loop would return; a lane whose host-side work raises is
  errored alone; a lane that overflows its claim slots leaves the batch
  and re-solves solo (the solo loop's own N-doubling restart — claim
  decisions are N-invariant, so the final decisions match). A failure
  of the BATCHED dispatch itself returns every lane to the solo path —
  degraded throughput, never a wrong or missing answer.
- Decisions are bit-identical to solo by construction: the vmapped
  program runs the same `solve_scan` per lane (tests/test_fleet.py pins
  the parity matrix; phase 4 pins it on a sharded mesh).

Trace shape (satellite: the coalescing wait must be visible): each
request's trace keeps its own wire id and gains a `fleet_dispatch` span
covering window wait + shared execution, plus a `fleet_window` event
carrying (lanes, bucket, window wait, rounds) — a client waterfall
shows the coalescing wait instead of unexplained dead time.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

import numpy as np

from karpenter_tpu import logging as klog
from karpenter_tpu import metrics, tracing
from karpenter_tpu.solver import buckets, epochs

_log = klog.root.named("solver.fleet")

# -- fleet metrics (docs/observability.md catalogs these) --------------------

FLEET_SOLVES = metrics.REGISTRY.counter(
    "karpenter_fleet_solves_total",
    "Scan-path solves offered to the fleet coalescer, by outcome: "
    "coalesced (shared vmapped dispatch), solo_window (no sibling "
    "arrived in the window), fallback (overflow/lane error returned the "
    "lane to the solo path).",
    ("mode",),
)
FLEET_LANES = metrics.REGISTRY.histogram(
    "karpenter_fleet_lanes_per_dispatch",
    "Real lanes per coalesced fleet dispatch (before pow-2 padding).",
)
FLEET_WINDOW_WAIT = metrics.REGISTRY.histogram(
    "karpenter_fleet_window_wait_seconds",
    "Per-lane wall-clock from window entry to coalesced-result handoff "
    "(the coalescing latency a client trades for shared dispatches).",
)

# the hard cap on a non-leader lane's result wait. Before the leader
# drains the window a waiter that exhausts its deadline-shaped budget
# WITHDRAWS (removes itself from the lane list and solves solo); after
# the drain the leader owns the lane, so the waiter takes the handoff
# under this cap — the leader sets every drained lane's done event in a
# finally, so exceeding it means the leader thread was destroyed
# un-Pythonically, and the lane falls back to the solo path
_RESULT_WAIT_CAP_SECONDS = 600.0

# Mesh-sharded dispatches must be LAUNCH-ORDERED: two sharded programs
# in flight over the same device set (two windows from different
# fingerprint groups, or a window racing a warm-up) interleave their
# collective rendezvous and deadlock — observed live on the 8-virtual-
# device CPU backend (AllReduce participants of two run_ids each waiting
# for all 8 devices), and the same rule governs real multi-chip
# backends. One module-level lock totally orders sharded fleet
# launches; single-device dispatches carry no collectives and never
# take it.
_MESH_DISPATCH_LOCK = threading.Lock()


def _mesh_active(B: int) -> bool:
    """Whether shard_lanes would place a B-lane batch over the mesh —
    the condition under which dispatches must serialize."""
    import jax

    n = len(jax.devices())
    return n > 1 and B % n == 0


# ---------------------------------------------------------------------------
# the shared lane-stack / dispatch core (dryrun phase 4 + the coalescer)


def stack_lanes(st_list, xs_list):
    """Stack per-lane State/PodX pytrees onto a leading fleet axis.
    Lanes must be shape-compatible (same table fingerprint + claim-slot
    rung); the caller owns padding the lane COUNT to its pow-2 bucket."""
    import jax
    import jax.numpy as jnp

    st_b = jax.tree_util.tree_map(lambda *a: jnp.stack(a), *st_list)
    xs_b = jax.tree_util.tree_map(lambda *a: jnp.stack(a), *xs_list)
    return st_b, xs_b


def shard_lanes(st_b, xs_b):
    """Place stacked lane operands over a `fleet` mesh axis when the
    backend has multiple devices and the DEVICE COUNT divides the lane
    bucket (each device gets whole lanes; a B=2 window on 8 devices
    stays unsharded).
    Lanes are independent whole solves, so the sharding propagates the
    batch axis end to end with zero cross-device collectives
    (dryrun_multichip phase 4's layout); on a single device this is a
    no-op. Parity is unaffected either way — the mesh only changes
    placement."""
    import jax

    devices = jax.devices()
    B = int(xs_b.valid.shape[0])
    if not _mesh_active(B):
        return st_b, xs_b
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(devices), ("fleet",))
    lane_sh = NamedSharding(mesh, P("fleet"))
    return jax.device_put(st_b, lane_sh), jax.device_put(xs_b, lane_sh)


_fleet_fn_cache: dict[tuple, object] = {}


def fleet_fn(relax: bool, sharded: bool = False):
    """The jitted vmapped solve entry: `vmap(solve_scan, in_axes=(None,
    0, 0))` — tables shared, State/PodX per lane. Module-level cache per
    (relax, sharded) pair (a per-call closure would recompile every
    window); the jit cache then keys on the (B, P, N) bucketed shapes,
    which the AOT prewarm ladder covers (solver/aot.py fleet combos).

    `sharded` wraps the vmapped solve in a `shard_map` over the `fleet`
    mesh axis so each device runs its own lane block INDEPENDENTLY.
    Under plain vmap, GSPMD lifts every data-dependent `while_loop`
    predicate inside solve_scan to a cross-LANE reduce_or ("run until
    all lanes are done"), and on a sharded lane axis that consensus
    compiles to a per-iteration all-reduce over the whole mesh — a real
    cross-device collective on the fleet axis, caught by `graftlint
    --spmd`'s collective census (the lane-sharded budget pins zero).
    shard_map keeps the loop predicates device-local: lanes are
    independent whole solves, so no consensus is needed, the compiled
    program carries ZERO collectives, and per-lane results stay
    bit-identical (each lane runs the same solo program either way)."""
    fn = _fleet_fn_cache.get((relax, sharded))
    if fn is None:
        import functools

        import jax

        from karpenter_tpu.solver import tpu_kernel as K

        vmapped = jax.vmap(
            functools.partial(K.solve_scan, relax=relax),
            in_axes=(None, 0, 0),
        )
        if sharded:
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("fleet",))
            # check_rep=False: the replication checker rejects the
            # solver's while loops; correctness rides on lanes being
            # independent (the bit-parity the fleet tests pin)
            vmapped = shard_map(
                vmapped,
                mesh=mesh,
                in_specs=(P(), P("fleet"), P("fleet")),
                out_specs=P("fleet"),
                check_rep=False,
            )
        fn = jax.jit(vmapped)
        _fleet_fn_cache[(relax, sharded)] = fn
    return fn


def fleet_dispatch(tb, st_b, xs_b, relax: bool = True, sharded=None):
    """ONE device dispatch running every stacked lane's solve step
    batch; returns (st_b, kinds_b, slots_b, over_b, odo_b) with a
    leading lane axis (over_b is per lane — solve_scan's any-overflow
    scalar, mapped; odo_b the per-lane kernel odometer block).
    Counted under the existing per-dispatch accounting as path=fleet.

    `sharded` selects the shard_map program variant (see fleet_fn);
    None means auto — the same `_mesh_active` condition `shard_lanes`
    places under. Callers that deliberately keep a divisible batch off
    the mesh (`SolverServer(use_mesh=False)`) pass sharded=False."""
    if sharded is None:
        sharded = _mesh_active(int(xs_b.valid.shape[0]))
    out = fleet_fn(relax, sharded=sharded)(tb, st_b, xs_b)
    tracing.SOLVE_DISPATCHES.inc({"path": "fleet"})
    return out


# ---------------------------------------------------------------------------
# the batch-window coalescer


class _Lane:
    """One request's seat in a batch window. Mutated by the leader
    thread while the owner blocks on `done`; ownership hands back at
    done.set(), so no field is ever accessed concurrently."""

    __slots__ = (
        "sched", "problem", "tb", "order", "N", "relax", "deadline",
        "trace", "done", "result", "error", "entered_at",
        "st", "kinds", "slots", "pending", "finished", "timed_out",
        "solo", "rounds", "lanes_in_window", "epoch_key", "odo",
    )

    def __init__(self, sched, problem, tb, order, N, relax, deadline, trace):
        self.sched = sched
        self.problem = problem
        self.tb = tb
        self.order = order
        self.N = N
        self.relax = relax
        self.deadline = deadline
        self.trace = trace
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.entered_at = time.monotonic()
        self.st = None
        self.kinds = None
        self.slots = None
        self.pending: list[int] = []
        self.finished = False
        self.timed_out = False
        self.solo = False
        self.rounds = 0
        self.lanes_in_window = 1
        self.epoch_key = None
        # per-lane kernel-odometer accumulation across shared rounds
        # (tpu.py folds it into the request's last_odometer/metrics)
        self.odo = {"steps": 0, "tier_steps": 0, "tier_hist": [], "dispatches": 0}


class _Window:
    """One open batch window for a lane-group key. The FIRST lane in
    becomes the leader: it waits `window_seconds` (woken early when the
    window fills), drains the lane list, and drives every lane's rounds
    through shared dispatches while the others block on their events."""

    def __init__(self, first: _Lane):
        self.lanes: list[_Lane] = [first]
        self.full = threading.Event()
        # set under the coalescer lock when the leader copies the lane
        # list: a waiter that gives up BEFORE the drain removes itself
        # (the leader never sees it); after the drain the leader owns
        # the lane and the waiter must take the handoff, not fork a
        # duplicate solo solve of the same scheduler
        self.drained = False


class FleetCoalescer:
    """The batch-window layer in front of scan-path solves.

    Concurrency contract (graftlint race tier): the single lock guards
    only the open-window map and lane-list membership — never held
    across a wait, a dispatch, or any jax call, so it is a leaf in the
    program's lock graph. Leader/waiter handoff rides per-lane Events;
    the leader sets every lane's result-or-error in a finally, so a
    waiter can only time out if the leader thread was destroyed
    mid-solve (then the lane solves solo — degraded, never stuck).

    `window_seconds` is the latency a request trades for siblings; a
    window that closes with one lane charges only that wait and falls
    back to the solo path (mode=solo_window). `max_lanes` wakes the
    leader early when the window fills — the pow-2 lane bucket the AOT
    ladder covers caps there."""

    def __init__(
        self,
        window_seconds: float = 0.02,
        max_lanes: int = 8,
        use_mesh: bool = True,
    ):
        self.window_seconds = float(window_seconds)
        self.max_lanes = int(max_lanes)
        self.use_mesh = use_mesh
        self._lock = threading.Lock()
        self._open: dict[tuple, _Window] = {}

    # -- the TpuScheduler hook -------------------------------------------

    def solve_lane(
        self, sched, problem, tb, order, N: int, relax: bool, deadline, trace,
        table_fp: Optional[str] = None, epoch_key=None,
    ):
        """Offer one scan-path solve to the current batch window.

        Returns (st, kinds, slots, timed_out, odo) — the solo scan
        loop's tuple plus this lane's accumulated kernel-odometer dict,
        ready for `TpuScheduler._decode` — or None
        when the lane must run the solo path instead (no sibling
        arrived, claim-slot overflow, lane-local or batch-wide failure).
        Never raises for coalescing-machinery faults: the solo path is
        always the floor.

        `table_fp` is the upload phase's already-computed table
        fingerprint (tpu.py passes it whenever a DeviceTableCache is
        wired — the sidecar shape), saving the per-entry re-hash; the
        window key cannot be the epoch id ALONE because the shared
        tables also hash the pod batch's topology-group tables, so two
        same-epoch solves with different spread/affinity mixes must land
        in different windows. `epoch_key` ((client, epoch id), when the
        sidecar materialized this request from a resident epoch) rides
        the lane's window event: same-epoch lanes are visible sharing
        one window — and, through the cache's table-level single-flight,
        one device materialization."""
        lane = _Lane(sched, problem, tb, order, N, relax, deadline, trace)
        lane.epoch_key = epoch_key
        if table_fp is None:
            table_fp = epochs.table_fingerprint(problem)
        key = (table_fp, int(N), bool(relax))
        with tracing.span_of(trace, "fleet_dispatch"):
            try:
                result = self._submit(key, lane)
            except Exception as e:
                # a batch-wide coalescing fault (stack/dispatch raised in
                # THIS lane's leader turn) must land on the solo KERNEL
                # loop, not propagate into HybridScheduler's last-resort
                # pristine-oracle guard — the request itself is fine,
                # only the shared dispatch failed (siblings were already
                # errored to their own solo fallbacks by _submit)
                lane.error = e
                result = None
        wait = time.monotonic() - lane.entered_at
        FLEET_WINDOW_WAIT.observe(wait)
        if result is None:
            mode = "solo_window" if lane.error is None and not lane.solo else "fallback"
            FLEET_SOLVES.inc({"mode": mode})
            if lane.error is not None:
                _log.warn(
                    "fleet lane fell back to the solo path",
                    error=f"{type(lane.error).__name__}: {lane.error}",
                )
            if trace is not None:
                trace.event(
                    "fleet_window", mode=mode, wait_seconds=round(wait, 6)
                )
            return None
        FLEET_SOLVES.inc({"mode": "coalesced"})
        if trace is not None:
            attrs = dict(
                mode="coalesced",
                lanes=lane.lanes_in_window,
                bucket=buckets.bucket_lanes(lane.lanes_in_window),
                wait_seconds=round(wait, 6),
                rounds=lane.rounds,
            )
            if lane.epoch_key is not None:
                attrs["epoch"] = str(lane.epoch_key)
            trace.event("fleet_window", **attrs)
            # rounds can be 0 (a lane whose deadline was blown before
            # the first shared round): no phantom dispatch on the trace
            if lane.rounds:
                trace.count("dispatches", by=lane.rounds)
        return result

    def _submit(self, key: tuple, lane: _Lane):
        with self._lock:
            window = self._open.get(key)
            if window is not None and len(window.lanes) >= self.max_lanes:
                # the incumbent window is FULL (its leader is waking to
                # drain it): never join past max_lanes — a burst bigger
                # than the lane budget would otherwise swell the bucket
                # past the prewarmed ladder and compile a fresh vmapped
                # shape mid-serving. Open a fresh window in the map slot;
                # the drain-time `is window` check keeps both sound.
                window = None
            if window is None:
                window = _Window(lane)
                self._open[key] = window
                leader = True
            else:
                window.lanes.append(lane)
                leader = False
                if len(window.lanes) >= self.max_lanes:
                    window.full.set()
        if not leader:
            # deadline-shaped first wait: a lane with a short budget
            # should not sit a full result-cap behind a cold window
            # (the first coalesced dispatch can compile for tens of
            # seconds on this backend)
            budget = _RESULT_WAIT_CAP_SECONDS
            if lane.deadline is not None:
                budget = min(
                    budget,
                    max(1.0, lane.deadline - time.monotonic())
                    + self.window_seconds
                    + 60.0,
                )
            if not lane.done.wait(budget):
                with self._lock:
                    if not window.drained:
                        # the leader hasn't taken the lane list yet:
                        # withdraw cleanly and solve solo — the leader
                        # will never see this lane
                        window.lanes.remove(lane)
                        lane.error = TimeoutError(
                            "fleet window leader never answered"
                        )
                        return None
                # drained: the leader OWNS this lane (it is already
                # gathering/dispatching for it) — forking a solo solve
                # now would run the same scheduler concurrently twice.
                # Take the handoff under the hard cap; only a leader
                # thread destroyed un-Pythonically leaves this unset.
                if not lane.done.wait(_RESULT_WAIT_CAP_SECONDS):
                    lane.error = TimeoutError(
                        "fleet window leader never answered"
                    )
                    return None
            if lane.error is not None:
                return None
            return lane.result
        window.full.wait(self.window_seconds)
        with self._lock:
            if self._open.get(key) is window:
                del self._open[key]
            window.drained = True
            lanes = list(window.lanes)
        try:
            if len(lanes) == 1:
                return None  # no sibling arrived: solo path, zero extra compile
            self._run_window(lanes)
        except BaseException as e:
            for l in lanes:
                if l.result is None and l.error is None:
                    l.error = e if isinstance(e, Exception) else RuntimeError(
                        f"fleet window aborted: {type(e).__name__}"
                    )
            raise
        finally:
            for l in lanes:
                if l is not lane:
                    l.done.set()
        if lane.error is not None:
            return None
        return lane.result

    # -- the coalesced multi-round solve ---------------------------------

    def _run_window(self, lanes: list[_Lane]) -> None:
        """Drive every lane's requeue rounds (scheduler.go:380 "schedule
        again if progress was made") through shared vmapped dispatches.
        This is the solo scan loop of `TpuScheduler._solve_traced`
        replicated per lane: same per-round pending sets, same stall
        rule, same deadline/timeout semantics, same overflow handling
        (a lane that overflows leaves the batch for the solo loop's
        N-doubling restart). One compiled shape serves the whole window:
        the pod axis stays at the window's initial pow-2 rung and
        finished lanes are backfilled with lane 0, so every round reuses
        the (B, P, N) program the first dispatch traced."""
        import jax

        from karpenter_tpu.solver import tpu_kernel as K
        from karpenter_tpu.solver.tpu_problem import _pow2

        tb = lanes[0].tb
        relax = lanes[0].relax
        B_pad = buckets.bucket_lanes(len(lanes))
        P0 = max(_pow2(len(l.order)) for l in lanes)
        for l in lanes:
            l.lanes_in_window = len(lanes)
        for l in lanes:
            try:
                l.st = l.sched._init_state(l.problem, l.N)
                l.kinds = np.full(len(l.problem.pods), K.KIND_FAIL, np.int32)
                l.slots = np.full(len(l.problem.pods), -1, np.int32)
                l.pending = list(l.order)
            except Exception as e:
                l.error = e
                l.finished = True
        first_round = True
        while True:
            now = time.monotonic()
            for l in lanes:
                if (
                    not l.finished
                    and l.deadline is not None
                    and now > l.deadline
                ):
                    l.timed_out = True
                    l.finished = True
            active = [
                l
                for l in lanes
                if not l.finished and not l.solo and l.error is None
            ]
            if not active:
                break
            # per-lane host work is isolated: a gather failure errors that
            # lane alone and its siblings keep the round
            xs_list, st_list, ok = [], [], []
            for l in active:
                try:
                    xs_list.append(self._gather(l, P0))
                    st_list.append(l.st)
                    ok.append(l)
                except Exception as e:
                    l.error = e
                    l.finished = True
            if not ok:
                continue
            # backfill to the pow-2 lane bucket with lane 0 (results of
            # pad lanes are discarded; one compiled shape per window)
            while len(st_list) < B_pad:
                st_list.append(st_list[0])
                xs_list.append(xs_list[0])
            st_b, xs_b = stack_lanes(st_list, xs_list)
            sharded = self.use_mesh and _mesh_active(B_pad)
            if sharded:
                st_b, xs_b = shard_lanes(st_b, xs_b)
            # sharded launches are totally ordered (see _MESH_DISPATCH_
            # LOCK); the device_get rides inside the critical section so
            # the program has RETIRED before the next sharded launch —
            # launch order alone does not prevent rendezvous interleaving
            # on backends that overlap execution
            with _MESH_DISPATCH_LOCK if sharded else contextlib.nullcontext():
                st_b, kinds_b, slots_b, over_b, odo_b = fleet_dispatch(
                    tb, st_b, xs_b, relax=relax, sharded=sharded
                )
                kinds_b, slots_b, over_b, odo_b = jax.device_get(
                    (kinds_b, slots_b, over_b, odo_b)
                )
                if sharded:
                    # the carried state is consumed NEXT round by another
                    # sharded launch; materialize it before releasing the
                    # launch order
                    st_b = jax.block_until_ready(st_b)
            if first_round:
                FLEET_LANES.observe(float(len(ok)))
                first_round = False
            for i, l in enumerate(ok):
                l.rounds += 1
                # this lane's slice of the per-lane odometer block (its
                # own scan steps / tier trips — pad lanes' work is the
                # replicated lane 0's and is charged to nobody)
                l.odo["steps"] += int(odo_b.steps[i])
                l.odo["tier_steps"] += int(odo_b.tier_steps[i])
                hist = [int(v) for v in np.asarray(odo_b.tier_hist[i])]
                if not l.odo["tier_hist"]:
                    l.odo["tier_hist"] = [0] * len(hist)
                for t, v in enumerate(hist):
                    l.odo["tier_hist"][t] += v
                l.odo["dispatches"] += 1
                l.st = jax.tree_util.tree_map(
                    lambda a, i=i: a[i], st_b
                )
                if bool(over_b[i]):
                    # scan-path overflow: the solo loop restarts the whole
                    # solve at 2N — send this lane there; siblings keep
                    # their committed rounds
                    l.solo = True
                    l.finished = True
                    continue
                n = len(l.pending)
                got_kinds = np.asarray(kinds_b[i][:n])
                got_slots = np.asarray(slots_b[i][:n])
                batch = np.asarray(l.pending, np.int64)
                l.kinds[batch] = got_kinds
                l.slots[batch] = got_slots
                round_failed = [
                    p for p, k in zip(l.pending, got_kinds) if k == K.KIND_FAIL
                ]
                if not round_failed or len(round_failed) == n:
                    # all placed, or no progress: stall (queue.go:52)
                    l.finished = True
                else:
                    l.pending = round_failed
        for l in lanes:
            if l.error is not None or l.solo:
                l.result = None
            else:
                l.result = (l.st, l.kinds, l.slots, l.timed_out, l.odo)

    @staticmethod
    def _gather(l: _Lane, P0: int):
        """One lane's round PodX at the window's shared pod rung — the
        SAME `_pod_xs_with_idx` assembly the solo path uses, padded to
        P0 instead of the lane's own pow-2 so lanes stack (pad positions
        carry idx 0 and valid=False; the kernel never commits them)."""
        return l.sched._pod_xs_with_idx(l.problem, l.pending, pad_to=P0)[0]
