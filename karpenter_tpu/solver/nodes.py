"""In-flight scheduling nodes: NodeClaimTemplate, NodeClaim (hypothetical node
being packed), ExistingNode (real node being packed), ReservationManager, and
instance-type filtering.

Reference:
- NodeClaimTemplate  /root/reference/pkg/controllers/provisioning/scheduling/nodeclaimtemplate.go:46-123
- NodeClaim          .../nodeclaim.go:83-268
- ExistingNode       .../existingnode.go:29-119
- ReservationManager .../reservationmanager.go:28-110
- filterInstanceTypesByRequirements .../nodeclaim.go:373-441
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Iterable, Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api import objects as api
from karpenter_tpu.api.objects import NodePool, Operator, Pod, Taint
from karpenter_tpu.cloudprovider.types import InstanceType, InstanceTypes, Offering
from karpenter_tpu.scheduling import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    Requirement,
    Requirements,
    Taints,
)
from karpenter_tpu.scheduling.hostports import HostPortUsage, get_host_ports
from karpenter_tpu.scheduling.volumeusage import VolumeUsage, volume_limit
from karpenter_tpu.solver.topology import Topology
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.resources import ResourceList

# Max instance types sent for launch (nodeclaimtemplate.go:41)
MAX_INSTANCE_TYPES = 600

_hostname_seq = itertools.count(1)


def next_placeholder_hostname() -> str:
    """The shared synthetic-hostname sequence (nodeclaim.go:92). Every
    code path that fabricates a claim — the oracle and the TPU decode —
    MUST draw from this one counter: independent counters collide, merging
    two claims' topology domain counts (see the hybrid continuation
    regression in tests/test_hybrid.py)."""
    return f"hostname-placeholder-{next(_hostname_seq):04d}"


@dataclass
class PodData:
    """Pre-computed pod scheduling data (scheduler.go:186 PodData)."""

    requests: ResourceList
    requirements: Requirements
    strict_requirements: Requirements


class ReservedOfferingError(Exception):
    """Pod couldn't be placed due to reserved-offering constraints; the
    relaxation ladder must NOT run for these (nodeclaim.go:62)."""


# ---------------------------------------------------------------------------
# ReservationManager


class ReservationManager:
    """Counts remaining capacity of `reserved` offerings; reservations are
    idempotent per hostname (reservationmanager.go:28)."""

    def __init__(self, instance_types_by_pool: dict[str, InstanceTypes]):
        self.capacity: dict[str, int] = {}
        self.reservations: dict[str, set[str]] = {}  # hostname -> reservation ids
        for its in instance_types_by_pool.values():
            for it in its:
                for o in it.offerings:
                    if o.capacity_type() != well_known.CAPACITY_TYPE_RESERVED:
                        continue
                    rid = o.reservation_id()
                    # track the minimum amongst duplicates for safety
                    if rid not in self.capacity or o.reservation_capacity < self.capacity[rid]:
                        self.capacity[rid] = o.reservation_capacity

    def can_reserve(self, hostname: str, offering: Offering) -> bool:
        rid = offering.reservation_id()
        if rid in self.reservations.get(hostname, ()):
            return True
        return self.capacity.get(rid, 0) > 0

    def reserve(self, hostname: str, *offerings: Offering) -> None:
        for o in offerings:
            rid = o.reservation_id()
            held = self.reservations.setdefault(hostname, set())
            if rid in held:
                continue
            self.capacity[rid] = self.capacity.get(rid, 0) - 1
            held.add(rid)

    def release(self, hostname: str, *offerings: Offering) -> None:
        for o in offerings:
            rid = o.reservation_id()
            held = self.reservations.get(hostname)
            if held and rid in held:
                held.discard(rid)
                self.capacity[rid] = self.capacity.get(rid, 0) + 1


# ---------------------------------------------------------------------------
# instance-type filtering


@dataclass
class InstanceTypeFilterError:
    """Rich scheduling-failure diagnostics (nodeclaim.go:296): which of the
    three criteria (requirements / fits / offering) excluded all types."""

    requirements_met: bool = False
    fits: bool = False
    has_offering: bool = False
    requirements_and_fits: bool = False
    requirements_and_offering: bool = False
    fits_and_offering: bool = False
    min_values_err: Optional[str] = None
    requirements: Optional[Requirements] = None
    pod_requests: Optional[ResourceList] = None
    daemon_requests: Optional[ResourceList] = None

    def __str__(self) -> str:
        resources_str = res.to_string(
            res.merge(self.daemon_requests or {}, self.pod_requests or {})
        )
        suffix = f"requirements={self.requirements!r}, resources={resources_str}"
        if self.min_values_err:
            return f"{self.min_values_err}, {suffix}"
        if not self.requirements_met and not self.fits and not self.has_offering:
            return (
                "no instance type met the scheduling requirements or had enough "
                f"resources or had a required offering, {suffix}"
            )
        if not self.requirements_met and not self.fits:
            return f"no instance type met the scheduling requirements or had enough resources, {suffix}"
        if not self.requirements_met and not self.has_offering:
            return f"no instance type met the scheduling requirements or had a required offering, {suffix}"
        if not self.fits and not self.has_offering:
            return f"no instance type had enough resources or had a required offering, {suffix}"
        if not self.requirements_met:
            return f"no instance type met all requirements, {suffix}"
        if not self.fits:
            return f"no instance type has enough resources, {suffix}"
        if not self.has_offering:
            return f"no instance type has the required offering, {suffix}"
        if self.requirements_and_fits:
            return (
                "no instance type which met the scheduling requirements and had "
                f"enough resources, had a required offering, {suffix}"
            )
        if self.fits_and_offering:
            return (
                "no instance type which had enough resources and the required "
                f"offering met the scheduling requirements, {suffix}"
            )
        if self.requirements_and_offering:
            return (
                "no instance type which met the scheduling requirements and the "
                f"required offering had the required resources, {suffix}"
            )
        return f"no instance type met the requirements/resources/offering tuple, {suffix}"


def filter_instance_types(
    instance_types: Iterable[InstanceType],
    requirements: Requirements,
    pod_requests: ResourceList,
    daemon_requests: ResourceList,
    total_requests: ResourceList,
    relax_min_values: bool = False,
) -> tuple[InstanceTypes, dict[str, int], Optional[InstanceTypeFilterError]]:
    """nodeclaim.go:373 filterInstanceTypesByRequirements: keep instance types
    that are (a) requirement-compatible, (b) fit the accumulated requests, and
    (c) have an available compatible offering; track per-criterion bits for
    error reporting and enforce minValues."""
    err = InstanceTypeFilterError(
        requirements=requirements,
        pod_requests=pod_requests,
        daemon_requests=daemon_requests,
    )
    remaining = InstanceTypes()
    for it in instance_types:
        it_compat = it.requirements.intersects(requirements) is None
        it_fits = res.fits(total_requests, it.allocatable())
        it_has_offering = any(
            o.available
            and requirements.is_compatible(
                o.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
            )
            for o in it.offerings
        )
        err.requirements_met = err.requirements_met or it_compat
        err.fits = err.fits or it_fits
        err.has_offering = err.has_offering or it_has_offering
        err.requirements_and_fits = err.requirements_and_fits or (
            it_compat and it_fits and not it_has_offering
        )
        err.requirements_and_offering = err.requirements_and_offering or (
            it_compat and it_has_offering and not it_fits
        )
        err.fits_and_offering = err.fits_and_offering or (
            it_fits and it_has_offering and not it_compat
        )
        if it_compat and it_fits and it_has_offering:
            remaining.append(it)

    unsatisfiable: dict[str, int] = {}
    if requirements.has_min_values():
        _, unsatisfiable, min_err = remaining.satisfies_min_values(requirements)
        if min_err is not None:
            if not relax_min_values:
                err.min_values_err = min_err
                remaining = InstanceTypes()
    if not remaining:
        return InstanceTypes(), unsatisfiable, err
    return remaining, unsatisfiable, None


# ---------------------------------------------------------------------------
# NodeClaimTemplate


class NodeClaimTemplate:
    """Per-NodePool launch template (nodeclaimtemplate.go:46)."""

    def __init__(self, node_pool: NodePool):
        self.nodepool_name = node_pool.name
        self.nodepool_uid = node_pool.metadata.uid
        self.weight = node_pool.weight
        self.is_static = node_pool.replicas is not None
        spec = node_pool.template
        self.taints: list[Taint] = list(spec.taints)
        self.startup_taints: list[Taint] = list(spec.startup_taints)
        self.node_class_ref = spec.node_class_ref
        self.expire_after_seconds = spec.expire_after_seconds
        self.termination_grace_period_seconds = spec.termination_grace_period_seconds
        self.labels = dict(spec.labels)
        self.labels[well_known.NODEPOOL_LABEL_KEY] = node_pool.name
        self.annotations = dict(spec.annotations)
        self.requirements = Requirements()
        self.requirements.add(
            *Requirements.from_node_selector_requirements(spec.requirements).values()
        )
        self.requirements.add(*Requirements.from_labels(self.labels).values())
        self.instance_type_options: InstanceTypes = InstanceTypes()

    def to_node_claim(self, requirements: Requirements, instance_types: InstanceTypes) -> api.NodeClaim:
        """Produce the launchable NodeClaim: price-ordered instance types
        truncated to MAX_INSTANCE_TYPES injected as an In requirement
        (nodeclaimtemplate.go:79 ToNodeClaim)."""
        reqs = requirements.copy()
        if not self.is_static:
            ordered = InstanceTypes(instance_types).order_by_price(reqs)[:MAX_INSTANCE_TYPES]
            reqs.add(
                Requirement(
                    well_known.INSTANCE_TYPE_LABEL_KEY,
                    Operator.IN,
                    [it.name for it in ordered],
                    min_values=reqs.get(well_known.INSTANCE_TYPE_LABEL_KEY).min_values,
                )
            )
        nc = api.NodeClaim(
            metadata=api.ObjectMeta(
                name=f"{self.nodepool_name}-{api.new_uid()[:8]}",
                labels=dict(self.labels),
                annotations=dict(self.annotations),
            ),
            requirements=reqs.to_node_selector_requirements(),
            taints=list(self.taints),
            startup_taints=list(self.startup_taints),
            node_class_ref=self.node_class_ref,
            expire_after_seconds=self.expire_after_seconds,
            termination_grace_period_seconds=self.termination_grace_period_seconds,
        )
        return nc


# ---------------------------------------------------------------------------
# NodeClaim (in-flight)


class SchedulingNodeClaim:
    """A hypothetical node being packed (nodeclaim.go:40 NodeClaim)."""

    def __init__(
        self,
        template: NodeClaimTemplate,
        topology: Topology,
        daemon_resources: ResourceList,
        daemon_host_port_usage: HostPortUsage,
        instance_types: InstanceTypes,
        reservation_manager: ReservationManager,
        reserved_offering_strict: bool = False,
        reserved_capacity_enabled: bool = False,
    ):
        self.template = template
        self.hostname = next_placeholder_hostname()
        self.requirements = Requirements(template.requirements.values())
        self.requirements.add(
            Requirement(well_known.HOSTNAME_LABEL_KEY, Operator.IN, [self.hostname])
        )
        self.instance_type_options = InstanceTypes(instance_types)
        self.requests: ResourceList = dict(daemon_resources)
        self.daemon_resources = daemon_resources
        self.pods: list[Pod] = []
        self.topology = topology
        self.host_port_usage = daemon_host_port_usage.copy()
        self.reservation_manager = reservation_manager
        self.reserved_offerings: list[Offering] = []
        self.reserved_offering_strict = reserved_offering_strict
        self.reserved_capacity_enabled = reserved_capacity_enabled
        self.annotations: dict[str, str] = dict(template.annotations)

    @property
    def nodepool_name(self) -> str:
        return self.template.nodepool_name

    def can_add(
        self, pod: Pod, pod_data: PodData, relax_min_values: bool = False
    ) -> tuple[
        Optional[Requirements],
        Optional[InstanceTypes],
        Optional[list[Offering]],
        Optional[str],
    ]:
        """Taints -> host ports -> requirements -> topology -> instance-type
        filter -> reserved offerings (nodeclaim.go:114 CanAdd). Returns
        (requirements, instance types, offerings-to-reserve, error)."""
        err = Taints(self.template.taints).tolerates_pod(pod)
        if err is not None:
            return None, None, None, err
        hp_err = self.host_port_usage.conflicts(pod, get_host_ports(pod))
        if hp_err is not None:
            return None, None, None, f"checking host port usage, {hp_err}"
        requirements = Requirements(self.requirements.values())
        compat_err = requirements.compatible(
            pod_data.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
        )
        if compat_err is not None:
            return None, None, None, f"incompatible requirements, {compat_err}"
        requirements.add(*pod_data.requirements.values())

        topo_reqs, topo_err = self.topology.add_requirements(
            pod,
            self.template.taints,
            pod_data.strict_requirements,
            requirements,
            ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
        )
        if topo_err is not None:
            return None, None, None, topo_err
        compat_err = requirements.compatible(topo_reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)
        if compat_err is not None:
            return None, None, None, compat_err
        requirements.add(*topo_reqs.values())

        total = res.merge(self.requests, pod_data.requests)
        remaining, unsatisfiable, filter_err = filter_instance_types(
            self.instance_type_options,
            requirements,
            pod_data.requests,
            self.daemon_resources,
            total,
            relax_min_values,
        )
        if relax_min_values:
            for key, min_values in unsatisfiable.items():
                requirements.get(key).min_values = min_values
        if filter_err is not None:
            return None, None, None, str(filter_err)
        offerings, reserve_err = self._offerings_to_reserve(remaining, requirements)
        if reserve_err is not None:
            raise ReservedOfferingError(reserve_err)
        return requirements, remaining, offerings, None

    def add(
        self,
        pod: Pod,
        pod_data: PodData,
        requirements: Requirements,
        instance_types: InstanceTypes,
        offerings_to_reserve: list[Offering],
    ) -> None:
        """nodeclaim.go:168 Add."""
        self.pods.append(pod)
        self.instance_type_options = instance_types
        self.requests = res.merge(self.requests, pod_data.requests)
        self.requirements = requirements
        self.topology.register(well_known.HOSTNAME_LABEL_KEY, self.hostname)
        self.topology.record(
            pod, self.template.taints, requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
        )
        self.host_port_usage.add(pod, get_host_ports(pod))
        self.reservation_manager.reserve(self.hostname, *offerings_to_reserve)
        updated = {o.reservation_id() for o in offerings_to_reserve}
        for o in self.reserved_offerings:
            if o.reservation_id() not in updated:
                self.reservation_manager.release(self.hostname, o)
        self.reserved_offerings = list(offerings_to_reserve)

    def _offerings_to_reserve(
        self, instance_types: InstanceTypes, requirements: Requirements
    ) -> tuple[list[Offering], Optional[str]]:
        """nodeclaim.go:201 offeringsToReserve."""
        if not self.reserved_capacity_enabled:
            return [], None
        has_compatible = False
        reserved: list[Offering] = []
        for it in instance_types:
            for o in it.offerings:
                if (
                    o.capacity_type() != well_known.CAPACITY_TYPE_RESERVED
                    or not o.available
                ):
                    continue
                if not requirements.is_compatible(
                    o.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
                ):
                    continue
                has_compatible = True
                if self.reservation_manager.can_reserve(self.hostname, o):
                    reserved.append(o)
        if self.reserved_offering_strict:
            if has_compatible and not reserved:
                return [], (
                    "one or more instance types with compatible reserved offerings "
                    "are available, but could not be reserved"
                )
            if self.reserved_offerings and not reserved:
                return [], (
                    "satisfying updated nodeclaim constraints would remove all "
                    "compatible reserved offering options"
                )
        return reserved, None

    def finalize(self) -> None:
        """Strip the synthetic hostname, inject reservation requirements
        (nodeclaim.go:252 FinalizeScheduling)."""
        self.requirements.pop(well_known.HOSTNAME_LABEL_KEY)
        if self.reserved_offerings:
            self.requirements._reqs[well_known.CAPACITY_TYPE_LABEL_KEY] = Requirement(
                well_known.CAPACITY_TYPE_LABEL_KEY,
                Operator.IN,
                [well_known.CAPACITY_TYPE_RESERVED],
            )
            self.requirements.add(
                Requirement(
                    well_known.RESERVATION_ID_LABEL_KEY,
                    Operator.IN,
                    [o.reservation_id() for o in self.reserved_offerings],
                )
            )

    def to_node_claim(self) -> api.NodeClaim:
        nc = self.template.to_node_claim(self.requirements, self.instance_type_options)
        nc.resources_requests = dict(self.requests)
        nc.metadata.annotations[well_known.NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY] = (
            "true"
            if any(
                (r.min_values is not None)
                and (self.template.requirements.get(r.key).min_values or 0) > r.min_values
                for r in self.requirements.values()
            )
            else "false"
        )
        return nc


# ---------------------------------------------------------------------------
# ExistingNode


@dataclass
class StateNodeView:
    """The slice of cluster-state a scheduling simulation needs about a live
    or in-flight node. Produced by the control plane's state cache (M6) or
    synthesized in tests (reference: state.StateNode)."""

    name: str
    node_labels: Optional[dict[str, str]] = None  # None while claim is in flight
    labels: dict[str, str] = field(default_factory=dict)
    taints: list[Taint] = field(default_factory=list)
    available: ResourceList = field(default_factory=dict)
    capacity: ResourceList = field(default_factory=dict)
    daemonset_requests: ResourceList = field(default_factory=dict)
    initialized: bool = False
    hostname: str = ""
    host_port_usage: HostPortUsage = field(default_factory=HostPortUsage)
    volume_usage: VolumeUsage = field(default_factory=VolumeUsage)
    # CSINode allocatable equivalent: attachable volumes per CSI driver
    # (reference volumeusage.go:187); merged with the label-derived default
    csi_allocatable: dict = field(default_factory=dict)
    # set by the scheduler when a pod is nominated to this node
    nominations: int = 0

    def __post_init__(self):
        if not self.hostname:
            self.hostname = self.labels.get(well_known.HOSTNAME_LABEL_KEY, self.name)


class ExistingNode:
    """existingnode.go:29."""

    def __init__(
        self,
        view: StateNodeView,
        topology: Topology,
        taints: list[Taint],
        daemon_resources: ResourceList,
    ):
        self.view = view
        self.cached_taints = taints
        self.topology = topology
        self.pods: list[Pod] = []
        remaining_daemon = res.subtract(daemon_resources, view.daemonset_requests)
        for k, v in list(remaining_daemon.items()):
            if v < 0:
                remaining_daemon[k] = 0
        self.remaining_resources = res.subtract(view.available, remaining_daemon)
        self.requirements = Requirements.from_labels(view.labels)
        self.requirements.add(
            Requirement(well_known.HOSTNAME_LABEL_KEY, Operator.IN, [view.hostname])
        )
        self.host_port_usage = view.host_port_usage.copy()
        self.volume_usage = view.volume_usage.copy()
        # per-driver limits: CSINode allocatable wins per driver; the node
        # label provides the default "" bucket (volumeusage.go:187)
        limits = dict(view.csi_allocatable or {})
        label_default = volume_limit(view.labels)
        if label_default is not None:
            limits.setdefault("", label_default)
        self.volume_limits = limits or None
        topology.register(well_known.HOSTNAME_LABEL_KEY, view.hostname)

    @property
    def name(self) -> str:
        return self.view.name

    def can_add(
        self, pod: Pod, pod_data: PodData
    ) -> tuple[Optional[Requirements], Optional[str]]:
        """existingnode.go:70 CanAdd. NOTE: no allow-undefined option — custom
        labels must exist on real nodes."""
        err = Taints(self.cached_taints).tolerates_pod(pod)
        if err is not None:
            return None, err
        hp_err = self.host_port_usage.conflicts(pod, get_host_ports(pod))
        if hp_err is not None:
            return None, f"checking host port usage, {hp_err}"
        vol_err = self.volume_usage.exceeds_limit(pod, self.volume_limits)
        if vol_err is not None:
            return None, f"checking volume usage, {vol_err}"
        if not res.fits(pod_data.requests, self.remaining_resources):
            return None, "exceeds node resources"
        compat_err = self.requirements.compatible(pod_data.requirements)
        if compat_err is not None:
            return None, compat_err
        requirements = Requirements(self.requirements.values())
        requirements.add(*pod_data.requirements.values())
        topo_reqs, topo_err = self.topology.add_requirements(
            pod, self.cached_taints, pod_data.strict_requirements, requirements
        )
        if topo_err is not None:
            return None, topo_err
        compat_err = requirements.compatible(topo_reqs)
        if compat_err is not None:
            return None, compat_err
        requirements.add(*topo_reqs.values())
        return requirements, None

    def add(self, pod: Pod, pod_data: PodData, requirements: Requirements) -> None:
        self.pods.append(pod)
        res.subtract_from(self.remaining_resources, pod_data.requests)
        self.requirements = requirements
        self.topology.record(pod, self.cached_taints, requirements)
        self.host_port_usage.add(pod, get_host_ports(pod))
        self.volume_usage.add(pod)
