"""Topology tracking: topology-spread constraints, pod affinity, pod
anti-affinity, and *inverse* anti-affinity.

Semantics ported from the reference:
- Topology           /root/reference/pkg/controllers/provisioning/scheduling/topology.go:47-583
- TopologyGroup      .../topologygroup.go:56-433
- TopologyNodeFilter .../topologynodefilter.go:31-97
- TopologyDomainGroup .../topologydomaingroup.go:28-72

A TopologyGroup tracks `SELECT COUNT(*) FROM pods GROUP BY(topology_key)` for
the pods matching one constraint; groups are deduplicated by a structural hash
so a 100-replica deployment with self anti-affinity is one group with 100
owners. The group answers "which domain may this pod pick next" — max-skew
argmin for spreads, non-empty domains for affinity, empty domains for
anti-affinity.
"""

from __future__ import annotations

import sys
from enum import IntEnum
from typing import Callable, Iterable, Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    LabelSelector,
    LabelSelectorRequirement,
    NodeInclusionPolicy,
    Operator,
    Pod,
    PodAffinityTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WhenUnsatisfiable,
)
from karpenter_tpu.scheduling import Requirement, Requirements, Taints

MAX_I32 = (1 << 31) - 1


class TopologyType(IntEnum):
    SPREAD = 0
    POD_AFFINITY = 1
    POD_ANTI_AFFINITY = 2

    def __str__(self) -> str:
        return ["topology spread", "pod affinity", "pod anti-affinity"][int(self)]


# ---------------------------------------------------------------------------
# node filter


def _selector_canonical(sel: Optional[LabelSelector]):
    if sel is None:
        return None
    return (
        frozenset(sel.match_labels.items()),
        frozenset(
            (e.key, e.operator, frozenset(e.values)) for e in sel.match_expressions
        ),
    )


def _requirements_canonical(reqs: Requirements):
    return frozenset(
        (r.key, r.complement, frozenset(r.values), r.greater_than, r.less_than)
        for r in reqs.values()
    )


class TopologyNodeFilter:
    """Decides if a node participates in a spread topology for counting
    purposes (reference topologynodefilter.go:31). A default-constructed
    filter matches everything (used for affinity/anti-affinity)."""

    def __init__(
        self,
        requirements: Optional[list[Requirements]] = None,
        taint_policy: NodeInclusionPolicy = NodeInclusionPolicy.IGNORE,
        affinity_policy: NodeInclusionPolicy = NodeInclusionPolicy.HONOR,
        tolerations: Optional[list[Toleration]] = None,
    ):
        self.requirements = requirements or []
        self.taint_policy = taint_policy
        self.affinity_policy = affinity_policy
        self.tolerations = tolerations or []

    @classmethod
    def for_pod(
        cls,
        pod: Pod,
        taint_policy: NodeInclusionPolicy,
        affinity_policy: NodeInclusionPolicy,
    ) -> "TopologyNodeFilter":
        """MakeTopologyNodeFilter: node selector AND any required node-affinity
        term (terms OR'd) (topologynodefilter.go:38)."""
        selector_reqs = Requirements.from_labels(pod.node_selector)
        affinity = pod.node_affinity
        if affinity is None or not affinity.required_terms:
            return cls([selector_reqs], taint_policy, affinity_policy, pod.tolerations)
        req_list = []
        for term in affinity.required_terms:
            reqs = Requirements()
            reqs.add(*selector_reqs.values())
            reqs.add(
                *(
                    Requirement.from_node_selector_requirement(e)
                    for e in term.match_expressions
                )
            )
            req_list.append(reqs)
        return cls(req_list, taint_policy, affinity_policy, pod.tolerations)

    def matches(
        self,
        taints: Iterable[Taint],
        requirements: Requirements,
        allow_undefined: Optional[set] = None,
    ) -> bool:
        matches_affinity = True
        if self.affinity_policy == NodeInclusionPolicy.HONOR and self.requirements:
            matches_affinity = any(
                requirements.compatible(req, allow_undefined) is None
                for req in self.requirements
            )
        matches_taints = True
        if self.taint_policy == NodeInclusionPolicy.HONOR:
            matches_taints = Taints(taints).tolerates(self.tolerations) is None
        return matches_affinity and matches_taints

    def canonical(self):
        return (
            self.taint_policy,
            self.affinity_policy,
            tuple(sorted(map(repr, map(_requirements_canonical, self.requirements)))),
            frozenset(self.tolerations) if self.taint_policy == NodeInclusionPolicy.HONOR else None,
        )


# ---------------------------------------------------------------------------
# domain groups


class TopologyDomainGroup(dict):
    """domain -> list of taint-sets under which the domain is reachable
    (reference topologydomaingroup.go:28)."""

    def insert(self, domain: str, taints: tuple[Taint, ...] = ()) -> None:
        groups = self.get(domain)
        if groups is None or len(taints) == 0:
            self[domain] = [tuple(taints)]
            return
        if len(groups[0]) == 0:
            return  # already reachable untainted
        groups.append(tuple(taints))

    def for_each_domain(
        self, pod: Pod, taint_policy: NodeInclusionPolicy, fn: Callable[[str], None]
    ) -> None:
        for domain, taint_groups in self.items():
            if taint_policy == NodeInclusionPolicy.IGNORE:
                fn(domain)
                continue
            for taints in taint_groups:
                if Taints(taints).tolerates_pod(pod) is None:
                    fn(domain)
                    break


def build_domain_groups(
    node_pools, instance_types_by_pool: dict
) -> dict[str, TopologyDomainGroup]:
    """Universe of domains per topology key = NodePool requirements+labels ∩
    instance-type requirements (reference topology.go:105 buildDomainGroups)."""
    pools_by_name = {np.name: np for np in node_pools}
    domain_groups: dict[str, TopologyDomainGroup] = {}
    for pool_name, its in instance_types_by_pool.items():
        np = pools_by_name[pool_name]
        taints = tuple(np.template.taints)
        base = Requirements.from_node_selector_requirements(np.template.requirements)
        base.add(*Requirements.from_labels(np.template.labels).values())
        for it in its:
            requirements = base.copy()
            requirements.add(*it.requirements.values())
            for key in requirements:
                group = domain_groups.setdefault(key, TopologyDomainGroup())
                for domain in requirements.get(key).values:
                    group.insert(domain, taints)
        for key in base:
            req = base.get(key)
            if req.operator() == Operator.IN:
                group = domain_groups.setdefault(key, TopologyDomainGroup())
                for domain in req.values:
                    group.insert(domain, taints)
    return domain_groups


# ---------------------------------------------------------------------------
# topology group


class TopologyGroup:
    """reference topologygroup.go:56."""

    def __init__(
        self,
        topology_type: TopologyType,
        key: str,
        pod: Pod,
        namespaces: frozenset[str],
        selector: Optional[LabelSelector],
        max_skew: int,
        min_domains: Optional[int],
        taint_policy: Optional[NodeInclusionPolicy],
        affinity_policy: Optional[NodeInclusionPolicy],
        domain_group: Optional[TopologyDomainGroup],
    ):
        self.type = topology_type
        self.key = key
        self.namespaces = namespaces
        self.selector = selector
        self.max_skew = max_skew
        self.min_domains = min_domains
        if topology_type == TopologyType.SPREAD:
            self.node_filter = TopologyNodeFilter.for_pod(
                pod,
                taint_policy if taint_policy is not None else NodeInclusionPolicy.IGNORE,
                affinity_policy
                if affinity_policy is not None
                else NodeInclusionPolicy.HONOR,
            )
        else:
            self.node_filter = TopologyNodeFilter()  # always matches
        self.owners: set[str] = set()  # pod UIDs governed by this group
        self.domains: dict[str, int] = {}
        self.empty_domains: set[str] = set()
        if domain_group is not None:
            domain_group.for_each_domain(
                pod, self.node_filter.taint_policy, self._register_one
            )

    def _register_one(self, domain: str) -> None:
        if domain not in self.domains:
            self.domains[domain] = 0
            self.empty_domains.add(domain)

    # -- bookkeeping ------------------------------------------------------

    def record(self, *domains: str) -> None:
        for d in domains:
            self.domains[d] = self.domains.get(d, 0) + 1
            self.empty_domains.discard(d)

    def register(self, *domains: str) -> None:
        for d in domains:
            self._register_one(d)

    def unregister(self, *domains: str) -> None:
        for d in domains:
            self.domains.pop(d, None)
            self.empty_domains.discard(d)

    def add_owner(self, uid: str) -> None:
        self.owners.add(uid)

    def remove_owner(self, uid: str) -> None:
        self.owners.discard(uid)

    def is_owned_by(self, uid: str) -> bool:
        return uid in self.owners

    def selects(self, pod: Pod) -> bool:
        return pod.namespace in self.namespaces and (
            self.selector is not None and self.selector.matches(pod.metadata.labels)
        )

    def counts(
        self,
        pod: Pod,
        taints: Iterable[Taint],
        requirements: Requirements,
        allow_undefined: Optional[set] = None,
    ) -> bool:
        """Would this pod count for the topology if scheduled on a node with
        these requirements (topologygroup.go:150)."""
        return self.selects(pod) and self.node_filter.matches(
            taints, requirements, allow_undefined
        )

    def hash_key(self):
        """Structural identity for dedup (topologygroup.go:186 Hash). Unlike
        the reference we also include minDomains — two constraints differing
        only there should not share counts."""
        return (
            self.key,
            self.type,
            self.namespaces,
            self.max_skew,
            self.min_domains,
            self.node_filter.canonical(),
            _selector_canonical(self.selector),
        )

    # -- domain selection ---------------------------------------------------

    def get(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        if self.type == TopologyType.SPREAD:
            return self._next_domain_spread(pod, pod_domains, node_domains)
        if self.type == TopologyType.POD_AFFINITY:
            return self._next_domain_affinity(pod, pod_domains, node_domains)
        return self._next_domain_anti_affinity(pod_domains, node_domains)

    def _next_domain_spread(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        """topologygroup.go:226 nextDomainTopologySpread — pick the min-count
        node-reachable domain within maxSkew of the global min."""
        min_count = self._domain_min_count(pod_domains)
        self_selecting = self.selects(pod)

        # hostname special case: a new NodeClaim's hostname domain isn't
        # registered yet; global min is always 0 since we can mint a new node.
        # (Guarded to concrete In values; for complements .values holds the
        # *excluded* set.)
        if (
            self.key == well_known.HOSTNAME_LABEL_KEY
            and not node_domains.complement
            and len(node_domains.values) == 1
        ):
            hostname = next(iter(node_domains.values))
            count = self.domains.get(hostname, 0)
            if self_selecting:
                count += 1
            if count <= self.max_skew:
                return Requirement(self.key, Operator.IN, [hostname])
            return Requirement(self.key, Operator.DOES_NOT_EXIST)

        # Deterministic tie-break: the reference iterates Go maps (randomized
        # per iteration), so ties are unspecified there. We determinize to
        # sorted order; the TPU kernel assigns vocab ids in sorted order so the
        # two agree bit-for-bit.
        best_domain = None
        best_count = MAX_I32
        if node_domains.operator() == Operator.IN:
            candidates = (d for d in sorted(node_domains.values) if d in self.domains)
        else:
            candidates = (d for d in sorted(self.domains) if node_domains.has(d))
        for domain in candidates:
            count = self.domains[domain]
            if self_selecting:
                count += 1
            if count - min_count <= self.max_skew and count < best_count:
                best_domain = domain
                best_count = count
        if best_domain is None:
            return Requirement(self.key, Operator.DOES_NOT_EXIST)
        return Requirement(self.key, Operator.IN, [best_domain])

    def _domain_min_count(self, pod_domains: Requirement) -> int:
        """topologygroup.go:289 domainMinCount."""
        if self.key == well_known.HOSTNAME_LABEL_KEY:
            return 0
        min_count = MAX_I32
        supported = 0
        for domain, count in self.domains.items():
            if pod_domains.has(domain):
                supported += 1
                if count < min_count:
                    min_count = count
        if self.min_domains is not None and supported < self.min_domains:
            min_count = 0
        return min_count

    def _next_domain_affinity(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        """topologygroup.go:313 nextDomainAffinity."""
        options = Requirement(self.key, Operator.DOES_NOT_EXIST)

        if (
            self.key == well_known.HOSTNAME_LABEL_KEY
            and not node_domains.complement
            and len(node_domains.values) == 1
        ):
            hostname = next(iter(node_domains.values))
            if not pod_domains.has(hostname):
                return options
            if self.domains.get(hostname, 0) > 0:
                options.values.add(hostname)
                return options
            if self.selects(pod) and (
                len(self.domains) == len(self.empty_domains)
                or not self._any_compatible_pod_domain(pod_domains)
            ):
                options.values.add(hostname)
            return options

        if node_domains.operator() == Operator.IN:
            for domain in node_domains.values:
                if (
                    pod_domains.has(domain)
                    and self.domains.get(domain, 0) > 0
                ):
                    options.values.add(domain)
        else:
            for domain, count in self.domains.items():
                if pod_domains.has(domain) and count > 0 and node_domains.has(domain):
                    options.values.add(domain)
        if options.values:
            return options

        # bootstrap: self-selecting pod and either nothing scheduled yet or the
        # scheduled pods are incompatible with our pod domains
        if self.selects(pod) and (
            len(self.domains) == len(self.empty_domains)
            or not self._any_compatible_pod_domain(pod_domains)
        ):
            intersected = pod_domains.intersection(node_domains)
            for domain in sorted(self.domains):  # determinized (see spread)
                if intersected.has(domain):
                    options.values.add(domain)
                    break
            if not options.values:
                for domain in sorted(self.domains):
                    if pod_domains.has(domain):
                        options.values.add(domain)
                        break
        return options

    def _any_compatible_pod_domain(self, pod_domains: Requirement) -> bool:
        return any(
            pod_domains.has(d) and c > 0 for d, c in self.domains.items()
        )

    def _next_domain_anti_affinity(
        self, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        """topologygroup.go:393 nextDomainAntiAffinity — only empty domains."""
        options = Requirement(self.key, Operator.DOES_NOT_EXIST)
        if (
            self.key == well_known.HOSTNAME_LABEL_KEY
            and not node_domains.complement
            and len(node_domains.values) == 1
        ):
            hostname = next(iter(node_domains.values))
            if self.domains.get(hostname, 0) == 0:
                options.values.add(hostname)
            return options
        if (
            node_domains.operator() == Operator.IN
            and len(node_domains.values) < len(self.empty_domains)
        ):
            for domain in node_domains.values:
                if domain in self.empty_domains and pod_domains.has(domain):
                    options.values.add(domain)
        else:
            for domain in self.empty_domains:
                if node_domains.has(domain) and pod_domains.has(domain):
                    options.values.add(domain)
        return options


# ---------------------------------------------------------------------------
# cluster view for domain counting


class ClusterSource:
    """The slice of cluster state topology counting needs: existing scheduled
    pods (with their nodes) and node label/taint views. The control plane
    passes its state cache; benchmarks pass nothing (reference topology.go
    gets this from the kube client + state nodes)."""

    def __init__(
        self,
        pods_by_namespace: Optional[dict[str, list[Pod]]] = None,
        nodes_by_name: Optional[dict] = None,
        namespace_labels: Optional[dict[str, dict[str, str]]] = None,
    ):
        self.pods_by_namespace = pods_by_namespace or {}
        self.nodes_by_name = nodes_by_name or {}
        # namespace name -> labels, for namespaceSelector resolution
        self.namespace_labels = namespace_labels or {}

    def list_pods(self, namespace: str) -> list[Pod]:
        return self.pods_by_namespace.get(namespace, [])

    def get_node(self, name: str):
        return self.nodes_by_name.get(name)

    def pods_with_anti_affinity(self):
        for pods in self.pods_by_namespace.values():
            for p in pods:
                if p.pod_anti_affinity and p.node_name:
                    node = self.get_node(p.node_name)
                    if node is not None:
                        yield p, node


# ---------------------------------------------------------------------------
# Topology


class Topology:
    """reference topology.go:47."""

    def __init__(
        self,
        node_pools,
        instance_types_by_pool: dict,
        pods: list[Pod],
        cluster: Optional[ClusterSource] = None,
        state_node_views: Optional[list] = None,
        ignore_preferences: bool = False,
    ):
        self.cluster = cluster or ClusterSource()
        self.ignore_preferences = ignore_preferences
        self.domain_groups = build_domain_groups(node_pools, instance_types_by_pool)
        self.topology_groups: dict = {}
        self.inverse_topology_groups: dict = {}
        self.excluded_pods: set[str] = {p.uid for p in pods}
        # The namespace universe for namespaceSelector resolution: explicit
        # Namespace objects plus namespaces that exist implicitly because a
        # pod lives in them (in real Kubernetes the Namespace object always
        # exists; a sim need not create one). Implicit namespaces carry no
        # labels, so an empty match-all selector still finds them while a
        # label-matched selector correctly does not.
        self._namespace_universe: dict[str, dict[str, str]] = dict(
            self.cluster.namespace_labels
        )
        for ns in self.cluster.pods_by_namespace:
            self._namespace_universe.setdefault(ns, {})
        for p in pods:
            self._namespace_universe.setdefault(p.namespace, {})
        self._namespace_list_cache: dict = {}
        # label views of real nodes so countDomains can capture domains that
        # exist only on live nodes (topology.go:345-362)
        self.state_node_views = state_node_views or []

        for p, node in self.cluster.pods_with_anti_affinity():
            if p.uid in self.excluded_pods:
                continue
            self._update_inverse_anti_affinity(p, node.metadata.labels)
        for p in pods:
            self.update(p)

    # -- group construction -------------------------------------------------

    def update(self, pod: Pod) -> None:
        """(Re-)register the pod as owner of the topologies its current spec
        implies; called after relaxation to drop preferred constraints
        (topology.go:162 Update)."""
        for tg in self.topology_groups.values():
            tg.remove_owner(pod.uid)

        has_required_anti = bool(pod.pod_anti_affinity)
        has_any_anti = has_required_anti or bool(pod.pod_anti_affinity_preferred)
        if (self.ignore_preferences and has_required_anti) or (
            not self.ignore_preferences and has_any_anti
        ):
            self._update_inverse_anti_affinity(pod, None)

        groups = self._new_for_topologies(pod) + self._new_for_affinities(pod)
        for tg in groups:
            key = tg.hash_key()
            existing = self.topology_groups.get(key)
            if existing is None:
                self._count_domains(tg)
                self.topology_groups[key] = tg
                existing = tg
            existing.add_owner(pod.uid)

    def _build_namespace_list(
        self, pod_namespace: str, term: PodAffinityTerm
    ) -> frozenset[str]:
        """topology.go:503 buildNamespaceList: no namespaces and no selector
        -> the pod's namespace; explicit list without selector -> that list;
        a selector unions label-matched namespaces with the explicit list."""
        selector = getattr(term, "namespace_selector", None)
        if not term.namespaces and selector is None:
            return frozenset({pod_namespace})
        if selector is None:
            return frozenset(term.namespaces)
        # memoized per (selector, explicit list): identical replicas of one
        # deployment would otherwise rescan the namespace universe N times
        from karpenter_tpu.solver.ordering import _selector_key

        key = (_selector_key(selector), tuple(sorted(term.namespaces)))
        got = self._namespace_list_cache.get(key)
        if got is None:
            selected = {
                name
                for name, labels in self._namespace_universe.items()
                if selector.matches(labels)
            }
            selected.update(term.namespaces)
            got = frozenset(selected)
            self._namespace_list_cache[key] = got
        return got

    def _new_for_topologies(self, pod: Pod) -> list[TopologyGroup]:
        groups = []
        for tsc in pod.topology_spread_constraints:
            if (
                self.ignore_preferences
                and tsc.when_unsatisfiable != WhenUnsatisfiable.DO_NOT_SCHEDULE
            ):
                continue
            selector = tsc.label_selector
            if tsc.match_label_keys:
                # topology.go:434: fold the pod's own values for each
                # matchLabelKeys entry into the selector as In expressions,
                # scoping the spread to pods sharing those values (e.g. one
                # group per deployment revision)
                extra = [
                    LabelSelectorRequirement(
                        key=k, operator=Operator.IN, values=[pod.metadata.labels[k]]
                    )
                    for k in tsc.match_label_keys
                    if k in pod.metadata.labels
                ]
                if extra:
                    selector = LabelSelector(
                        match_labels=dict(selector.match_labels)
                        if selector
                        else {},
                        match_expressions=(
                            list(selector.match_expressions) if selector else []
                        )
                        + extra,
                    )
            groups.append(
                TopologyGroup(
                    TopologyType.SPREAD,
                    tsc.topology_key,
                    pod,
                    frozenset({pod.namespace}),
                    selector,
                    tsc.max_skew,
                    tsc.min_domains,
                    tsc.node_taints_policy,
                    tsc.node_affinity_policy,
                    self.domain_groups.get(tsc.topology_key),
                )
            )
        return groups

    def _new_for_affinities(self, pod: Pod) -> list[TopologyGroup]:
        groups = []
        terms: list[tuple[TopologyType, PodAffinityTerm]] = [
            (TopologyType.POD_AFFINITY, t) for t in pod.pod_affinity
        ]
        if not self.ignore_preferences:
            terms += [
                (TopologyType.POD_AFFINITY, w.term) for w in pod.pod_affinity_preferred
            ]
        terms += [(TopologyType.POD_ANTI_AFFINITY, t) for t in pod.pod_anti_affinity]
        if not self.ignore_preferences:
            terms += [
                (TopologyType.POD_ANTI_AFFINITY, w.term)
                for w in pod.pod_anti_affinity_preferred
            ]
        for topology_type, term in terms:
            namespaces = self._build_namespace_list(pod.namespace, term)
            groups.append(
                TopologyGroup(
                    topology_type,
                    term.topology_key,
                    pod,
                    namespaces,
                    term.label_selector,
                    MAX_I32,
                    None,
                    None,
                    None,
                    self.domain_groups.get(term.topology_key),
                )
            )
        return groups

    def _update_inverse_anti_affinity(
        self, pod: Pod, node_labels: Optional[dict]
    ) -> None:
        """Track pods with anti-affinity so we can avoid scheduling their
        targets near them (topology.go:297). Only required terms."""
        for term in pod.pod_anti_affinity:
            namespaces = self._build_namespace_list(pod.namespace, term)
            tg = TopologyGroup(
                TopologyType.POD_ANTI_AFFINITY,
                term.topology_key,
                pod,
                namespaces,
                term.label_selector,
                MAX_I32,
                None,
                None,
                None,
                self.domain_groups.get(term.topology_key),
            )
            key = tg.hash_key()
            existing = self.inverse_topology_groups.get(key)
            if existing is None:
                self.inverse_topology_groups[key] = tg
            else:
                tg = existing
            if node_labels and tg.key in node_labels:
                tg.record(node_labels[tg.key])
            tg.add_owner(pod.uid)

    def _count_domains(self, tg: TopologyGroup) -> None:
        """Seed a new group with existing-cluster pod counts
        (topology.go:328 countDomains)."""
        # capture domains only present on live nodes
        for view in self.state_node_views:
            if view.node_labels is None:
                continue
            if not tg.node_filter.matches(
                view.taints, Requirements.from_labels(view.node_labels)
            ):
                continue
            domain = view.node_labels.get(tg.key)
            if domain is not None:
                tg.register(domain)

        for namespace in tg.namespaces:
            for p in self.cluster.list_pods(namespace):
                if not p.node_name or p.phase in ("Succeeded", "Failed") or p.terminating:
                    continue
                if p.uid in self.excluded_pods:
                    continue
                if tg.selector is None or not tg.selector.matches(p.metadata.labels):
                    continue
                node = self.cluster.get_node(p.node_name)
                if node is None:
                    continue
                domain = node.metadata.labels.get(tg.key)
                if domain is None and tg.key == well_known.HOSTNAME_LABEL_KEY:
                    domain = node.name
                if domain is None:
                    continue
                if not tg.node_filter.matches(
                    node.taints, Requirements.from_labels(node.metadata.labels)
                ):
                    continue
                tg.record(domain)

    # -- solve-time interface -------------------------------------------------

    def add_requirements(
        self,
        pod: Pod,
        taints: Iterable[Taint],
        pod_requirements: Requirements,
        node_requirements: Requirements,
        allow_undefined: Optional[set] = None,
    ) -> tuple[Optional[Requirements], Optional[str]]:
        """Tighten node requirements with the next viable domain per matching
        topology (topology.go:226 AddRequirements). Returns (requirements,
        error)."""
        requirements = Requirements(node_requirements.values())
        for tg in self._matching_topologies(pod, taints, node_requirements, allow_undefined):
            pod_domains = (
                pod_requirements.get(tg.key)
                if pod_requirements.has(tg.key)
                else Requirement(tg.key, Operator.EXISTS)
            )
            node_domains = (
                node_requirements.get(tg.key)
                if node_requirements.has(tg.key)
                else Requirement(tg.key, Operator.EXISTS)
            )
            domains = tg.get(pod, pod_domains, node_domains)
            if len(domains) == 0:
                counts = dict(sorted(tg.domains.items())[:25])
                return None, (
                    f"unsatisfiable topology constraint for {tg.type}, key={tg.key} "
                    f"(counts = {counts}, podDomains = {pod_domains!r}, "
                    f"nodeDomains = {node_domains!r})"
                )
            requirements.add(domains)
        return requirements, None

    def record(
        self,
        pod: Pod,
        taints: Iterable[Taint],
        requirements: Requirements,
        allow_undefined: Optional[set] = None,
    ) -> None:
        """Commit domain counts after a pod lands (topology.go:197 Record)."""
        for tg in self.topology_groups.values():
            if tg.counts(pod, taints, requirements, allow_undefined):
                domains = requirements.get(tg.key)
                if tg.type == TopologyType.POD_ANTI_AFFINITY:
                    tg.record(*domains.values)
                elif len(domains) == 1:
                    tg.record(next(iter(domains.values)))
        for tg in self.inverse_topology_groups.values():
            if tg.is_owned_by(pod.uid):
                tg.record(*requirements.get(tg.key).values)

    def register(self, topology_key: str, domain: str) -> None:
        for tg in self.topology_groups.values():
            if tg.key == topology_key:
                tg.register(domain)
        for tg in self.inverse_topology_groups.values():
            if tg.key == topology_key:
                tg.register(domain)

    def unregister(self, topology_key: str, domain: str) -> None:
        for tg in self.topology_groups.values():
            if tg.key == topology_key:
                tg.unregister(domain)
        for tg in self.inverse_topology_groups.values():
            if tg.key == topology_key:
                tg.unregister(domain)

    def _matching_topologies(
        self,
        pod: Pod,
        taints: Iterable[Taint],
        requirements: Requirements,
        allow_undefined: Optional[set],
    ) -> list[TopologyGroup]:
        """Groups owning the pod + inverse groups whose owners' anti-affinity
        selects the pod (topology.go:528 getMatchingTopologies)."""
        out = [
            tg for tg in self.topology_groups.values() if tg.is_owned_by(pod.uid)
        ]
        out += [
            tg
            for tg in self.inverse_topology_groups.values()
            if tg.counts(pod, taints, requirements, allow_undefined)
        ]
        return out
