"""The shared FFD pod ordering, with a class-grouped tie-break.

The reference sorts pods by CPU-then-memory descending, breaking ties by
creation timestamp then UID (queue.go:72-108). The tie-break is pure
determinism — any total order over equal-request pods yields a valid
first-fit-decreasing run. This framework inserts one extra key between the
requests and the timestamp: a *scheduling-class signature*, a hash of every
pod field that influences the scheduler's per-pod decision (requirements,
constraints, tolerations — NOT the pod's own labels, which only affect what
the pod records into topology counts, never where it can go).

Why: pods of the same class become contiguous in the solve order, which
lets the TPU kernel evaluate a class once and bulk-commit whole runs of
identical pods per device step (solver/tpu_kernel.py run scan) instead of
one pod per step. The oracle uses the same comparator, so oracle/TPU parity
is preserved exactly.
"""

from __future__ import annotations

import zlib

from karpenter_tpu.api.objects import Pod, PodAffinityTerm
from karpenter_tpu.utils import resources as res


def _selector_key(sel) -> tuple:
    if sel is None:
        return ()
    return (
        tuple(sorted(sel.match_labels.items())),
        tuple(
            (e.key, str(e.operator), tuple(sorted(e.values)))
            for e in sel.match_expressions
        ),
    )


def _term_key(t: PodAffinityTerm, pod: Pod) -> tuple:
    sel = t.label_selector
    return (
        t.topology_key,
        _selector_key(sel),
        tuple(sorted(t.namespaces or ())),
        _selector_key(getattr(t, "namespace_selector", None)),
        # whether the term selects the pod itself changes the decision
        # (self-counting in skew math), so it is part of the class
        bool(sel is not None and sel.matches(pod.metadata.labels)),
    )


def pod_class_key(pod: Pod) -> tuple:
    """The canonical tuple of every decision-relevant pod field. Two pods
    with equal keys and equal requests make identical scheduling decisions
    against any solver state (their labels may still differ — labels only
    drive topology-count records, which the kernel applies per pod).
    Memoized on the pod object: the sort and the encoder both consult it
    for every pod of every solve. Dedup uses THIS tuple (exact equality);
    the crc in pod_class_signature is only a sort tie-break, where a
    collision merely reorders ties."""
    cached = getattr(pod, "_ktpu_class_key", None)
    if cached is not None:
        return cached
    na = pod.node_affinity
    key = (
        pod.namespace,
        tuple(sorted(pod.node_selector.items())),
        tuple(
            (
                tuple(
                    (e.key, str(e.operator), tuple(sorted(e.values)))
                    for e in term.match_expressions
                ),
            )
            for term in (na.required_terms if na else ())
        ),
        tuple(
            (
                w.weight,
                tuple(
                    (e.key, str(e.operator), tuple(sorted(e.values)))
                    for e in w.preference.match_expressions
                ),
            )
            for w in (na.preferred if na else ())
        ),
        tuple(_term_key(t, pod) for t in pod.pod_affinity),
        tuple(_term_key(t, pod) for t in pod.pod_anti_affinity),
        tuple(
            (w.weight,) + _term_key(w.term, pod) for w in pod.pod_affinity_preferred
        ),
        tuple(
            (w.weight,) + _term_key(w.term, pod)
            for w in pod.pod_anti_affinity_preferred
        ),
        tuple(
            (t.key, t.operator, t.value, str(t.effect)) for t in pod.tolerations
        ),
        tuple(
            (
                t.topology_key,
                t.max_skew,
                str(t.when_unsatisfiable),
                _selector_key(t.label_selector),
                t.min_domains,
                str(t.node_taints_policy),
                str(t.node_affinity_policy),
                bool(
                    t.label_selector is not None
                    and t.label_selector.matches(pod.metadata.labels)
                ),
                tuple(
                    (k, pod.metadata.labels.get(k))
                    for k in getattr(t, "match_label_keys", ())
                ),
            )
            for t in pod.topology_spread_constraints
        ),
        tuple(sorted(pod.host_ports)),
        tuple(sorted(pod.volume_claims)),
    )
    try:
        pod._ktpu_class_key = key
    except AttributeError:
        pass  # frozen/slotted pods just recompute
    return key


def pod_class_signature(pod: Pod) -> int:
    """A 32-bit digest of pod_class_key for the FFD sort tie-break only —
    stable across processes (unlike hash()); collisions just group ties
    differently, never merge distinct classes."""
    cached = getattr(pod, "_ktpu_class_sig", None)
    if cached is not None:
        return cached
    sig = zlib.crc32(repr(pod_class_key(pod)).encode())
    try:
        pod._ktpu_class_sig = sig
    except AttributeError:
        pass
    return sig


def pod_encode_class(pod: Pod, requests) -> tuple:
    """Key under which pods share identical solver encodings: the full
    canonical class tuple plus the exact request vector (exact equality —
    no hashing on the dedup path)."""
    return (pod_class_key(pod), tuple(sorted(requests.items())))


def ffd_sort_key(pod: Pod, requests: res.ResourceList):
    """queue.go:72 FFD order + class-grouped tie-break (module docstring)."""
    return (
        -requests.get(res.CPU, 0),
        -requests.get(res.MEMORY, 0),
        pod_class_signature(pod),
        pod.metadata.creation_timestamp,
        pod.uid,
    )


def ffd_order(pods: list[Pod], requests_of) -> list:
    """Vectorized FFD ordering: identical total order to sorting by
    ffd_sort_key (np.lexsort and Python sort are both stable over the same
    keys), built from flat arrays so a 50k-pod solve does not pay a
    per-pod tuple construction. `requests_of(pod)` returns the cached
    ResourceList."""
    import numpy as np

    from karpenter_tpu.utils import resources as res

    n = len(pods)
    if n <= 1:
        return list(range(n))
    cpu = np.empty(n, np.int64)
    mem = np.empty(n, np.int64)
    sig = np.empty(n, np.int64)
    ts = np.empty(n, np.float64)
    uid = np.empty(n, dtype=object)
    for i, p in enumerate(pods):
        r = requests_of(p)
        cpu[i] = r.get(res.CPU, 0)
        mem[i] = r.get(res.MEMORY, 0)
        sig[i] = pod_class_signature(p)
        ts[i] = p.metadata.creation_timestamp
        uid[i] = p.uid
    # least-significant key first. The uid dtype is sized to the longest
    # uid present: a fixed width would silently truncate caller-set uids
    # and break the REQUIRED equivalence with ffd_sort_key's full-string
    # comparison (tests/test_requirements.py pins the equivalence).
    width = max(len(u) for u in uid)
    order = np.lexsort((uid.astype(f"U{width}"), ts, sig, -mem, -cpu))
    return order.tolist()
