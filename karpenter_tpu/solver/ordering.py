"""The shared FFD pod ordering, with a class-grouped tie-break.

The reference sorts pods by CPU-then-memory descending, breaking ties by
creation timestamp then UID (queue.go:72-108). The tie-break is pure
determinism — any total order over equal-request pods yields a valid
first-fit-decreasing run. This framework inserts one extra key between the
requests and the timestamp: a *scheduling-class signature*, a hash of every
pod field that influences the scheduler's per-pod decision (requirements,
constraints, tolerations — NOT the pod's own labels, which only affect what
the pod records into topology counts, never where it can go).

Why: pods of the same class become contiguous in the solve order, which
lets the TPU kernel evaluate a class once and bulk-commit whole runs of
identical pods per device step (solver/tpu_kernel.py run scan) instead of
one pod per step. The oracle uses the same comparator, so oracle/TPU parity
is preserved exactly.
"""

from __future__ import annotations

import zlib

from karpenter_tpu.api.objects import Pod, PodAffinityTerm
from karpenter_tpu.utils import resources as res


# Enum-or-string normalizer: the API enums subclass str, so str.__str__
# returns the underlying value for both an enum member and the plain string
# the wire codec decodes it to — a pod must land in the same class either
# way (the sidecar reconstructs pods from JSON; tests/test_service.py pins
# remote == in-process packing). One C call, unlike f-strings or .value.
_es = str.__str__


def _opt(x):
    return None if x is None else _es(x)


def _selector_key(sel) -> tuple:
    if sel is None:
        return ()
    ml = sel.match_labels
    me = sel.match_expressions
    return (
        tuple(sorted(ml.items())) if ml else (),
        tuple((e.key, _es(e.operator), tuple(sorted(e.values))) for e in me)
        if me
        else (),
    )


def _term_key(t: PodAffinityTerm, pod: Pod) -> tuple:
    sel = t.label_selector
    return (
        t.topology_key,
        _selector_key(sel),
        tuple(sorted(t.namespaces)) if t.namespaces else (),
        _selector_key(t.namespace_selector),
        # whether the term selects the pod itself changes the decision
        # (self-counting in skew math), so it is part of the class
        bool(sel is not None and sel.matches(pod.metadata.labels)),
    )


def pod_class_key(pod: Pod) -> tuple:
    """The canonical tuple of every decision-relevant pod field. Two pods
    with equal keys and equal requests make identical scheduling decisions
    against any solver state (their labels may still differ — labels only
    drive topology-count records, which the kernel applies per pod).
    Memoized on the pod object: the sort and the encoder both consult it
    for every pod of every solve. Dedup uses repr bytes of THIS tuple
    (pod_class_repr — every element has a faithful repr); the crc in
    pod_class_signature is only a sort tie-break, where a collision merely
    reorders ties.

    Enum-valued fields are normalized to their plain string values via _es
    (str.__str__ — one C call; the former str() enum formatting was a
    measured hot spot at 50k pods) so a wire-decoded pod lands in the same
    class as its in-process twin. Empty constraint lists short-circuit to
    () for the same reason: most pods of a big batch carry no affinity/TSC
    at all."""
    cached = getattr(pod, "_ktpu_class_key", None)
    if cached is not None:
        return cached
    na = pod.node_affinity
    labels = pod.metadata.labels
    key = (
        pod.namespace,
        tuple(sorted(pod.node_selector.items())) if pod.node_selector else (),
        tuple(
            (
                tuple(
                    (e.key, _es(e.operator), tuple(sorted(e.values)))
                    for e in term.match_expressions
                ),
            )
            for term in na.required_terms
        )
        if na is not None and na.required_terms
        else (),
        tuple(
            (
                w.weight,
                tuple(
                    (e.key, _es(e.operator), tuple(sorted(e.values)))
                    for e in w.preference.match_expressions
                ),
            )
            for w in na.preferred
        )
        if na is not None and na.preferred
        else (),
        tuple(_term_key(t, pod) for t in pod.pod_affinity)
        if pod.pod_affinity
        else (),
        tuple(_term_key(t, pod) for t in pod.pod_anti_affinity)
        if pod.pod_anti_affinity
        else (),
        tuple(
            (w.weight,) + _term_key(w.term, pod) for w in pod.pod_affinity_preferred
        )
        if pod.pod_affinity_preferred
        else (),
        tuple(
            (w.weight,) + _term_key(w.term, pod)
            for w in pod.pod_anti_affinity_preferred
        )
        if pod.pod_anti_affinity_preferred
        else (),
        tuple(
            (t.key, _opt(t.operator), t.value, _opt(t.effect))
            for t in pod.tolerations
        )
        if pod.tolerations
        else (),
        tuple(
            (
                t.topology_key,
                t.max_skew,
                _es(t.when_unsatisfiable),
                _selector_key(t.label_selector),
                t.min_domains,
                _opt(t.node_taints_policy),
                _opt(t.node_affinity_policy),
                bool(
                    t.label_selector is not None
                    and t.label_selector.matches(labels)
                ),
                tuple((k, labels.get(k)) for k in t.match_label_keys)
                if t.match_label_keys
                else (),
            )
            for t in pod.topology_spread_constraints
        )
        if pod.topology_spread_constraints
        else (),
        tuple(sorted(pod.host_ports)) if pod.host_ports else (),
        tuple(sorted(pod.volume_claims)) if pod.volume_claims else (),
    )
    try:
        pod._ktpu_class_key = key
    except AttributeError:
        pass  # frozen/slotted pods just recompute
    return key


def pod_class_repr(pod: Pod) -> bytes:
    """Canonical byte serialization of pod_class_key — the dedup dict key.
    Python tuples re-hash their full contents on every dict lookup; bytes
    hash in C once, which is what makes 50k-pod class dedup a non-event.
    repr is faithful for everything the key contains (str, int, bool,
    (str, Enum) members, nested tuples), so equal reprs == equal keys."""
    cached = getattr(pod, "_ktpu_class_repr", None)
    if cached is not None:
        return cached
    out = repr(pod_class_key(pod)).encode()
    try:
        pod._ktpu_class_repr = out
    except AttributeError:
        pass
    return out


def pod_class_signature(pod: Pod) -> int:
    """A 32-bit digest of pod_class_key for the FFD sort tie-break only —
    stable across processes (unlike hash()); collisions just group ties
    differently, never merge distinct classes."""
    cached = getattr(pod, "_ktpu_class_sig", None)
    if cached is not None:
        return cached
    sig = zlib.crc32(pod_class_repr(pod))
    try:
        pod._ktpu_class_sig = sig
    except AttributeError:
        pass
    return sig


def pod_encode_class(pod: Pod, requests) -> tuple:
    """Key under which pods share identical solver encodings: the class
    repr bytes plus the exact request vector (exact equality — no hashing
    on the dedup path)."""
    return (pod_class_repr(pod), tuple(sorted(requests.items())))


def ffd_sort_key(pod: Pod, requests: res.ResourceList):
    """queue.go:72 FFD order + class-grouped tie-break (module docstring)."""
    return (
        -requests.get(res.CPU, 0),
        -requests.get(res.MEMORY, 0),
        pod_class_signature(pod),
        pod.metadata.creation_timestamp,
        pod.uid,
    )


def ffd_order_cols(cpu, mem, sig, ts_list: list, uids: list) -> list:
    """Vectorized FFD ordering from pre-built columns: identical total
    order to sorting by ffd_sort_key (np.lexsort and Python sort are both
    stable over the same keys). cpu/mem/sig are int arrays; ts_list/uids
    are plain Python lists (timestamps may be ints wider than float64 —
    see below)."""
    import numpy as np

    n = len(uids)
    if n <= 1:
        return list(range(n))
    ts = np.asarray(ts_list, dtype=np.float64)
    # Integer timestamps above 2^53 (nanosecond epochs) don't round-trip
    # through float64; a lossy column would diverge from ffd_sort_key's
    # exact tuple comparison that the parity contract pins. Verify the
    # round-trip and fall back to the exact Python sort when it fails.
    if ts.tolist() != ts_list:
        order = sorted(
            range(n),
            key=lambda i: (-int(cpu[i]), -int(mem[i]), int(sig[i]), ts_list[i], uids[i]),
        )
        return order
    # least-significant key first. The uid dtype is sized to the longest
    # uid present: a fixed width would silently truncate caller-set uids
    # and break the REQUIRED equivalence with ffd_sort_key's full-string
    # comparison (tests/test_requirements.py pins the equivalence).
    uid = np.array(uids, dtype=object)
    width = max(len(u) for u in uids)
    order = np.lexsort((uid.astype(f"U{width}"), ts, sig, -np.asarray(mem), -np.asarray(cpu)))
    return order.tolist()


def ffd_order(pods: list[Pod], requests_of) -> list:
    """ffd_order_cols over columns gathered from pod objects.
    `requests_of(pod)` returns the cached ResourceList."""
    import numpy as np

    from karpenter_tpu.utils import resources as res

    n = len(pods)
    if n <= 1:
        return list(range(n))
    cpu = np.empty(n, np.int64)
    mem = np.empty(n, np.int64)
    sig = np.empty(n, np.int64)
    ts_list = [0.0] * n
    uids = [""] * n
    for i, p in enumerate(pods):
        r = requests_of(p)
        cpu[i] = r.get(res.CPU, 0)
        mem[i] = r.get(res.MEMORY, 0)
        sig[i] = pod_class_signature(p)
        ts_list[i] = p.metadata.creation_timestamp
        uids[i] = p.uid
    return ffd_order_cols(cpu, mem, sig, ts_list, uids)
