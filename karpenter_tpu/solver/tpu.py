"""TpuScheduler: the TPU-native batched solver with oracle fallback.

Drop-in for karpenter_tpu.solver.oracle.Scheduler (same constructor, same
solve() -> Results), implementing SURVEY.md §7 M3/M4: the whole scheduling
problem is encoded once into dense tensors (solver/tpu_problem.py) and a
jitted lax.scan packs pods at device speed (solver/tpu_kernel.py), while
the host only sorts pods, pads shapes, and decodes results.

Fidelity contract: for supported problems the per-pod decisions (which
existing node / in-flight claim / new template, in first-fit order) are
bit-identical to the oracle — tests/test_tpu_parity.py enforces this against
randomized problem mixes, including the reference benchmark's diverse pod
classes (scheduling_benchmark_test.go:257 makeDiversePods). Unsupported
features (preference relaxation, host ports, reserved capacity, hostname
selectors, exotic topology filters) raise UnsupportedBySolver at encode
time; Solver.solve() then falls back to the oracle — the hybrid dispatch.

The queue progress loop (scheduler.go:380 "schedule again if progress was
made") maps to outer rounds: failed pods are re-submitted against the
carried device state while any round schedules at least one pod — provably
equivalent to the reference's requeue-at-end + stall detection.
"""

from __future__ import annotations

import itertools
import time as time_mod
from typing import Optional

import numpy as np

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import NodePool, Operator, Pod
from karpenter_tpu.cloudprovider.types import InstanceTypes
from karpenter_tpu.ops.encode import Reqs, decode_row
from karpenter_tpu.ops.kernels import VocabArrays
from karpenter_tpu.scheduling import Requirement, Requirements
from karpenter_tpu.solver.nodes import (
    SchedulingNodeClaim,
    StateNodeView,
    filter_instance_types,
)
from karpenter_tpu.solver.oracle import Results, Scheduler, SchedulerOptions
from karpenter_tpu.solver.topology import Topology
from karpenter_tpu.solver.tpu_problem import (
    EncodedProblem,
    UnsupportedBySolver,
    encode_problem,
)
from karpenter_tpu.utils import resources as res

_claim_seq = itertools.count(1)


def _typeok_chunk_impl(ireq, va, preq_chunk, iw: int):
    """[B, IW] u32: pairwise pod-vs-type requirement intersection bits."""
    import jax.numpy as jnp

    from karpenter_tpu.ops.kernels import intersects_only

    B = preq_chunk.mask.shape[0]
    I = ireq.mask.shape[0]
    a = Reqs(*(x[None, :] for x in ireq))  # [1, I, ...]
    b = Reqs(*(x[:, None] for x in preq_chunk))  # [B, 1, ...]
    ok = intersects_only(a, b, va)  # [B, I]
    pad = jnp.zeros((B, iw * 32 - I), bool)
    bits = jnp.concatenate([ok, pad], axis=-1).reshape(B, iw, 32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, None]
    return jnp.sum(bits.astype(jnp.uint32) * weights, axis=-1, dtype=jnp.uint32)


_typeok_chunk_cached = None


def _typeok_chunk(ireq, va, preq_chunk, iw: int):
    """Module-level jit cache (a per-call closure would recompile every
    solve)."""
    global _typeok_chunk_cached
    if _typeok_chunk_cached is None:
        import jax

        _typeok_chunk_cached = jax.jit(
            _typeok_chunk_impl, static_argnames=("iw",)
        )
    return _typeok_chunk_cached(ireq, va, preq_chunk, iw=iw)


def _pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


class TpuScheduler:
    """Same surface as oracle.Scheduler, solving on the accelerator."""

    def __init__(
        self,
        node_pools: list[NodePool],
        instance_types_by_pool: dict[str, InstanceTypes],
        topology: Topology,
        state_nodes: Optional[list[StateNodeView]] = None,
        daemonset_pods: Optional[list[Pod]] = None,
        options: Optional[SchedulerOptions] = None,
    ):
        # reuse the oracle's init wholesale: template filtering, daemon
        # overhead, existing-node ordering, limits (scheduler.go:116)
        self.oracle = Scheduler(
            node_pools,
            instance_types_by_pool,
            topology,
            state_nodes,
            daemonset_pods,
            options,
        )
        self.opts = self.oracle.opts

    # -- solve ----------------------------------------------------------

    def solve(self, pods: list[Pod]) -> Results:
        """May raise UnsupportedBySolver; Solver wrappers catch and fall
        back to the oracle."""
        import jax  # deferred so encoding errors surface first

        problem = encode_problem(self.oracle, pods)
        deadline = (
            time_mod.monotonic() + self.opts.timeout_seconds
            if self.opts.timeout_seconds
            else None
        )

        # FFD order (queue.go:72): cpu desc, memory desc, creation, uid
        data = self.oracle.cached_pod_data
        for p in pods:
            self.oracle._update_cached_pod_data(p)
        order = sorted(
            range(len(pods)),
            key=lambda i: (
                -data[pods[i].uid].requests.get(res.CPU, 0),
                -data[pods[i].uid].requests.get(res.MEMORY, 0),
                pods[i].metadata.creation_timestamp,
                pods[i].uid,
            ),
        )

        from karpenter_tpu.solver import tpu_kernel as K

        tb = self._tables(problem)
        self._typeok = self._pod_typeok(problem, tb)

        # Claim slots: most solves create far fewer claims than pods (the
        # bench mix averages ~5 pods/claim), so start small and grow on the
        # kernel's overflow signal — smaller N cuts every per-step candidate
        # screen. Worst case (one pod per claim) ends at _pow2(len(pods)).
        N = min(_pow2(max(64, (len(pods) + 3) // 4)), _pow2(len(pods)))
        while True:
            st = self._init_state(problem, N)
            kinds = np.full(len(pods), K.KIND_FAIL, dtype=np.int32)
            slots = np.full(len(pods), -1, dtype=np.int32)
            pending = list(order)
            timed_out = False
            overflowed = False
            while pending:
                if deadline is not None and time_mod.monotonic() > deadline:
                    timed_out = True
                    break
                xs = self._pod_xs(problem, pending)
                st, got_kinds, got_slots, got_over = K.solve_scan(tb, st, xs)
                # one batched device->host fetch (the tunnel charges per call)
                got_kinds, got_slots, got_over = jax.device_get(
                    (got_kinds, got_slots, got_over)
                )
                if bool(got_over):
                    overflowed = True
                    break
                got_kinds = got_kinds[: len(pending)]
                got_slots = got_slots[: len(pending)]
                kinds[pending] = got_kinds
                slots[pending] = got_slots
                failed = [i for i, k in zip(pending, got_kinds) if k == K.KIND_FAIL]
                if len(failed) == len(pending):
                    break  # no progress: stall (queue.go:52)
                pending = failed
            if not overflowed:
                break
            N *= 2  # slots exhausted: re-solve from scratch with room

        return self._decode(problem, st, kinds, slots, timed_out)

    def _pod_typeok(self, p: EncodedProblem, tb) -> np.ndarray:
        """[P, IW] u32 — per pod, the instance types whose requirements
        intersect the pod's (pairwise screen; the kernel's while_loop stays
        exact for three-way intersections, offerings, and minValues)."""
        import jax.numpy as jnp

        I = p.num_types
        IW = max(1, (I + 31) // 32)
        P = len(p.pods)
        out = np.zeros((P, IW), dtype=np.uint32)
        CH = 2048
        for lo in range(0, P, CH):
            hi = min(lo + CH, P)
            # pow2-pad chunks so compiled shapes are reused across solves
            pad_to = min(CH, _pow2(hi - lo))
            idx = np.arange(lo, lo + pad_to) % P
            chunk = Reqs(*(jnp.asarray(a[idx]) for a in p.preq))
            got = np.asarray(_typeok_chunk(tb.ireq, tb.va, chunk, iw=IW))
            out[lo:hi] = got[: hi - lo]
        return out

    # -- tensor construction --------------------------------------------

    def _tables(self, p: EncodedProblem):
        import jax.numpy as jnp

        from karpenter_tpu.solver import tpu_kernel as K

        def pad_group_v(a, fill=0):
            if a.shape[0] == 0:
                return jnp.asarray(
                    np.full((1,) + a.shape[1:], fill, dtype=a.dtype)
                )
            return jnp.asarray(a)

        Gv, Gh = len(p.vgroups), len(p.hgroups)
        va = VocabArrays.from_vocab(p.vocab)
        v_anti = np.array(
            [g.group.type.value == 2 for g in p.vgroups], dtype=bool
        ).reshape(Gv)
        h_inverse = np.array([g.inverse for g in p.hgroups], dtype=bool).reshape(Gh)
        jreq = lambda r: Reqs(*(jnp.asarray(a) for a in r))

        def pad_reqs_rows(r: Reqs) -> Reqs:
            if r.mask.shape[0] > 0:
                return jreq(r)
            return Reqs(
                *(
                    jnp.asarray(np.zeros((0,) + a.shape[1:], dtype=a.dtype))
                    for a in r
                )
            )

        return K.Tables(
            va=va,
            treq=jreq(p.treq),
            tdaemon=jnp.asarray(p.tdaemon),
            ttypes=jnp.asarray(p.ttypes),
            tlimit_def=jnp.asarray(p.tlimit_def),
            thas_limits=jnp.asarray(p.thas_limits),
            ireq=jreq(p.ireq),
            ialloc=jnp.asarray(p.ialloc),
            icap=jnp.asarray(p.icap),
            otype=jnp.asarray(p.otype),
            oword=jnp.asarray(p.oword),
            obit=jnp.asarray(p.obit),
            v_kid=pad_group_v(p.v_kid),
            v_word=pad_group_v(p.v_word, fill=-1),
            v_bit=pad_group_v(p.v_bit),
            v_reg=pad_group_v(p.v_reg, fill=False),
            v_skew=pad_group_v(p.v_skew),
            v_mindom=pad_group_v(p.v_mindom, fill=-1),
            v_filt=pad_group_v(p.v_filt, fill=-1),
            v_anti=pad_group_v(v_anti, fill=False),
            h_skew=pad_group_v(p.h_skew),
            h_filt=pad_group_v(p.h_filt, fill=-1),
            h_inverse=pad_group_v(h_inverse, fill=False),
            filter_reqs=pad_reqs_rows(p.filter_reqs),
        )

    def _init_state(self, p: EncodedProblem, N: int):
        import jax.numpy as jnp

        from karpenter_tpu.ops.encode import empty_reqs
        from karpenter_tpu.solver import tpu_kernel as K

        vocab, table = p.vocab, p.table
        R = table.num_resources
        I = p.num_types
        IW = max(1, (I + 31) // 32)
        E = p.num_existing
        Gv = max(len(p.vgroups), 1)
        Gh = max(len(p.hgroups), 1)
        S = E + N
        creq = empty_reqs(vocab, (N,))
        jreq = lambda r: Reqs(*(jnp.asarray(a) for a in r))
        v_cnt = (
            p.v_cnt if len(p.vgroups) else np.zeros((1, p.vmax or 1), np.int32)
        )
        h_cnt = np.zeros((Gh, S), np.int32)
        for g, slot, c in p.h_seed:
            h_cnt[g, slot] += c
        return K.State(
            active=jnp.zeros(N, bool),
            count=jnp.zeros(N, jnp.int32),
            rank=jnp.zeros(N, jnp.int32),
            tmpl=jnp.zeros(N, jnp.int32),
            creq=jreq(creq),
            crequests=jnp.zeros((N, R), jnp.int32),
            alive=jnp.zeros((N, IW), jnp.uint32),
            cmax_alloc=jnp.zeros((N, R), jnp.int32),
            n_claims=jnp.zeros((), jnp.int32),
            ereq=jreq(p.ereq),
            eavail=jnp.asarray(p.eavail),
            trem=jnp.asarray(p.tlimit_rem),
            v_cnt=jnp.asarray(v_cnt),
            h_cnt=jnp.asarray(h_cnt),
        )

    def _pod_xs(self, p: EncodedProblem, indices: list[int]):
        import jax.numpy as jnp

        from karpenter_tpu.solver import tpu_kernel as K

        n = len(indices)
        P_pad = _pow2(n)
        idx = np.array(indices + [0] * (P_pad - n), dtype=np.int32)
        valid = np.zeros(P_pad, bool)
        valid[:n] = True
        Gv = max(len(p.vgroups), 1)
        Gh = max(len(p.hgroups), 1)

        def pad_g(a, G):
            if a.shape[1] == G:
                return a[idx]
            return np.zeros((P_pad, G), a.dtype)

        return K.PodX(
            preq=Reqs(*(jnp.asarray(a[idx]) for a in p.preq)),
            prequests=jnp.asarray(p.prequests[idx]),
            typeok=jnp.asarray(self._typeok[idx]),
            tol_t=jnp.asarray(p.ptol_t[idx]),
            tol_e=jnp.asarray(p.ptol_e[idx]),
            topo_kind=jnp.asarray(p.ptopo_kind[idx]),
            topo_gid=jnp.asarray(p.ptopo_gid[idx]),
            topo_sel=jnp.asarray(p.ptopo_sel[idx]),
            sel_v=jnp.asarray(pad_g(p.psel_v, Gv)),
            sel_h=jnp.asarray(pad_g(p.psel_h, Gh)),
            inv_h=jnp.asarray(pad_g(p.pinv_h, Gh)),
            own_h=jnp.asarray(pad_g(p.pown_h, Gh)),
            valid=jnp.asarray(valid),
        )

    # -- decoding --------------------------------------------------------

    def _decode(
        self,
        p: EncodedProblem,
        st,
        kinds: np.ndarray,
        slots: np.ndarray,
        timed_out: bool,
    ) -> Results:
        import jax

        from karpenter_tpu.solver import tpu_kernel as K

        vocab, table = p.vocab, p.table
        scheduler = self.oracle
        # one batched device->host fetch of everything decode reads
        st = jax.device_get(st)
        n_claims = int(st.n_claims)
        creq = Reqs(*(np.asarray(a) for a in st.creq))
        crequests = np.asarray(st.crequests)
        alive = np.asarray(st.alive)
        tmpl = np.asarray(st.tmpl)
        eavail = np.asarray(st.eavail)
        ereq = Reqs(*(np.asarray(a) for a in st.ereq))

        # global type table order (same construction as encode_problem)
        type_idx: dict[int, int] = {}
        for nct in scheduler.templates:
            for it in nct.instance_type_options:
                if id(it) not in type_idx:
                    type_idx[id(it)] = len(type_idx)

        claims: list[SchedulingNodeClaim] = []
        for slot in range(n_claims):
            nct = scheduler.templates[int(tmpl[slot])]
            claim = SchedulingNodeClaim.__new__(SchedulingNodeClaim)
            claim.template = nct
            claim.hostname = f"hostname-placeholder-{next(_claim_seq):04d}"
            claim.requirements = decode_row(vocab, creq.row(slot))
            live = [
                it
                for it in nct.instance_type_options
                if (alive[slot][type_idx[id(it)] // 32] >> (type_idx[id(it)] % 32)) & 1
            ]
            claim.instance_type_options = InstanceTypes(live)
            claim.requests = table.decode(crequests[slot])
            claim.daemon_resources = scheduler.daemon_overhead[nct]
            claim.pods = []
            claim.topology = scheduler.topology
            claim.host_port_usage = scheduler.daemon_host_ports[nct].copy()
            claim.reservation_manager = scheduler.reservation_manager
            claim.reserved_offerings = []
            claim.reserved_offering_strict = False
            claim.reserved_capacity_enabled = False
            claim.annotations = dict(nct.annotations)
            claims.append(claim)

        for e, node in enumerate(scheduler.existing_nodes):
            node.remaining_resources = table.decode(eavail[e])
            reqs = decode_row(vocab, ereq.row(e))
            reqs.add(
                Requirement(
                    well_known.HOSTNAME_LABEL_KEY, Operator.IN, [node.view.hostname]
                )
            )
            node.requirements = reqs

        pod_errors: dict[str, str] = {}
        for i, pod in enumerate(p.pods):
            kind, slot = int(kinds[i]), int(slots[i])
            if kind == K.KIND_EXISTING:
                scheduler.existing_nodes[slot].pods.append(pod)
            elif kind in (K.KIND_CLAIM, K.KIND_NEW):
                claims[slot].pods.append(pod)
            elif not timed_out:
                pod_errors[pod.uid] = self._error_for(pod)

        scheduler.new_node_claims = claims
        return Results(
            new_node_claims=claims,
            existing_nodes=scheduler.existing_nodes,
            pod_errors=pod_errors,
            timed_out=timed_out,
        )

    def _error_for(self, pod: Pod) -> str:
        """Reconstruct a template-level failure message host-side
        (nodeclaim.go:296 semantics). Topology-caused failures get a generic
        message — the batched solver doesn't track per-template reasons."""
        scheduler = self.oracle
        data = scheduler.cached_pod_data[pod.uid]
        errs = []
        for nct in scheduler.templates:
            requirements = Requirements(nct.requirements.values())
            err = requirements.compatible(data.requirements)
            if err is not None:
                errs.append(f"incompatible requirements, {err}")
                continue
            requirements.add(*data.requirements.values())
            total = res.merge(
                scheduler.daemon_overhead[nct], data.requests
            )
            _, _, ferr = filter_instance_types(
                nct.instance_type_options,
                requirements,
                data.requests,
                scheduler.daemon_overhead[nct],
                total,
            )
            if ferr is not None:
                errs.append(str(ferr))
        if not errs:
            return "unsatisfiable topology constraint"
        return "; ".join(errs)
