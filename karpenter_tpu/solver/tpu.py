"""TpuScheduler: the TPU-native batched solver with oracle fallback.

Drop-in for karpenter_tpu.solver.oracle.Scheduler (same constructor, same
solve() -> Results), implementing SURVEY.md §7 M3/M4: the whole scheduling
problem is encoded once into dense tensors (solver/tpu_problem.py) and a
jitted lax.scan packs pods at device speed (solver/tpu_kernel.py), while
the host only sorts pods, pads shapes, and decodes results.

Fidelity contract: for supported problems the per-pod decisions (which
existing node / in-flight claim / new template, in first-fit order) are
bit-identical to the oracle — tests/test_tpu_parity.py enforces this against
randomized problem mixes, including the reference benchmark's diverse pod
classes (scheduling_benchmark_test.go:257 makeDiversePods). Preference
relaxation rides the kernel (round 4): the ladder's tiers are encoded per
requirement class and a pod's step attempts them in order
(tpu_kernel._step_relax — scheduler.go:434 trySchedule's inline
relax-on-a-copy). Unsupported features (host ports, volume claims,
reserved capacity, hostname requirements, exotic topology filters) raise
UnsupportedBySolver at encode time; Solver.solve() then falls back to the
oracle — the hybrid dispatch.

The queue progress loop (scheduler.go:380 "schedule again if progress was
made") maps to outer rounds: failed pods are re-submitted against the
carried device state while any round schedules at least one pod — provably
equivalent to the reference's requeue-at-end + stall detection.
"""

from __future__ import annotations

import collections
import time as time_mod
from typing import Optional

import numpy as np

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import NodePool, Operator, Pod
from karpenter_tpu.cloudprovider.types import InstanceTypes
from karpenter_tpu.ops.encode import Reqs, decode_row
from karpenter_tpu.ops.kernels import VocabArrays
from karpenter_tpu.scheduling import Requirement, Requirements
from karpenter_tpu.solver import buckets
from karpenter_tpu.solver import nodes as nodes_mod
from karpenter_tpu.solver.epochs import problem_fingerprint, table_fingerprint
from karpenter_tpu.solver.nodes import (
    SchedulingNodeClaim,
    StateNodeView,
    filter_instance_types,
)
from karpenter_tpu.solver.oracle import Results, Scheduler, SchedulerOptions
from karpenter_tpu.solver.topology import Topology
from karpenter_tpu.solver.tpu_problem import (
    EncodedProblem,
    UnsupportedBySolver,
    _pow2,
    encode_problem,
)
from karpenter_tpu.utils import resources as res


def _typeok_chunk_impl(ireq, va, preq_chunk, iw: int):
    """[B, IW] u32: pairwise pod-vs-type requirement intersection bits."""
    import jax.numpy as jnp

    from karpenter_tpu.ops.kernels import intersects_only

    B = preq_chunk.mask.shape[0]
    I = ireq.mask.shape[0]
    a = Reqs(*(x[None, :] for x in ireq))  # [1, I, ...]
    b = Reqs(*(x[:, None] for x in preq_chunk))  # [B, 1, ...]
    ok = intersects_only(a, b, va)  # [B, I]
    pad = jnp.zeros((B, iw * 32 - I), bool)
    bits = jnp.concatenate([ok, pad], axis=-1).reshape(B, iw, 32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, None]
    return jnp.sum(bits.astype(jnp.uint32) * weights, axis=-1, dtype=jnp.uint32)


_typeok_chunk_cached = None


def _typeok_chunk(ireq, va, preq_chunk, iw: int):
    """Module-level jit cache (a per-call closure would recompile every
    solve)."""
    global _typeok_chunk_cached
    if _typeok_chunk_cached is None:
        import jax

        _typeok_chunk_cached = jax.jit(
            _typeok_chunk_impl, static_argnames=("iw",)
        )
    return _typeok_chunk_cached(ireq, va, preq_chunk, iw=iw)


_gather_xs_cached = None


def _gather_xs(tables, idx, n):
    """Device-side PodX assembly: gather class rows + per-pod selection
    rows for a round's pod indices. `idx` is the only per-pod upload of a
    round (compact dtype); validity derives from `n` on device."""
    global _gather_xs_cached
    if _gather_xs_cached is None:
        import jax

        def impl(tables, idx, n):
            import jax.numpy as jnp

            from karpenter_tpu.solver import tpu_kernel as K

            # Heavy rows live per REQUIREMENT-class (pod_class_key without
            # the request vector — few distinct values even when every pod's
            # requests differ); only the request vectors are per
            # encode-class. Selection rows live per (namespace, labels)
            # srow. This keeps the per-solve host->device upload
            # proportional to distinct shapes, not pods — the tunnel
            # transfer of per-pod rows used to dominate solve wall-clock.
            (
                preq_r, typeok_r, tol_t_r, tol_e_r,
                kind_r, gid_r, tsel_r, rcls_of,
                prequests_c, cls, srow, sel_rows_v, sel_rows_h,
                inv_c, own_c, ntiers_r, rrow_of, hp_own_r, hp_conf_r,
            ) = tables
            idx = idx.astype(jnp.int32)
            ci = cls[idx].astype(jnp.int32)
            ri = rcls_of[ci]
            si = srow[idx].astype(jnp.int32)
            valid = jnp.arange(idx.shape[0], dtype=jnp.int32) < n
            return K.PodX(
                preq=Reqs(*(a[ri] for a in preq_r)),
                prequests=prequests_c[ci],
                typeok=typeok_r[ri],
                tol_t=tol_t_r[ri],
                tol_e=tol_e_r[ri],
                topo_kind=kind_r[ri],
                topo_gid=gid_r[ri],
                topo_sel=tsel_r[ri],
                sel_v=sel_rows_v[si],
                sel_h=sel_rows_h[si],
                inv_h=inv_c[ci],
                own_h=own_c[ci],
                valid=valid,
                rrow=rrow_of[ri],
                ntiers=ntiers_r[ri],
                hp_own=hp_own_r[ri],
                hp_conf=hp_conf_r[ri],
            )

        _gather_xs_cached = jax.jit(impl)
    return _gather_xs_cached(tables, idx, n)


_run_arrays_cached = None


def _run_arrays(cls_d, bulk_c, aff_c, idx, n):
    """Device-side RunX driver arrays (is_head/bulk/aff/run_rem) from the
    round's index array + per-class flags — the [P]-sized host builds and
    uploads these replaced cost ~0.4s/solve in tunnel bytes at 50k pods.
    Padding positions (>= n) are their own single-pod runs with bulk off,
    matching the former host construction."""
    global _run_arrays_cached
    if _run_arrays_cached is None:
        import jax

        def impl(cls_d, bulk_c, aff_c, idx, n):
            import jax.numpy as jnp

            P = idx.shape[0]
            pos = jnp.arange(P, dtype=jnp.int32)
            valid = pos < n
            ci = cls_d[idx.astype(jnp.int32)].astype(jnp.int32)
            prev = jnp.roll(ci, 1)
            is_head = (pos == 0) | (ci != prev) | ~valid
            big = jnp.int32(2**31 - 1)
            arr = jnp.where(is_head, pos, big)
            m = jax.lax.cummin(arr, reverse=True)  # m[i] = min(arr[i:])
            nh = jnp.concatenate(
                [m[1:], jnp.full((1,), P, jnp.int32)]
            )  # next head strictly after i (padding is all heads)
            # a tail run with no head after it ends at P (nh would be the
            # big sentinel when the batch exactly fills P)
            run_rem = jnp.minimum(nh, P) - pos
            bulk = bulk_c[ci] & valid
            aff = aff_c[ci] & valid
            return is_head, bulk, aff, run_rem

        _run_arrays_cached = jax.jit(impl)
    return _run_arrays_cached(cls_d, bulk_c, aff_c, idx, n)


_grow_state_cached = None


def _grow_state(st, seq, pad):
    """Append inert claim-slot rows to the carried State + seq (overflow
    continuation: slot count only gates claim creation, so decisions made
    at the smaller N are unchanged — the host pads and resumes instead of
    re-solving). `pad` is a host-built tuple of pad blocks."""
    global _grow_state_cached
    if _grow_state_cached is None:
        import jax

        def impl(st, seq, pad):
            import jax.numpy as jnp

            (
                pcreq, pactive, pints, pcrequests, palive, pcmax, pseq, ph,
                pheld, php,
            ) = pad
            cat = lambda a, b: jnp.concatenate([a, b], axis=0)
            return st._replace(
                active=cat(st.active, pactive),
                count=cat(st.count, pints),
                rank=cat(st.rank, pints),
                tmpl=cat(st.tmpl, pints),
                creq=Reqs(*(cat(a, b) for a, b in zip(st.creq, pcreq))),
                crequests=cat(st.crequests, pcrequests),
                alive=cat(st.alive, palive),
                cmax_alloc=cat(st.cmax_alloc, pcmax),
                h_cnt=jnp.concatenate([st.h_cnt, ph], axis=1),
                held=cat(st.held, pheld),
                hp_used=cat(st.hp_used, php),
            ), cat(seq, pseq)

        _grow_state_cached = jax.jit(impl)
    return _grow_state_cached(st, seq, pad)


_slice_decode_cached = None


def _slice_decode_state(st, n2: int, ecols: int):
    """Device-side slice of the decode-relevant State fields to the live
    pow2 claim bucket (module-level jit cache; n2/ecols are static so each
    bucket compiles once)."""
    global _slice_decode_cached
    if _slice_decode_cached is None:
        import jax

        def impl(st, n2, ecols):
            return (
                Reqs(*(a[:n2] for a in st.creq)),
                st.crequests[:n2],
                st.alive[:n2],
                st.tmpl[:n2],
                st.eavail,
                st.ereq,
                st.v_cnt,
                st.h_cnt[:, :ecols],
                st.trem,
            )

        _slice_decode_cached = jax.jit(impl, static_argnames=("n2", "ecols"))
    return _slice_decode_cached(st, n2=n2, ecols=ecols)


_dedup_decode_cached = None
_slice_rows_cached = None
# dedup fetch pays an extra round trip; below this bucket the raw fetch is
# cheaper (tests lower it to drive the dedup path on small problems)
_DEDUP_DECODE_MIN = 2048


def _dedup_decode_state(st, n2: int, ecols: int):
    """Large-solve decode fetch: claims overwhelmingly share identical
    (requirement-row, surviving-types) pairs — a 50k-pod solve has ~10k
    claim slots but only tens of distinct rows, and the tunnel charges per
    byte. Device side: pack creq+alive into one u32 matrix, sort by two
    independent 32-bit row hashes, compact FULL-ROW-compared uniques to
    the front, and hand back (uniques kept on device for a sliced second
    fetch, inverse index, small raw fields). Hash collisions only place
    equal rows non-adjacently — costing duplicate "uniques", never
    merging distinct rows — so the result is exact.

    Returns (small, compact): small is a device pytree to fetch whole;
    compact stays on device until the caller knows n_uniq."""
    global _dedup_decode_cached
    if _dedup_decode_cached is None:
        import jax
        import jax.numpy as jnp

        def impl(st, n2, ecols):
            r = st.creq
            bits = lambda a: jax.lax.bitcast_convert_type(a[:n2], jnp.uint32)
            cols = [
                r.mask[:n2],
                r.exmask[:n2],
                r.other[:n2].astype(jnp.uint32),
                r.notin[:n2].astype(jnp.uint32),
                r.defined[:n2].astype(jnp.uint32),
                bits(r.gt),
                bits(r.lt),
                bits(r.minv),
                st.alive[:n2],
            ]
            rows = jnp.concatenate(cols, axis=1)  # [n2, C] u32
            C = rows.shape[1]
            j = jnp.arange(C, dtype=jnp.uint32)
            m1 = (2 * j + 1) * jnp.uint32(2654435761)
            m2 = (2 * j + 1) * jnp.uint32(2246822519)
            h1 = jnp.sum(rows * m1[None, :], axis=1, dtype=jnp.uint32)
            h2 = jnp.sum((rows + j[None, :]) * m2[None, :], axis=1,
                         dtype=jnp.uint32)
            order = jnp.lexsort((h2, h1))
            sm = rows[order]
            is_new = jnp.concatenate(
                [jnp.ones(1, bool), jnp.any(sm[1:] != sm[:-1], axis=1)]
            )
            dest = jnp.cumsum(is_new) - 1  # [n2]
            compact = jnp.zeros_like(sm).at[dest].set(sm)
            inv = jnp.zeros(n2, jnp.int32).at[order].set(dest.astype(jnp.int32))
            n_uniq = dest[-1] + 1
            small = (
                n_uniq,
                inv,
                st.crequests[:n2],
                st.tmpl[:n2],
                st.eavail,
                st.ereq,
                st.v_cnt,
                st.h_cnt[:, :ecols],
                st.trem,
            )
            return small, compact

        _dedup_decode_cached = jax.jit(impl, static_argnames=("n2", "ecols"))
    return _dedup_decode_cached(st, n2=n2, ecols=ecols)


def _slice_rows(compact, u2: int):
    global _slice_rows_cached
    if _slice_rows_cached is None:
        import jax

        _slice_rows_cached = jax.jit(
            lambda m, u2: m[:u2], static_argnames=("u2",)
        )
    return _slice_rows_cached(compact, u2=u2)


def _odo_dispatch_dict(odo) -> dict:
    """One fetched kernel Odometer (tpu_kernel.Odometer of host arrays)
    as plain ints — the `kernel` block a dispatch span carries and the
    unit the per-solve totals accumulate."""
    hist = [int(v) for v in np.asarray(odo.tier_hist)]
    d = {
        "steps": int(odo.steps),
        "bulk_steps": int(odo.bulk_steps),
        "tier_steps": int(odo.tier_steps),
    }
    if d["tier_steps"]:
        d["tier_hist"] = hist
    return d


def _new_odo_totals() -> dict:
    """Per-solve kernel-odometer accumulator (TpuScheduler.last_odometer).
    Dispatch counters sum across every kernel launch of the solve —
    including a scan-path overflow attempt that was later re-solved: the
    odometer reports device work actually executed, not just the work
    that survived. claims_opened / claim_slots / occupancy land in
    _decode (they are final-state facts, not per-dispatch deltas)."""
    from karpenter_tpu.solver import tpu_kernel as K

    return {
        "steps": 0,
        "bulk_steps": 0,
        "tier_steps": 0,
        "tier_hist": [0] * K.ODO_TIER_BINS,
        "dispatches": 0,
        "overflow_signals": 0,
        "regrows": 0,
    }


def _fold_odo_totals(totals: dict, d: dict, path: str) -> None:
    """Fold one odometer dict — a dispatch's fetched block or a fleet
    lane's accumulation — into the solve totals and the labeled kernel
    metrics. ONE implementation for both paths, so their accounting
    cannot drift."""
    from karpenter_tpu import tracing

    totals["steps"] += d.get("steps", 0)
    totals["bulk_steps"] += d.get("bulk_steps", 0)
    totals["tier_steps"] += d.get("tier_steps", 0)
    for t, v in enumerate(d.get("tier_hist", ())):
        totals["tier_hist"][t] += v
    totals["dispatches"] += d.get("dispatches", 1)
    if d.get("steps"):
        tracing.KERNEL_ITERATIONS.inc({"path": path}, by=d["steps"])
    for t, v in enumerate(d.get("tier_hist", ())):
        if v:
            tracing.KERNEL_TIER_STEPS.inc({"tier": str(t)}, by=v)


def _record_odo_dispatch(totals: dict, odo, path: str) -> dict:
    """Fold one dispatch's fetched odometer into the solve totals and the
    labeled kernel metrics; returns the dispatch's `kernel` span block."""
    d = _odo_dispatch_dict(odo)
    _fold_odo_totals(totals, d, path)
    return d


def _tree_nbytes(tree) -> int:
    """Total bytes across a pytree of device arrays — the table-upload
    accounting behind karpenter_solve_upload_bytes_total (CLAUDE.md: the
    host<->device tunnel charges per byte, so the uploads are the number
    to watch before op counts)."""
    import jax

    return int(
        sum(
            int(getattr(leaf, "nbytes", 0))
            for leaf in jax.tree_util.tree_leaves(tree)
        )
    )


def _popcount_rows(seg: np.ndarray) -> np.ndarray:
    return np.unpackbits(
        seg.astype("<u4").view(np.uint8), axis=-1
    ).sum(axis=-1)


def _bulk_gates(p: EncodedProblem, strict_types: bool = True) -> bool:
    """Problem-level gates for the run kernel's bulk phases (see
    solver/tpu_runs.py module docstring). When any fails, every pod runs
    the exact per-pod step inside the same kernel — correctness never
    depends on these.

    strict_types: the per-key type-structure rule. The consolidation
    sweep's delta kernel (disruption/sweep.py) has NO per-commit verify,
    so it requires every concrete type key single-valued or spanning the
    whole vocab segment (pairwise == three-way). The RUN kernel verifies
    surviving types EXACTLY at every bulk commit (case_level okv /
    case_solo tok / case_new t_final_i), so it only needs the screens to
    be sound relative to the TYPE UNIVERSE: values a pod references that
    no instance type carries (e.g. a preference for a zone that doesn't
    exist) must not blunt the gate — compare row popcounts against the
    union of type rows, not the whole segment (round 5; this is what kept
    the realistic-mix bench on the per-pod path)."""
    if (p.treq.minv != -1).any() or (p.preq_c.minv != -1).any():
        return False
    if p.num_existing and (p.ereq.minv != -1).any():
        return False
    if p.thas_limits.any():
        return False
    # template daemonset host ports: bulk case_new creates claims without
    # seeding thp into hp_used, so a later host-port pod could co-locate
    # onto a conflicting bulk-created claim — run everything per-pod
    # instead (port-OWNING classes are already excluded per class; this
    # covers port-free bulk classes creating claims from porty templates)
    if p.thp is not None and p.thp.any():
        return False
    vocab = p.vocab
    for kid in range(vocab.num_keys):
        off, words = vocab.word_offset[kid], vocab.words_per_key[kid]
        seg = p.ireq.mask[:, off : off + words]
        pop = _popcount_rows(seg)
        concrete = p.ireq.defined[:, kid] & ~p.ireq.other[:, kid]
        if strict_types:
            full = len(vocab.values[kid])
        else:
            union = np.bitwise_or.reduce(
                np.where(concrete[:, None], seg, 0), axis=0
            )
            full = int(_popcount_rows(union[None])[0])
        if (concrete & (pop > 1) & (pop < full)).any():
            return False
    # offerings decompose per key: every capacity-type a type offers must
    # cover the same zone set (so "an offering exists for the chosen zone"
    # is independent of which zone the tighten picks)
    zone_kid = vocab.key_index.get(well_known.TOPOLOGY_ZONE_LABEL_KEY)
    per_type: dict[int, dict[int, set]] = {}
    # bucket-padded offering rows (ovalid=False) carry sentinel words that
    # must not perturb the per-type zone-coverage decomposition
    for o in range(p.num_offerings_real):
        i = int(p.otype[o])
        if p.oword[o, 2] != -1:
            return False  # reservation-id offerings
        zw, cw = int(p.oword[o, 0]), int(p.oword[o, 1])
        z = -1 if zw == -1 else zw * 32 + int(p.obit[o, 0])
        c = -1 if cw == -1 else cw * 32 + int(p.obit[o, 1])
        per_type.setdefault(i, {}).setdefault(c, set()).add(z)
    for zones_by_ct in per_type.values():
        wildcard = zones_by_ct.pop(-1, None)
        if wildcard is not None and -1 in wildcard:
            continue  # a fully unconstrained offering covers everything
        sets = [frozenset(v) for v in zones_by_ct.values()]
        if sets and len(set(sets)) > 1 and not any(-1 in s for s in sets):
            return False
    return True


def _bulk_class_flags(p: EncodedProblem, gates_ok: bool) -> np.ndarray:
    """[NC] bool — class admits bulk phases. Only self-selecting
    zone-family spread/anti constraints are dynamic beyond what the kernel's
    per-slot hostname budgets model (their domain counts move mid-run), so
    only those force the exact per-pod path."""
    from karpenter_tpu.solver.tpu_problem import TOPO_ANTI_V, TOPO_SPREAD_V

    NC = len(p.class_reps)
    if not gates_ok:
        return np.zeros(NC, bool)
    dyn_v = np.isin(p.ptopo_kind_c, (TOPO_SPREAD_V, TOPO_ANTI_V)) & p.ptopo_sel_c
    # relaxable classes run the exact per-pod step (the tier loop lives
    # there); bulk phases assume a run of single-tier identical deciders.
    # host-port classes are slot-stateful per commit (hostportusage.go:35)
    # and take the exact step too
    ntiers_c = p.ntiers_r[p.rcls_of]
    has_ports = (
        p.php_own_c.any(axis=1)
        if p.php_own_c is not None and p.php_own_c.shape[1]
        else np.zeros(NC, bool)
    )
    return ~dyn_v.any(axis=1) & (ntiers_c == 1) & ~has_ports




_DecodeView = collections.namedtuple(
    "_DecodeView",
    [
        "n_claims", "creq", "crequests", "alive", "tmpl", "eavail",
        "ereq", "v_cnt", "h_cnt",
    ],
)


class TpuScheduler:
    """Same surface as oracle.Scheduler, solving on the accelerator."""

    # Testing knob (testing/fuzz.py dual-path parity): force the exact
    # per-pod SCAN step even when every class passes the bulk gates. The
    # scan path is always semantically valid — the runs kernel is purely
    # an iteration-count optimization over it — so forcing it re-checks
    # the same decisions through the other compiled program. Never set
    # in production paths.
    debug_force_scan = False

    def __init__(
        self,
        node_pools: list[NodePool],
        instance_types_by_pool: dict[str, InstanceTypes],
        topology: Topology,
        state_nodes: Optional[list[StateNodeView]] = None,
        daemonset_pods: Optional[list[Pod]] = None,
        options: Optional[SchedulerOptions] = None,
        table_cache=None,
        fleet=None,
        epoch_key=None,
    ):
        # reuse the oracle's init wholesale: template filtering, daemon
        # overhead, existing-node ordering, limits (scheduler.go:116)
        self.oracle = Scheduler(
            node_pools,
            instance_types_by_pool,
            topology,
            state_nodes,
            daemonset_pods,
            options,
        )
        self.opts = self.oracle.opts
        # epochs.DeviceTableCache (optional, shared across schedulers —
        # the sidecar server owns one): device table sets keyed by the
        # content fingerprint of every encoded array they derive from. A
        # hit skips _tables/_upload_pod_tables entirely, so a repeat
        # same-epoch solve uploads only the pending-pod batch (the
        # `epoch[runtime]` ir-transfer budget pins the zero; CLAUDE.md's
        # _ktpu_* invalidation invariant extends to these copies because
        # any relax/class-key mutation perturbs the fingerprinted arrays)
        self._table_cache = table_cache
        # fleet.FleetCoalescer (optional — the sidecar server owns one):
        # scan-path solves offer themselves to the batch window and ride
        # a shared vmapped dispatch when siblings arrive; any None answer
        # (no sibling, overflow, coalescing fault) runs the solo loop
        # below unchanged
        self._fleet = fleet
        # (client, epoch id) of the request this scheduler serves, when
        # the sidecar materialized it from a resident epoch (service.py
        # threads it): rides the fleet window's trace event, so a
        # waterfall shows WHICH epochs shared one materialization
        self._epoch_key = epoch_key
        self.last_used_fleet = False
        # device-truth kernel odometer of the most recent solve (dict; see
        # _new_odo_totals) — populated per solve, finished in _decode
        self.last_odometer = None

    # -- solve ----------------------------------------------------------

    def solve(self, pods: list[Pod], trace=None) -> Results:
        """May raise UnsupportedBySolver; Solver wrappers catch and fall
        back to the oracle. The persistent compile cache is configured by
        the solver package import (jaxsetup.ensure_compilation_cache).

        `trace` is an optional tracing.Trace the caller threads down from
        the controller (explicit context object — never contextvars, and
        every span is host-side, so the instrumentation cannot retrace a
        compiled program). Standalone solves own a local trace so phase
        metrics populate on EVERY solve; last_profile exposes it."""
        from karpenter_tpu import tracing

        with tracing.maybe_trace(trace, "tpu_solve") as tr:
            self.last_profile = tr
            return self._solve_traced(pods, tr)

    def _solve_traced(self, pods: list[Pod], prof) -> Results:
        import jax  # already imported by the package init; cheap rebind

        from karpenter_tpu import tracing

        if not pods:
            return Results(
                new_node_claims=[], existing_nodes=self.oracle.existing_nodes,
                pod_errors={},
            )
        with prof.span("encode", pods=len(pods)):
            problem = encode_problem(self.oracle, pods)
        deadline = (
            time_mod.monotonic() + self.opts.timeout_seconds
            if self.opts.timeout_seconds
            else None
        )

        # FFD order shared with the oracle (solver/ordering.py): cpu desc,
        # memory desc, class signature, creation, uid — class grouping makes
        # identical pods contiguous for the run kernel. Sort columns come
        # from the per-class tables (one PodData per class, shared by every
        # pod of the class); only timestamps/uids are gathered per pod.
        with prof.span("order"):
            order = self._order_pods(problem)

        from karpenter_tpu.solver import tpu_kernel as K
        from karpenter_tpu.solver import tpu_runs as KR

        with prof.span("upload"):
            cached = None
            fp = None
            tfp = None
            if self._table_cache is not None:
                fp = problem_fingerprint(problem)
                cached = self._table_cache.get(fp)
            if cached is not None:
                # device-resident hit: zero bytes cross the tunnel for
                # tables — the only remaining per-solve upload is the
                # pending-pod index batch (_pod_xs_with_idx)
                tb, self._typeok, self._dev_tables, self._aff_c = cached
                upload_bytes = 0
                prof.event("table_cache", outcome="hit")
            else:
                tb = None
                token = None
                if self._table_cache is not None:
                    # single-flight on the TABLE fingerprint: concurrent
                    # same-epoch solves (a fleet window's lanes all
                    # encoding before any put lands) elect one builder
                    # for the shared Tables pytree; the rest block here
                    # and reuse it — one materialization per window
                    tfp = table_fingerprint(problem)
                    tb, token = self._table_cache.begin_tables(tfp)
                try:
                    if tb is not None:
                        # shared-tables hit: tb is a pure function of the
                        # table-hashed fields (fleet.py's stacking
                        # precondition), so only the per-lane pod tables
                        # rebuild against the resident pytree
                        self._typeok = self._pod_typeok(problem, tb)
                        self._upload_pod_tables(problem)
                        upload_bytes = _tree_nbytes(self._dev_tables)
                        prof.event("table_cache", outcome="tables_hit")
                    else:
                        tb = self._tables(problem)  # also sets self._typeok
                        self._upload_pod_tables(problem)
                        upload_bytes = _tree_nbytes(tb) + _tree_nbytes(
                            self._dev_tables
                        )
                        if self._table_cache is not None:
                            prof.event("table_cache", outcome="miss")
                finally:
                    if self._table_cache is not None:
                        self._table_cache.end_tables(token, tb)
                if self._table_cache is not None:
                    self._table_cache.put(
                        fp, (tb, self._typeok, self._dev_tables, self._aff_c)
                    )
        if upload_bytes:
            prof.count("upload_bytes", by=upload_bytes)
            tracing.SOLVE_UPLOAD_BYTES.inc(by=upload_bytes)
        gates_ok = _bulk_gates(problem, strict_types=False)
        self._bulk_flags_c = _bulk_class_flags(problem, gates_ok)
        # trace-time static: with no relaxable requirement classes the
        # compiled program carries no tier machinery at all (VERDICT r4 #1
        # — the ladder must not tax preference-free workloads)
        relax = bool((problem.ntiers_r > 1).any())
        self.last_relax = relax
        use_runs = bool(self._bulk_flags_c.any()) and not self.debug_force_scan
        self.last_used_runs = use_runs  # introspection for tests/bench
        if use_runs:
            self._set_runflags_dev()

        # Claim slots: most solves create far fewer claims than pods (the
        # bench mix averages ~5 pods/claim), so start small — every
        # per-step candidate screen and the decode fetch scale with N. On
        # the kernel's overflow signal the runs path PADS the carried
        # state and continues from the overflow pod (decisions are
        # N-invariant: slot count only gates claim creation), so a small
        # start risks only a cheap growth event, not a re-solve. The scan
        # path (no early stop inside lax.scan) re-solves from scratch.
        div = max(1, int(self.opts.claim_slot_div))
        if not use_runs:
            # the scan path can't stop mid-batch (lax.scan), so overflow
            # means a full re-solve — don't undersize its slot pool
            div = min(div, 4)
        N = min(_pow2(max(64, (len(pods) + div - 1) // div)), _pow2(len(pods)))
        # bucket selection is a decision, not a duration: record it as a
        # marker so the trace waterfall shows which compiled-shape family
        # (path x claim-slot rung x relax) this solve rode
        path = "runs" if use_runs else "scan"
        prof.event("bucket", claim_slots=N, path=path, relax=relax)
        tiers_beyond_0 = int(problem.ntiers_r.max(initial=1)) - 1 if relax else 0
        if tiers_beyond_0:
            prof.count("relax_tiers", by=tiers_beyond_0)
            tracing.SOLVE_RELAX_TIERS.inc(by=tiers_beyond_0)
        # kernel odometers (device-truth counters): every dispatch below
        # returns its counter block in the SAME fetch; totals accumulate
        # here and finish in _decode (claims_opened / occupancy need the
        # final state). Discarded overflow attempts still count — the
        # odometer reports work executed, not work kept.
        odo_totals = _new_odo_totals()
        self.last_odometer = odo_totals
        # Fleet coalescing (solver/fleet.py): scan-path solves offer
        # themselves to the batch window; when siblings stack, the whole
        # requeue-round loop below runs inside ONE shared vmapped dispatch
        # per round and the lane's (st, kinds, slots, timed_out) comes
        # back solo-bit-identical. The runs path never coalesces — its
        # mid-solve claim regrow is host-driven per lane — and any None
        # answer (no sibling arrived, claim overflow, coalescing fault)
        # falls through to the unchanged solo loop.
        self.last_used_fleet = False
        if self._fleet is not None and not use_runs:
            got = self._fleet.solve_lane(
                self, problem, tb, order, N, relax, deadline, prof,
                # the upload phase already fingerprinted the tables when a
                # cache is wired (the sidecar shape); the coalescer reuses
                # it instead of re-hashing per window entry
                table_fp=tfp, epoch_key=self._epoch_key,
            )
            if got is not None:
                st, kinds, slots, timed_out, lane_odo = got
                self.last_used_fleet = True
                if lane_odo is not None:
                    _fold_odo_totals(odo_totals, lane_odo, "fleet")
                    prof.count("kernel_iterations", by=lane_odo.get("steps", 0))
                    if lane_odo.get("tier_steps"):
                        prof.count(
                            "kernel_tier_steps", by=lane_odo["tier_steps"]
                        )
                prof.annotate(
                    pods=len(pods), path="fleet", relax=relax,
                    claim_slots=N, timed_out=timed_out,
                )
                with prof.span("decode"):
                    return self._decode(problem, st, kinds, slots, timed_out)
        while True:
            st = self._init_state(problem, N)
            seq = jax.numpy.zeros(N, jax.numpy.int32)
            next_seq = jax.numpy.zeros((), jax.numpy.int32)
            kinds = np.full(len(pods), K.KIND_FAIL, dtype=np.int32)
            slots = np.full(len(pods), -1, dtype=np.int32)
            pending = list(order)
            timed_out = False
            overflowed = False
            while pending:
                if deadline is not None and time_mod.monotonic() > deadline:
                    timed_out = True
                    break
                # one requeue round over `pending` (scheduler.go:380); the
                # runs path may take several kernel launches per round when
                # an overflow growth lands mid-batch
                round_failed: list[int] = []
                offset = 0
                while True:
                    batch = pending[offset:]
                    # one device dispatch: upload the round's index array,
                    # run the kernel, fetch the verdicts + the kernel's
                    # odometer block (same fetch — zero extra dispatches).
                    # The pod_xs/kernel/fetch sub-spans are per-dispatch
                    # detail — individually recorded only behind the
                    # profiling gate
                    with prof.span("dispatch", path=path) as dsp:
                        if use_runs:
                            with prof.span("pod_xs", detail=True):
                                xs, idx_d, n_d = self._pod_xs_with_idx(problem, batch)
                                rx = self._run_x(xs, idx_d, n_d)
                            with prof.span("kernel", detail=True):
                                (
                                    st, seq, next_seq, got_kinds, got_slots,
                                    got_over, got_odo, got_ptr,
                                ) = KR.solve_runs(
                                    tb, st, rx, seq, next_seq,
                                    jax.numpy.int32(len(batch)),
                                    relax=relax,
                                )
                        else:
                            with prof.span("pod_xs", detail=True):
                                xs = self._pod_xs(problem, batch)
                            with prof.span("kernel", detail=True):
                                (
                                    st, got_kinds, got_slots, got_over,
                                    got_odo,
                                ) = K.solve_scan(tb, st, xs, relax=relax)
                                got_ptr = None
                        # one batched device->host fetch (the tunnel
                        # charges per call)
                        with prof.span("fetch", detail=True):
                            fetched = jax.device_get(
                                (got_kinds, got_slots, got_over, got_odo)
                                if got_ptr is None
                                else (
                                    got_kinds, got_slots, got_over, got_odo,
                                    got_ptr,
                                )
                            )
                        dsp["kernel"] = _record_odo_dispatch(
                            odo_totals, fetched[3], path
                        )
                    prof.count("dispatches")
                    tracing.SOLVE_DISPATCHES.inc({"path": path})
                    got_kinds, got_slots, got_over = fetched[:3]
                    if bool(got_over) and got_ptr is None:
                        # scan path: re-solve from scratch
                        overflowed = True
                        odo_totals["overflow_signals"] += 1
                        tracing.KERNEL_OVERFLOWS.inc({"path": path})
                        break
                    if bool(got_over):
                        # runs path: commit everything before the overflow
                        # pod, pad the state with fresh slots, continue the
                        # round from that pod
                        odo_totals["overflow_signals"] += 1
                        tracing.KERNEL_OVERFLOWS.inc({"path": path})
                        n_done = int(fetched[4])
                        done = batch[:n_done]
                        kinds[done] = got_kinds[:n_done]
                        slots[done] = got_slots[:n_done]
                        round_failed += [
                            i for i, k in zip(done, got_kinds[:n_done])
                            if k == K.KIND_FAIL
                        ]
                        with prof.span("regrow"):
                            st, seq = self._grow(problem, st, seq, N)
                        prof.count("regrows")
                        tracing.SOLVE_REGROWS.inc()
                        odo_totals["regrows"] += 1
                        N *= 2
                        offset += n_done
                        continue
                    got_kinds = got_kinds[: len(batch)]
                    got_slots = got_slots[: len(batch)]
                    kinds[batch] = got_kinds
                    slots[batch] = got_slots
                    round_failed += [
                        i for i, k in zip(batch, got_kinds) if k == K.KIND_FAIL
                    ]
                    break
                if overflowed:
                    break
                if len(round_failed) == len(pending):
                    break  # no progress: stall (queue.go:52)
                pending = round_failed
            if not overflowed:
                break
            N *= 2  # scan-path slots exhausted: re-solve with room

        prof.count("kernel_iterations", by=odo_totals["steps"])
        if odo_totals["tier_steps"]:
            prof.count("kernel_tier_steps", by=odo_totals["tier_steps"])
        prof.annotate(
            pods=len(pods), path=path, relax=relax, claim_slots=N,
            timed_out=timed_out,
        )
        with prof.span("decode"):
            return self._decode(problem, st, kinds, slots, timed_out)

    def _order_pods(self, p: EncodedProblem) -> list:
        """FFD order from class columns; also points cached_pod_data at one
        shared PodData per class (requests/requirements are class fields),
        so the former per-pod Requirements.from_pod pass disappears."""
        from karpenter_tpu.solver.ordering import (
            ffd_order_cols,
            pod_class_signature,
        )

        pods = p.pods
        data = self.oracle.cached_pod_data
        pd_c = []
        for i in p.class_reps:
            self.oracle._update_cached_pod_data(pods[i])
            pd_c.append(data[pods[i].uid])
        cls_list = p.pod_class.tolist()
        for pod, c in zip(pods, cls_list):
            data[pod.uid] = pd_c[c]
        cpu_c = np.fromiter(
            (pd.requests.get(res.CPU, 0) for pd in pd_c), np.int64, len(pd_c)
        )
        mem_c = np.fromiter(
            (pd.requests.get(res.MEMORY, 0) for pd in pd_c), np.int64, len(pd_c)
        )
        sig_c = np.fromiter(
            (pod_class_signature(pods[i]) for i in p.class_reps),
            np.int64,
            len(p.class_reps),
        )
        cls = p.pod_class
        ts_list = [pod.metadata.creation_timestamp for pod in pods]
        uids = [pod.uid for pod in pods]
        return ffd_order_cols(cpu_c[cls], mem_c[cls], sig_c[cls], ts_list, uids)

    def _set_runflags_dev(self) -> None:
        """Upload the per-class bulk/affinity flags for the run driver,
        bucket-padded in step with the class tables (_upload_pod_tables)
        so _run_arrays compiles per rung, not per class count."""
        import jax.numpy as jnp

        nc = int(self._dev_tables[8].shape[0])  # padded prequests_c rows
        self._runflags_dev = (
            jnp.asarray(buckets.pad_rows(self._bulk_flags_c, nc)),
            jnp.asarray(buckets.pad_rows(self._aff_c, nc)),
        )

    def _run_x(self, xs, idx_d, n_d):
        """Build the run-kernel driver arrays for a round — entirely on
        device from the round's already-uploaded index array (see
        _run_arrays). idx_d/n_d come from the _pod_xs_with_idx call that
        produced xs."""
        from karpenter_tpu.solver import tpu_runs as KR

        cls_d = self._dev_tables[9]
        bulk_d, aff_d = self._runflags_dev
        is_head, bulk, aff, run_rem = _run_arrays(cls_d, bulk_d, aff_d, idx_d, n_d)
        return KR.RunX(
            x=xs, is_head=is_head, bulk=bulk, aff=aff, run_rem=run_rem
        )

    def _cr_padded(self, p: EncodedProblem) -> np.ndarray:
        """[NR_pad] class index per requirement class, bucket-padded by
        repeating real rows (solver/buckets.py: pad rows are never
        gathered — rcls_of only holds real ids — so repeats are the
        cheapest shape-stable filler)."""
        cr = np.asarray(p.rclass_creps, dtype=np.int64)
        if not buckets.enabled() or len(cr) == 0:
            return cr
        return cr[np.arange(buckets.bucket(len(cr))) % len(cr)]

    def _pod_typeok(self, p: EncodedProblem, tb):
        """[NR_pad, IW] u32 DEVICE array — per requirement-class, the
        instance types whose requirements intersect the class's (pairwise
        screen; the kernel's while_loop stays exact for three-way
        intersections, offerings, and minValues). Stays on device
        end-to-end: the profile showed pulling it to host only to
        re-upload in _upload_pod_tables cost ~0.5s/solve in tunnel
        round-trips. Rows are bucket-padded in step with _cr_padded."""
        import jax.numpy as jnp

        I = p.num_types
        IW = max(1, (I + 31) // 32)
        cr = self._cr_padded(p)
        NR = len(cr)
        chunks = []
        CH = 2048
        for lo in range(0, NR, CH):
            hi = min(lo + CH, NR)
            # pow2-pad chunks so compiled shapes are reused across solves
            pad_to = min(CH, _pow2(hi - lo))
            idx = cr[np.arange(lo, lo + pad_to) % NR]
            chunk = Reqs(*(jnp.asarray(a[idx]) for a in p.preq_c))
            chunks.append(_typeok_chunk(tb.ireq, tb.va, chunk, iw=IW)[: hi - lo])
        if not chunks:
            return jnp.zeros((0, IW), jnp.uint32)
        return chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks)

    def _tier_typeok(self, p: EncodedProblem, tb):
        """[NRx, L, IW] u32 — per relaxable rclass and tier, the pairwise
        pod-vs-type screen (the tier analog of _pod_typeok)."""
        import jax.numpy as jnp

        I = p.num_types
        IW = max(1, (I + 31) // 32)
        if not p.rt_tier_reqs:
            return jnp.zeros((1, 1, IW), jnp.uint32)
        NRx = len(p.rt_tier_reqs)
        L = p.num_tiers
        flat = Reqs(*(a.reshape((NRx * L,) + a.shape[2:]) for a in p.rt_preq))
        pad_to = _pow2(NRx * L)
        idx = np.arange(pad_to) % (NRx * L)
        chunk = Reqs(*(jnp.asarray(a[idx]) for a in flat))
        rows = _typeok_chunk(tb.ireq, tb.va, chunk, iw=IW)[: NRx * L]
        return rows.reshape(NRx, L, IW)

    # -- tensor construction --------------------------------------------

    def _tables(self, p: EncodedProblem):
        import jax.numpy as jnp

        from karpenter_tpu.solver import tpu_kernel as K

        def pad_group_v(a, fill=0):
            if a.shape[0] == 0:
                return jnp.asarray(
                    np.full((1,) + a.shape[1:], fill, dtype=a.dtype)
                )
            return jnp.asarray(a)

        Gv, Gh = len(p.vgroups), len(p.hgroups)
        va = VocabArrays.from_vocab(p.vocab)
        v_anti = np.array(
            [g.group.type.value == 2 for g in p.vgroups], dtype=bool
        ).reshape(Gv)
        h_inverse = np.array([g.inverse for g in p.hgroups], dtype=bool).reshape(Gh)
        jreq = lambda r: Reqs(*(jnp.asarray(a) for a in r))

        def pad_rt(a):
            """Bucket the relaxable-rclass axis of the tier tables (rows
            past the real count are never gathered — x.rrow holds real
            ids only)."""
            if not buckets.enabled():
                return a
            return buckets.pad_rows(a, buckets.bucket(a.shape[0], floor=1))

        def pad_reqs_rows(r: Reqs) -> Reqs:
            if r.mask.shape[0] > 0:
                return jreq(r)
            return Reqs(
                *(
                    jnp.asarray(np.zeros((0,) + a.shape[1:], dtype=a.dtype))
                    for a in r
                )
            )

        tb = K.Tables(
            va=va,
            treq=jreq(p.treq),
            tdaemon=jnp.asarray(p.tdaemon),
            ttypes=jnp.asarray(p.ttypes),
            tlimit_def=jnp.asarray(p.tlimit_def),
            thas_limits=jnp.asarray(p.thas_limits),
            ireq=jreq(p.ireq),
            ialloc=jnp.asarray(p.ialloc),
            icap=jnp.asarray(p.icap),
            otype=jnp.asarray(p.otype),
            oword=jnp.asarray(p.oword),
            obit=jnp.asarray(p.obit),
            orid=jnp.asarray(
                p.orid
                if p.orid is not None
                else np.full(p.otype.shape[0], -1, np.int32)
            ),
            ovalid=jnp.asarray(
                p.ovalid
                if p.ovalid is not None
                else np.ones(p.otype.shape[0], bool)
            ),
            v_kid=pad_group_v(p.v_kid),
            v_word=pad_group_v(p.v_word, fill=-1),
            v_bit=pad_group_v(p.v_bit),
            v_reg=pad_group_v(p.v_reg, fill=False),
            v_skew=pad_group_v(p.v_skew),
            v_mindom=pad_group_v(p.v_mindom, fill=-1),
            v_filt=pad_group_v(p.v_filt, fill=-1),
            v_anti=pad_group_v(v_anti, fill=False),
            h_skew=pad_group_v(p.h_skew),
            h_filt=pad_group_v(p.h_filt, fill=-1),
            h_inverse=pad_group_v(h_inverse, fill=False),
            filter_reqs=pad_reqs_rows(p.filter_reqs),
            thp=jnp.asarray(
                p.thp
                if p.thp is not None
                else np.zeros((p.num_templates, 0), np.uint32)
            ),
            rt_preq=Reqs(*(jnp.asarray(pad_rt(a)) for a in p.rt_preq)),
            rt_typeok=jnp.zeros(
                (1, 1, max(1, (p.num_types + 31) // 32)), jnp.uint32
            ),
            rt_tol_t=jnp.asarray(pad_rt(p.rt_tol_t)),
            rt_tol_e=jnp.asarray(pad_rt(p.rt_tol_e)),
            rt_kind=jnp.asarray(pad_rt(p.rt_kind)),
            rt_gid=jnp.asarray(pad_rt(p.rt_gid)),
            rt_sel=jnp.asarray(pad_rt(p.rt_sel)),
        )
        # tier type-screens need tb.ireq/va: fill after base construction
        self._typeok = self._pod_typeok(p, tb)
        rt_typeok = self._tier_typeok(p, tb)
        if buckets.enabled():
            import jax

            NRx_pad = buckets.bucket(int(rt_typeok.shape[0]), floor=1)
            if NRx_pad > rt_typeok.shape[0]:
                rt_typeok = jax.numpy.concatenate(
                    [
                        rt_typeok,
                        jax.numpy.zeros(
                            (NRx_pad - rt_typeok.shape[0],) + rt_typeok.shape[1:],
                            rt_typeok.dtype,
                        ),
                    ]
                )
        return tb._replace(rt_typeok=rt_typeok)

    def _init_state(self, p: EncodedProblem, N: int):
        import jax.numpy as jnp

        from karpenter_tpu.ops.encode import empty_reqs
        from karpenter_tpu.solver import tpu_kernel as K

        vocab, table = p.vocab, p.table
        R = table.num_resources
        I = p.num_types
        IW = max(1, (I + 31) // 32)
        E = p.num_existing
        Gv = max(len(p.vgroups), 1)
        Gh = max(len(p.hgroups), 1)
        S = E + N
        creq = empty_reqs(vocab, (N,))
        jreq = lambda r: Reqs(*(jnp.asarray(a) for a in r))
        v_cnt = (
            p.v_cnt if len(p.vgroups) else np.zeros((1, p.vmax or 1), np.int32)
        )
        h_cnt = np.zeros((Gh, S), np.int32)
        for g, slot, c in p.h_seed:
            h_cnt[g, slot] += c
        return K.State(
            active=jnp.zeros(N, bool),
            count=jnp.zeros(N, jnp.int32),
            rank=jnp.zeros(N, jnp.int32),
            tmpl=jnp.zeros(N, jnp.int32),
            creq=jreq(creq),
            crequests=jnp.zeros((N, R), jnp.int32),
            alive=jnp.zeros((N, IW), jnp.uint32),
            cmax_alloc=jnp.zeros((N, R), jnp.int32),
            n_claims=jnp.zeros((), jnp.int32),
            ereq=jreq(p.ereq),
            eavail=jnp.asarray(p.eavail),
            trem=jnp.asarray(p.tlimit_rem),
            v_cnt=jnp.asarray(v_cnt),
            h_cnt=jnp.asarray(h_cnt),
            rescap=jnp.asarray(
                p.rescap0
                if p.rescap0 is not None
                else np.zeros(0, np.int32)
            ),
            held=jnp.zeros(
                (N, (p.num_reservations + 31) // 32), jnp.uint32
            ),
            hp_used=jnp.concatenate(
                [
                    jnp.asarray(
                        p.ehp
                        if p.ehp is not None
                        else np.zeros((E, 0), np.uint32)
                    ),
                    jnp.zeros(
                        (N, (p.num_host_ports + 31) // 32), jnp.uint32
                    ),
                ]
            ),
        )

    def _grow(self, p: EncodedProblem, st, seq, N: int):
        """Pad the carried device state from N to 2N claim slots (overflow
        continuation). Pad rows replicate _init_state's inert slots."""
        import jax.numpy as jnp

        from karpenter_tpu.ops.encode import empty_reqs

        vocab, table = p.vocab, p.table
        R = table.num_resources
        IW = max(1, (p.num_types + 31) // 32)
        Gh = st.h_cnt.shape[0]
        pad = (
            Reqs(*(jnp.asarray(a) for a in empty_reqs(vocab, (N,)))),
            jnp.zeros(N, bool),
            jnp.zeros(N, jnp.int32),
            jnp.zeros((N, R), jnp.int32),
            jnp.zeros((N, IW), jnp.uint32),
            jnp.zeros((N, R), jnp.int32),
            jnp.zeros(N, jnp.int32),
            jnp.zeros((Gh, N), jnp.int32),
            jnp.zeros((N, st.held.shape[1]), jnp.uint32),
            jnp.zeros((N, st.hp_used.shape[1]), jnp.uint32),
        )
        return _grow_state(st, seq, pad)

    def _upload_pod_tables(self, p: EncodedProblem) -> None:
        """Ship pod tables to the device once per solve; per-round pod
        batches are then just an index array (the tunnel charges per byte).
        Heavy rows (requirements, type screens, tolerations, topology
        ownership) upload per REQUIREMENT-class; request vectors and
        inverse-anti rows per encode-class; selection rows per unique
        (namespace, labels). The only [P]-sized uploads are the class and
        selection-row index columns, in the narrowest dtype that fits —
        a 10k-pod mix with 10k distinct request vectors but a handful of
        requirement shapes ships KBs, not MBs."""
        import jax.numpy as jnp

        cr = self._cr_padded(p)  # class idx per rclass, bucket-padded
        Gv = max(len(p.vgroups), 1)
        Gh = max(len(p.hgroups), 1)

        def pad_g(a, G):
            if a.shape[1] == G:
                return a
            return np.zeros((a.shape[0], G), a.dtype)

        def narrow(a):
            return a.astype(np.uint16) if a.max(initial=0) < 65536 else a

        # bucket the class/selection axes so steady-state traffic with a
        # drifting class mix reuses one compiled _gather_xs/_run_arrays
        # program per rung (solver/buckets.py; pad rows are never gathered
        # — the index columns only hold real ids)
        if buckets.enabled():
            NC_pad = buckets.bucket(p.prequests_c.shape[0])
            U_pad = buckets.bucket(p.sel_rows_v.shape[0])
            P_pad = buckets.bucket(len(p.pod_class))
        else:
            NC_pad = p.prequests_c.shape[0]
            U_pad = p.sel_rows_v.shape[0]
            P_pad = len(p.pod_class)
        NR_pad = max(len(cr), 1)
        pad_c = lambda a: buckets.pad_rows(a, NC_pad)
        pad_u = lambda a: buckets.pad_rows(a, U_pad)
        pad_p = lambda a: buckets.pad_rows(a, P_pad)
        self._dev_tables = (
            Reqs(*(jnp.asarray(a[cr]) for a in p.preq_c)),
            # _pod_typeok is already per requirement-class on device
            self._typeok,
            jnp.asarray(p.ptol_t_c[cr]),
            jnp.asarray(p.ptol_e_c[cr]),
            jnp.asarray(p.ptopo_kind_c[cr]),
            jnp.asarray(p.ptopo_gid_c[cr]),
            jnp.asarray(p.ptopo_sel_c[cr]),
            jnp.asarray(pad_c(p.rcls_of)),
            jnp.asarray(pad_c(p.prequests_c)),
            jnp.asarray(pad_p(narrow(p.pod_class))),
            jnp.asarray(pad_p(narrow(p.srow))),
            jnp.asarray(pad_u(pad_g(p.sel_rows_v, Gv))),
            jnp.asarray(pad_u(pad_g(p.sel_rows_h, Gh))),
            jnp.asarray(pad_c(pad_g(p.pinv_h_c, Gh))),
            jnp.asarray(pad_c(pad_g(p.pown_h_c, Gh))),
            jnp.asarray(buckets.pad_rows(p.ntiers_r, NR_pad, fill=1)),
            jnp.asarray(buckets.pad_rows(p.rrow_of_rcls, NR_pad)),
            jnp.asarray(p.php_own_c[cr]),
            jnp.asarray(p.php_conf_c[cr]),
        )
        from karpenter_tpu.solver.tpu_problem import (
            TOPO_AFFINITY_H,
            TOPO_AFFINITY_V,
        )

        aff_c = np.isin(
            p.ptopo_kind_c, (TOPO_AFFINITY_V, TOPO_AFFINITY_H)
        ).any(axis=1)
        self._aff_c = aff_c

    def _pod_xs_with_idx(
        self, p: EncodedProblem, indices: list[int], pad_to: int = 0
    ):
        """(PodX, idx_d, n_d) — the run-driver arrays (_run_x) derive from
        the same uploaded index array, so callers thread it through rather
        than paying a second [P] upload. `pad_to` overrides the pod-axis
        pad (>= the own pow-2 rung): fleet windows pad every lane to the
        window's shared rung so lanes stack (solver/fleet.py); pad
        positions carry idx 0 and valid=False either way."""
        import jax.numpy as jnp

        n = len(indices)
        P_pad = max(_pow2(n), pad_to)
        dt = np.uint16 if len(p.pods) < 65536 else np.int32
        idx = np.zeros(P_pad, dtype=dt)
        idx[:n] = np.asarray(indices, dtype=dt)
        idx_d = jnp.asarray(idx)
        n_d = jnp.asarray(np.int32(n))
        return _gather_xs(self._dev_tables, idx_d, n_d), idx_d, n_d

    def _pod_xs(self, p: EncodedProblem, indices: list[int]):
        return self._pod_xs_with_idx(p, indices)[0]

    # -- decoding --------------------------------------------------------

    def _decode(
        self,
        p: EncodedProblem,
        st,
        kinds: np.ndarray,
        slots: np.ndarray,
        timed_out: bool,
    ) -> Results:
        import jax

        from karpenter_tpu.solver import tpu_kernel as K

        vocab, table = p.vocab, p.table
        scheduler = self.oracle
        st_dev = st  # the device State (st is rebound to the host view)
        # Two-phase fetch: the scalar claim count first, then ONLY the live
        # claim rows (pow2-bucketed so the slice jit caches) — most solves
        # fill a fraction of the N padded slots, and the tunnel charges per
        # byte. count/rank/topology stay behind entirely.
        n_claims = int(jax.device_get(st.n_claims))
        N = st.active.shape[0]
        # finish the solve's odometer with the final-state facts: claim
        # slots opened + high-water occupancy of the padded slot pool
        # (claim_slot_div sizing feedback; ISSUE 15)
        odo = getattr(self, "last_odometer", None)
        if odo is not None:
            from karpenter_tpu import tracing

            odo["claims_opened"] = n_claims
            odo["claim_slots"] = N
            occupancy = (n_claims / N) if N else 0.0
            odo["claim_occupancy"] = round(occupancy, 4)
            tracing.KERNEL_CLAIMS_OPENED.inc(by=n_claims)
            tracing.KERNEL_CLAIM_OCCUPANCY.observe(occupancy)
        n2 = min(_pow2(max(n_claims, 1), floor=64), N)
        E = st.eavail.shape[0]
        if n2 >= _DEDUP_DECODE_MIN:
            # big solve: row-dedup fetch (the extra round trip for the
            # unique count amortizes against MBs of duplicate rows)
            small, compact = _dedup_decode_state(st, n2=n2, ecols=E + n2)
            (
                n_uniq, inv, crequests, tmpl, eavail, ereq_t, v_cnt, h_cnt,
                trem,
            ) = jax.device_get(small)
            n_uniq = int(n_uniq)
            u2 = min(_pow2(max(n_uniq, 1), floor=64), n2)
            uniq = np.asarray(jax.device_get(_slice_rows(compact, u2)))
            # unpack [u2, C] u32 back into the creq fields + alive, then
            # rematerialize full-size arrays through the inverse index —
            # host memcpy is cheap; only the tunnel bytes mattered
            TW = vocab.total_words
            Kk = vocab.num_keys
            IW = uniq.shape[1] - 2 * TW - 6 * Kk
            o = 0

            def take(w):
                nonlocal o
                out = uniq[:, o : o + w]
                o += w
                return out

            creq_u = Reqs(
                mask=take(TW),
                exmask=take(TW),
                other=take(Kk).astype(bool),
                notin=take(Kk).astype(bool),
                defined=take(Kk).astype(bool),
                gt=take(Kk).view(np.int32),
                lt=take(Kk).view(np.int32),
                minv=take(Kk).view(np.int32),
            )
            alive_u = take(IW)
            inv = np.asarray(inv)
            creq = Reqs(*(np.ascontiguousarray(a[inv]) for a in creq_u))
            alive = np.ascontiguousarray(alive_u[inv])
        else:
            (
                creq, crequests, alive, tmpl, eavail, ereq_t, v_cnt, h_cnt,
                trem,
            ) = jax.device_get(_slice_decode_state(st, n2=n2, ecols=E + n2))
            creq = Reqs(*(np.asarray(a) for a in creq))
            alive = np.asarray(alive)
        # shared tail: both branches produced (creq, alive); the small raw
        # fields convert identically
        crequests = np.asarray(crequests)
        tmpl = np.asarray(tmpl)
        eavail = np.asarray(eavail)
        ereq = Reqs(*(np.asarray(a) for a in ereq_t))
        st = _DecodeView(
            np.int32(n_claims), creq, crequests, alive, tmpl,
            eavail, ereq, np.asarray(v_cnt), np.asarray(h_cnt),
        )

        # global type table order (same construction as encode_problem)
        type_idx: dict[int, int] = {}
        for nct in scheduler.templates:
            for it in nct.instance_type_options:
                if id(it) not in type_idx:
                    type_idx[id(it)] = len(type_idx)

        # unpack every claim's surviving-type bits in one vectorized pass
        # (a per-claim per-type Python loop dominates decode at scale)
        alive_bits = np.unpackbits(
            np.ascontiguousarray(alive[:n_claims]).astype("<u4").view(np.uint8),
            axis=-1,
            bitorder="little",
        )
        ordered_types = [None] * len(type_idx)
        for it_id, i in type_idx.items():
            ordered_types[i] = it_id
        types_by_id = {}
        for nct in scheduler.templates:
            for it in nct.instance_type_options:
                types_by_id[id(it)] = it

        # many claims share identical requirement rows (same class/template/
        # domain) — decode each distinct row once and copy
        row_cache: dict[bytes, Requirements] = {}
        live_cache: dict[bytes, list] = {}

        def decode_cached(slot: int) -> Requirements:
            key = b"".join(np.ascontiguousarray(a[slot]).tobytes() for a in creq)
            got = row_cache.get(key)
            if got is None:
                got = decode_row(vocab, creq.row(slot))
                row_cache[key] = got
            return got.copy()

        claims: list[SchedulingNodeClaim] = []
        for slot in range(n_claims):
            nct = scheduler.templates[int(tmpl[slot])]
            claim = SchedulingNodeClaim.__new__(SchedulingNodeClaim)
            claim.template = nct
            claim.hostname = nodes_mod.next_placeholder_hostname()
            claim.requirements = decode_cached(slot)
            # claims of a class/template share surviving-type sets; build
            # each distinct list once and copy (lists are replaced, never
            # mutated, downstream)
            akey = alive_bits[slot].tobytes()
            live = live_cache.get(akey)
            if live is None:
                live_idx = np.flatnonzero(alive_bits[slot])
                live = [types_by_id[ordered_types[i]] for i in live_idx]
                live_cache[akey] = live
            claim.instance_type_options = InstanceTypes(live)
            claim.requests = table.decode(crequests[slot])
            claim.daemon_resources = scheduler.daemon_overhead[nct]
            claim.pods = []
            claim.topology = scheduler.topology
            claim.host_port_usage = scheduler.daemon_host_ports[nct].copy()
            claim.reservation_manager = scheduler.reservation_manager
            claim.reserved_offerings = []
            claim.reserved_offering_strict = False
            claim.reserved_capacity_enabled = self.opts.reserved_capacity_enabled
            claim.annotations = dict(nct.annotations)
            claims.append(claim)

        # reserved-capacity sync (round 5): the kernel's per-claim held
        # bitmasks become the claims' reserved_offerings and the host
        # ReservationManager's state, so finalize() (reservation-id
        # requirements) and later solves see the device's consumption
        if p.num_reservations and n_claims:
            import jax as _jax

            held_rows = _jax.device_get(st_dev.held[:n_claims])
            held_bits = np.unpackbits(
                np.ascontiguousarray(held_rows).astype("<u4").view(np.uint8),
                axis=-1,
                bitorder="little",
            )[:, : p.num_reservations]
            from karpenter_tpu.scheduling import ALLOW_UNDEFINED_WELL_KNOWN_LABELS

            for slot, claim in enumerate(claims):
                rids = {p.rid_names[r] for r in np.flatnonzero(held_bits[slot])}
                if not rids:
                    continue
                # the oracle's reserved_offerings list: every compatible
                # reserved offering of a surviving type whose rid is held
                # (nodes.py _offerings_to_reserve final pass)
                offs = [
                    o
                    for it in claim.instance_type_options
                    for o in it.offerings
                    if o.available
                    and o.capacity_type() == well_known.CAPACITY_TYPE_RESERVED
                    and o.reservation_id() in rids
                    and claim.requirements.is_compatible(
                        o.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
                    )
                ]
                claim.reserved_offerings = offs
                scheduler.reservation_manager.reserve(claim.hostname, *offs)

        for e, node in enumerate(scheduler.existing_nodes):
            node.remaining_resources = table.decode(eavail[e])
            reqs = decode_row(vocab, ereq.row(e))
            reqs.add(
                Requirement(
                    well_known.HOSTNAME_LABEL_KEY, Operator.IN, [node.view.hostname]
                )
            )
            node.requirements = reqs

        # sync nodepool-limit spend back to the host (scheduler.go:831
        # subtractMax semantics live on device in st.trem) so a partitioned
        # oracle continuation and later control-plane reads see the kernel's
        # spend — without this, hybrid partitioning double-spends limits
        trem = np.asarray(trem)
        for t, nct in enumerate(scheduler.templates):
            if not p.thas_limits[t]:
                continue
            rem = {}
            for name, ri in table.index.items():
                if p.tlimit_def[t, ri]:
                    rem[name] = int(trem[t, ri]) * table.scale[ri]
            scheduler.remaining_resources[nct.nodepool_name] = rem

        from karpenter_tpu.scheduling.hostports import get_host_ports

        pod_errors: dict[str, str] = {}
        for i, pod in enumerate(p.pods):
            kind, slot = int(kinds[i]), int(slots[i])
            if kind == K.KIND_EXISTING:
                scheduler.existing_nodes[slot].pods.append(pod)
                if p.num_host_ports:
                    hp = get_host_ports(pod)
                    if hp:
                        scheduler.existing_nodes[slot].host_port_usage.add(pod, hp)
            elif kind in (K.KIND_CLAIM, K.KIND_NEW):
                claims[slot].pods.append(pod)
                if p.num_host_ports:
                    hp = get_host_ports(pod)
                    if hp:
                        claims[slot].host_port_usage.add(pod, hp)
            elif not timed_out:
                pod_errors[pod.uid] = self._error_for(pod)

        scheduler.new_node_claims = claims

        # sync the host Topology's domain counts from the device state so a
        # continuation solve (per-pod hybrid partitioning) and any later
        # host-side simulation see the TPU-recorded placements as truth
        v_cnt = np.asarray(st.v_cnt)
        h_cnt = np.asarray(st.h_cnt)
        for g, vg in enumerate(p.vgroups):
            vals = vocab.values[vg.kid]
            tg = vg.group
            for vid, val in enumerate(vals):
                if p.v_reg[g, vid] or v_cnt[g, vid]:
                    tg.domains[val] = int(v_cnt[g, vid])
        # claim slots sit at offset p.num_existing (the pow2-PADDED count,
        # not the real node count — padded columns in between are inert)
        hostnames = [
            (slot, n.view.hostname)
            for slot, n in enumerate(scheduler.existing_nodes)
        ] + [(p.num_existing + j, c.hostname) for j, c in enumerate(claims)]
        for g, hg in enumerate(p.hgroups):
            tg = hg.group
            for slot, hn in hostnames:
                c = int(h_cnt[g, slot])
                if c:
                    tg.domains[hn] = c
        return Results(
            new_node_claims=claims,
            existing_nodes=scheduler.existing_nodes,
            pod_errors=pod_errors,
            timed_out=timed_out,
        )

    def _error_for(self, pod: Pod) -> str:
        """Reconstruct a template-level failure message host-side with the
        oracle's exact wording (nodeclaim.go:296 semantics; oracle._add):
        limits filter, then requirements compat (well-known labels may be
        undefined, like SchedulingNodeClaim.can_add), then the instance
        type filter. Topology-caused failures get a generic message — the
        batched solver doesn't track per-template reasons.
        tests/test_scheduling_families.py pins text parity per case."""
        from karpenter_tpu.scheduling import (
            ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
            Taints,
        )
        from karpenter_tpu.solver.oracle import _filter_by_remaining_resources

        scheduler = self.oracle
        data = scheduler.cached_pod_data[pod.uid]
        errs = []
        for nct in scheduler.templates:
            # oracle._add order: limits filter, then can_add (taints ->
            # compat -> type filter). The oracle REQUEUES failed pods, so
            # the error it reports is the FINAL attempt's — evaluated
            # against end-of-solve state, which is exactly what the synced
            # remaining_resources reflect here.
            its = nct.instance_type_options
            rem = scheduler.remaining_resources.get(nct.nodepool_name)
            if rem is not None:
                its = InstanceTypes(_filter_by_remaining_resources(its, rem))
                if not its:
                    errs.append(
                        f"all available instance types exceed limits for "
                        f"nodepool {nct.nodepool_name!r}"
                    )
                    continue
            terr = Taints(nct.taints).tolerates_pod(pod)
            if terr is not None:
                errs.append(terr)
                continue
            requirements = Requirements(nct.requirements.values())
            err = requirements.compatible(
                data.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
            )
            if err is not None:
                errs.append(f"incompatible requirements, {err}")
                continue
            requirements.add(*data.requirements.values())
            total = res.merge(
                scheduler.daemon_overhead[nct], data.requests
            )
            _, _, ferr = filter_instance_types(
                its,
                requirements,
                data.requests,
                scheduler.daemon_overhead[nct],
                total,
            )
            if ferr is not None:
                errs.append(str(ferr))
        if not errs:
            return "unsatisfiable topology constraint"
        return "; ".join(errs)
