"""The sequential oracle scheduler: an exact re-implementation of the
reference's first-fit-decreasing bin-packer
(/root/reference/pkg/controllers/provisioning/scheduling/scheduler.go:377-675).

Role in this framework: (1) the semantic referee every TPU kernel is tested
against, and (2) the in-process CPU baseline the TPU solver's speedup is
measured from (BASELINE.md). The TPU solver (karpenter_tpu.solver.tpu)
reproduces this exact pod ordering and lowest-index-wins target selection so
results are bit-identical where kernels cover the semantics.
"""

from __future__ import annotations

import time as time_mod
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    NodePool,
    Pod,
    TaintEffect,
    Toleration,
    TopologySpreadConstraint,
    WhenUnsatisfiable,
)
from karpenter_tpu.cloudprovider.types import InstanceTypes
from karpenter_tpu.scheduling import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    Requirements,
    Taints,
)
from karpenter_tpu.scheduling.hostports import HostPortUsage, get_host_ports
from karpenter_tpu.solver.nodes import (
    ExistingNode,
    NodeClaimTemplate,
    PodData,
    ReservationManager,
    ReservedOfferingError,
    SchedulingNodeClaim,
    StateNodeView,
    filter_instance_types,
)
from karpenter_tpu.solver.topology import Topology
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.resources import ResourceList


# ---------------------------------------------------------------------------
# queue (queue.go:31-108)


class Queue:
    """Pods sorted CPU-then-memory descending with stable tiebreak; stall
    detection via per-pod lastLen."""

    def __init__(self, pods: list[Pod], pod_data: dict[str, PodData]):
        from karpenter_tpu.solver.ordering import ffd_sort_key

        self.pods = deque(
            sorted(
                pods,
                key=lambda p: ffd_sort_key(p, pod_data[p.uid].requests),
            )
        )
        self.last_len: dict[str, int] = {}

    def pop(self) -> Optional[Pod]:
        if not self.pods:
            return None
        p = self.pods[0]
        if self.last_len.get(p.uid) == len(self.pods):
            return None  # cycled through without progress
        self.pods.popleft()
        return p

    def push(self, pod: Pod) -> None:
        self.pods.append(pod)
        self.last_len[pod.uid] = len(self.pods)


# ---------------------------------------------------------------------------
# preference relaxation (preferences.go:38-161)


class Preferences:
    def __init__(self, tolerate_prefer_no_schedule: bool = False):
        self.tolerate_prefer_no_schedule = tolerate_prefer_no_schedule

    def relax(self, pod: Pod) -> bool:
        for fn in (
            self._remove_required_node_affinity_term,
            self._remove_preferred_pod_affinity,
            self._remove_preferred_pod_anti_affinity,
            self._remove_preferred_node_affinity,
            self._remove_tsc_schedule_anyway,
        ):
            if fn(pod):
                self._invalidate_class_caches(pod)
                return True
        if self.tolerate_prefer_no_schedule and self._tolerate_prefer_no_schedule(pod):
            self._invalidate_class_caches(pod)
            return True
        return False

    @staticmethod
    def _invalidate_class_caches(pod: Pod) -> None:
        """Relaxation changes every decision-relevant field the memoized
        class key covers (solver/ordering.py); deep copies inherit the
        cached attributes, so a mutated pod must drop them or the encoder
        would dedup it into its pre-relaxation class."""
        for attr in ("_ktpu_class_key", "_ktpu_class_repr", "_ktpu_class_sig"):
            try:
                delattr(pod, attr)
            except AttributeError:
                pass

    @staticmethod
    def _remove_required_node_affinity_term(pod: Pod) -> bool:
        na = pod.node_affinity
        if na is None or len(na.required_terms) <= 1:
            return False  # can't remove the last required term
        na.required_terms = na.required_terms[1:]
        return True

    @staticmethod
    def _remove_preferred_pod_affinity(pod: Pod) -> bool:
        if not pod.pod_affinity_preferred:
            return False
        pod.pod_affinity_preferred.sort(key=lambda w: -w.weight)
        pod.pod_affinity_preferred = pod.pod_affinity_preferred[1:]
        return True

    @staticmethod
    def _remove_preferred_pod_anti_affinity(pod: Pod) -> bool:
        if not pod.pod_anti_affinity_preferred:
            return False
        pod.pod_anti_affinity_preferred.sort(key=lambda w: -w.weight)
        pod.pod_anti_affinity_preferred = pod.pod_anti_affinity_preferred[1:]
        return True

    @staticmethod
    def _remove_preferred_node_affinity(pod: Pod) -> bool:
        na = pod.node_affinity
        if na is None or not na.preferred:
            return False
        na.preferred.sort(key=lambda t: -t.weight)
        na.preferred = na.preferred[1:]
        return True

    @staticmethod
    def _remove_tsc_schedule_anyway(pod: Pod) -> bool:
        for i, tsc in enumerate(pod.topology_spread_constraints):
            if tsc.when_unsatisfiable == WhenUnsatisfiable.SCHEDULE_ANYWAY:
                # swap-remove like the reference
                last = len(pod.topology_spread_constraints) - 1
                pod.topology_spread_constraints[i] = pod.topology_spread_constraints[last]
                pod.topology_spread_constraints.pop()
                return True
        return False

    @staticmethod
    def _tolerate_prefer_no_schedule(pod: Pod) -> bool:
        marker = Toleration(operator="Exists", effect=TaintEffect.PREFER_NO_SCHEDULE)
        if any(
            t.operator == "Exists" and t.effect == TaintEffect.PREFER_NO_SCHEDULE and not t.key
            for t in pod.tolerations
        ):
            return False
        pod.tolerations = pod.tolerations + [marker]
        return True


# ---------------------------------------------------------------------------
# scheduler


@dataclass
class SchedulerOptions:
    ignore_preferences: bool = False  # PreferencePolicy=Ignore
    min_values_best_effort: bool = False  # MinValuesPolicy=BestEffort
    reserved_capacity_enabled: bool = False  # ReservedCapacity feature gate
    reserved_offering_strict: bool = False
    timeout_seconds: Optional[float] = None  # Solve budget (provisioner.go:366)
    # TPU solver: initial claim-slot pool = pods/claim_slot_div (pow2-
    # bucketed, grows on kernel overflow). Smaller pools cut per-step
    # candidate screens AND the decode fetch; the runs kernel pads the
    # carried state and CONTINUES on overflow (decisions are N-invariant),
    # so undersizing costs one growth event, not a re-solve.
    claim_slot_div: int = 16
    # Hybrid routing: batches below this size with NO topology groups run
    # on the oracle — the device launch/tunnel floor (~0.7s) beats the
    # oracle only above the crossover. Measured on the tunneled v5e
    # (requests-only mix, 50 types): oracle 1006 pods/s vs TPU 556 at 500
    # pods; TPU wins from ~1k. Topology-bearing problems skip the check:
    # the oracle's domain tracking collapses its throughput (~150 pods/s
    # at 250 diverse pods — TPU already 2x ahead there). 0 disables.
    tpu_min_pods: int = 768


@dataclass
class Results:
    """scheduler.go Results."""

    new_node_claims: list[SchedulingNodeClaim]
    existing_nodes: list[ExistingNode]
    pod_errors: dict[str, str]  # pod uid -> reason
    # Solve hit its deadline: pods still in the queue were never attempted
    # (the reference surfaces this as ctx.Err() next to Results).
    timed_out: bool = False

    def all_pods_scheduled(self) -> bool:
        return not self.pod_errors and not self.timed_out

    def node_pod_counts(self) -> list[int]:
        return [len(n.pods) for n in self.new_node_claims]


class Scheduler:
    """scheduler.go:116 NewScheduler + Solve."""

    def __init__(
        self,
        node_pools: list[NodePool],
        instance_types_by_pool: dict[str, InstanceTypes],
        topology: Topology,
        state_nodes: Optional[list[StateNodeView]] = None,
        daemonset_pods: Optional[list[Pod]] = None,
        options: Optional[SchedulerOptions] = None,
    ):
        self.opts = options or SchedulerOptions()
        self.topology = topology
        # NodePools are tried in weight order (provisioner.go:262)
        node_pools = sorted(node_pools, key=lambda np: (-np.weight, np.name))
        tolerate_pns = any(
            t.effect == TaintEffect.PREFER_NO_SCHEDULE
            for np in node_pools
            for t in np.template.taints
        )
        self.preferences = Preferences(tolerate_prefer_no_schedule=tolerate_pns)
        self.reservation_manager = ReservationManager(instance_types_by_pool)

        # Pre-filter each template's instance types (scheduler.go:140-158)
        self.templates: list[NodeClaimTemplate] = []
        for np in node_pools:
            nct = NodeClaimTemplate(np)
            its, _, _ = filter_instance_types(
                instance_types_by_pool.get(np.name, InstanceTypes()),
                nct.requirements,
                {},
                {},
                {},
                self.opts.min_values_best_effort,
            )
            if not its:
                continue  # nodepool requirements filtered out all instance types
            nct.instance_type_options = its
            self.templates.append(nct)

        self.remaining_resources: dict[str, ResourceList] = {
            np.name: dict(np.limits) for np in node_pools if np.limits
        }

        daemonset_pods = daemonset_pods or []
        self.daemon_overhead: dict[NodeClaimTemplate, ResourceList] = {}
        self.daemon_host_ports: dict[NodeClaimTemplate, HostPortUsage] = {}
        for nct in self.templates:
            compatible = [
                p for p in daemonset_pods if self._daemon_compatible(nct, p)
            ]
            self.daemon_overhead[nct] = res.requests_for_pods(compatible)
            usage = HostPortUsage()
            for p in compatible:
                usage.add(p, get_host_ports(p))
            self.daemon_host_ports[nct] = usage

        self.cached_pod_data: dict[str, PodData] = {}
        self.new_node_claims: list[SchedulingNodeClaim] = []
        self.existing_nodes: list[ExistingNode] = []
        for view in sorted(
            state_nodes or [], key=lambda v: (not v.initialized, v.name)
        ):
            daemons = [
                p
                for p in daemonset_pods
                if Taints(view.taints).tolerates_pod(p) is None
                and Requirements.from_labels(view.labels).compatible(
                    Requirements.strict_from_pod(p)
                )
                is None
            ]
            self.existing_nodes.append(
                ExistingNode(
                    view, topology, list(view.taints), res.requests_for_pods(daemons)
                )
            )
            pool = view.labels.get(well_known.NODEPOOL_LABEL_KEY)
            if pool in self.remaining_resources:
                self.remaining_resources[pool] = res.subtract(
                    self.remaining_resources[pool], view.capacity
                )

    @staticmethod
    def _daemon_compatible(nct: NodeClaimTemplate, pod: Pod) -> bool:
        """scheduler.go:806 isDaemonPodCompatible: tolerate PreferNoSchedule,
        relax required node affinity terms until compatible."""
        p = pod.deep_copy()
        Preferences._tolerate_prefer_no_schedule(p)
        if Taints(nct.taints).tolerates_pod(p) is not None:
            return False
        while True:
            if nct.requirements.is_compatible(
                Requirements.strict_from_pod(p), ALLOW_UNDEFINED_WELL_KNOWN_LABELS
            ):
                return True
            if not Preferences._remove_required_node_affinity_term(p):
                return False

    # -- solve ----------------------------------------------------------------

    def _update_cached_pod_data(self, pod: Pod) -> None:
        if self.opts.ignore_preferences:
            requirements = Requirements.strict_from_pod(pod)
        else:
            requirements = Requirements.from_pod(pod)
        strict = requirements
        if pod.node_affinity is not None and pod.node_affinity.preferred:
            strict = Requirements.strict_from_pod(pod)
        self.cached_pod_data[pod.uid] = PodData(
            # RequestsForPods semantics: every pod also consumes one unit of
            # the `pods` count resource (resources.go:30-38, scheduler.go:481)
            requests=res.requests_for_pods([pod]),
            requirements=requirements,
            strict_requirements=strict,
        )

    def solve(self, pods: list[Pod]) -> Results:
        """scheduler.go:377 Solve: loop while progress is being made — this
        (not topo-sort) is what makes batch affinities and alternating
        max-skew placements work."""
        pod_errors: dict[str, str] = {}
        for p in pods:
            self._update_cached_pod_data(p)
        q = Queue(list(pods), self.cached_pod_data)
        deadline = (
            time_mod.monotonic() + self.opts.timeout_seconds
            if self.opts.timeout_seconds
            else None
        )
        timed_out = False
        while True:
            pod = q.pop()
            if pod is None:
                break
            if deadline is not None and time_mod.monotonic() > deadline:
                timed_out = True
                break
            err = self._try_schedule(pod.deep_copy())
            if err is not None:
                pod_errors[pod.uid] = err
                self.topology.update(pod)
                self._update_cached_pod_data(pod)
                q.push(pod)
            else:
                pod_errors.pop(pod.uid, None)
        for claim in self.new_node_claims:
            claim.finalize()
        return Results(
            new_node_claims=self.new_node_claims,
            existing_nodes=self.existing_nodes,
            pod_errors=pod_errors,
            timed_out=timed_out,
        )

    def _try_schedule(self, pod: Pod) -> Optional[str]:
        """scheduler.go:434 trySchedule: relax-until-schedulable on a copy."""
        while True:
            err = self._add(pod)
            if err is None:
                return None
            if isinstance(err, ReservedOfferingError):
                return str(err)
            if not self.preferences.relax(pod):
                return err if isinstance(err, str) else str(err)
            self.topology.update(pod)
            self._update_cached_pod_data(pod)

    def _add(self, pod: Pod):
        """scheduler.go:488 add: existing nodes -> in-flight claims (sorted by
        pod count) -> new claim from templates in weight order; always the
        lowest index that accepts."""
        pod_data = self.cached_pod_data[pod.uid]
        # existing nodes first
        for node in self.existing_nodes:
            requirements, err = node.can_add(pod, pod_data)
            if err is None:
                node.add(pod, pod_data, requirements)
                return None
        # then in-flight claims, fewest pods first (scheduler.go:499)
        self.new_node_claims.sort(key=lambda c: len(c.pods))
        for claim in self.new_node_claims:
            try:
                requirements, its, offerings, err = claim.can_add(
                    pod, pod_data, self.opts.min_values_best_effort
                )
            except ReservedOfferingError:
                continue
            if err is None:
                claim.add(pod, pod_data, requirements, its, offerings)
                return None
        if not self.templates:
            return "nodepool requirements filtered out all available instance types"
        # then a new claim per template in weight order
        errs = []
        for nct in self.templates:
            its = nct.instance_type_options
            if nct.nodepool_name in self.remaining_resources:
                its = InstanceTypes(
                    _filter_by_remaining_resources(
                        its, self.remaining_resources[nct.nodepool_name]
                    )
                )
                if not its:
                    errs.append(
                        f"all available instance types exceed limits for nodepool "
                        f"{nct.nodepool_name!r}"
                    )
                    continue
            claim = SchedulingNodeClaim(
                nct,
                self.topology,
                self.daemon_overhead[nct],
                self.daemon_host_ports[nct],
                its,
                self.reservation_manager,
                reserved_offering_strict=self.opts.reserved_offering_strict,
                reserved_capacity_enabled=self.opts.reserved_capacity_enabled,
            )
            try:
                requirements, its2, offerings, err = claim.can_add(
                    pod, pod_data, self.opts.min_values_best_effort
                )
            except ReservedOfferingError as roe:
                return roe
            if err is not None:
                errs.append(err)
                continue
            claim.add(pod, pod_data, requirements, its2, offerings)
            self.new_node_claims.append(claim)
            if claim.nodepool_name in self.remaining_resources:
                self.remaining_resources[claim.nodepool_name] = _subtract_max(
                    self.remaining_resources[claim.nodepool_name],
                    claim.instance_type_options,
                )
            return None
        return "; ".join(errs) if errs else "failed to schedule pod"


def _subtract_max(remaining: ResourceList, instance_types: InstanceTypes) -> ResourceList:
    """Pessimistically subtract the max capacity over surviving instance types
    (scheduler.go:831 subtractMax)."""
    if not instance_types:
        return remaining
    max_caps = res.max_resources(*(it.capacity for it in instance_types))
    return {k: v - max_caps.get(k, 0) for k, v in remaining.items()}


def _filter_by_remaining_resources(instance_types, remaining: ResourceList):
    """Drop instance types whose capacity would breach nodepool limits
    (scheduler.go:851 filterByRemainingResources)."""
    out = []
    for it in instance_types:
        if all(it.capacity.get(name, 0) <= rem for name, rem in remaining.items()):
            out.append(it)
    return out
