"""The run kernel: bulk-commits whole runs of identical pods per device step.

The shared FFD comparator (solver/ordering.py) makes pods of the same
*scheduling class* contiguous, so the solve order is a sequence of runs of
pods whose per-pod decisions are the same function of solver state. This
kernel walks the pod sequence with a `lax.while_loop`:

- the first pod of a run (and every pod of a non-bulkable class) executes
  the exact per-pod step (`tpu_kernel._step`) — bit-identical to the
  oracle's decision by construction;
- after a bulkable run's head commits, a *run cache* is built once:
  per-target viability plus exact pod-unit capacities — deliberately tiny
  (a few KB), because the loop carry is copied every iteration on TPU and
  carry bytes are the dominant per-step cost. Final requirement rows are
  re-derived per commit window instead of cached per claim: the class's
  topology tightening is static for the run (gates below), so evaluating
  it for <= W chosen targets costs a few small ops;
- the remaining pods of the run then commit in bulk phases, many pods per
  device step: existing nodes first-fill by cumulative capacity, in-flight
  claims absorb one pod per claim per *count level* (the reference's
  ascending-pod-count round-robin, scheduler.go:499), a lone feasible claim
  absorbs a whole window, and fresh claims fill to their exact pod capacity
  in one step each.

Bulkability gates (everything else falls back to the exact per-pod step,
so unsupported shapes cost speed, never correctness):
- the class owns no hostname-family constraints and is not selected by any
  inverse anti-affinity group (their viability reads per-slot counts that
  change on every commit);
- its zone-family constraints are self-stable: pod-affinity (the positive
  domain set cannot change mid-run — commits only land inside it), or
  spread/anti-affinity whose group does NOT select the pod (the counts the
  constraint reads never move during the run);
- problem-level: no minValues anywhere, no nodepool limits, every instance
  type's requirement sets are single-valued or whole-vocabulary per key
  (pairwise screens are then exact three-way), and offerings decompose per
  key (zone×capacity-type coverage is a cartesian product) — computed
  host-side in solver/tpu.py and folded into the per-pod bulk flag.

Claim ordering uses an event-sequence key instead of the rank vector:
claims sort by (pod count asc, then creation order asc within count 1,
promotion recency desc within count >= 2) — provably the same total order
the reference's stable re-sort + front-of-block moves produce
(tpu_kernel._rank_after_increment/_rank_after_create). The rank vector a
`_step` call expects is derived from this key on demand.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from karpenter_tpu.ops.encode import Reqs
from karpenter_tpu.ops.kernels import compat, intersect
from karpenter_tpu.solver import tpu_kernel as K
from karpenter_tpu.solver.tpu_kernel import (
    INF_I,
    KIND_CLAIM,
    KIND_EXISTING,
    KIND_FAIL,
    KIND_NEW,
    PodX,
    State,
    Tables,
    _apply_tighten,
    _broadcast_row,
    _eval_topology,
    _pack,
    _row,
    _set_row,
    _topo_nonempty_ok,
    _type_filter,
    _unpack,
)

# bulk window: max pods committed per device step. Window work is O(W·I),
# so keep it modest — runs longer than W just take extra (cheap) steps.
W = 64
# seq-key building block; counts and seqs both stay far below this
_SEQ_LIM = 1 << 21

# bulk dispatch cases
_CASE_EXISTING = 0
_CASE_LEVEL = 1
_CASE_SOLO = 2
_CASE_NEW = 3
_CASE_FAIL = 4


class RunX(NamedTuple):
    """Per-pod driver inputs beyond PodX."""

    x: PodX  # [P] rows
    is_head: jax.Array  # [P] bool — first pod of its run
    bulk: jax.Array  # [P] bool — class is bulkable (incl. problem gates)
    # class owns a pod-affinity constraint: its head must commit through the
    # exact step BEFORE the cache builds (a bootstrap commit changes the
    # positive-domain set the rest of the run is confined to)
    aff: jax.Array  # [P] bool
    run_rem: jax.Array  # [P] i32 — pods from i to its run's end, inclusive


class RunCache(NamedTuple):
    """Static-per-run products, built once after the head pod commits.
    Kept small: the whole cache rides the loop carry."""

    active: jax.Array  # scalar bool — bulk mode on for the current run
    ok_c: jax.Array  # [N] bool — compat+tol+topology-viable (pre-capacity)
    excl_c: jax.Array  # [N] bool — exact-verify failures (permanent per run)
    ok_e: jax.Array  # [E] bool
    cape: jax.Array  # [E] i32 — exact pod-units remaining
    ok_t: jax.Array  # [T] bool — fully viable (incl. topology, static)
    final_t: Reqs  # [T] — rows a fresh claim writes (T is tiny)
    alive_t: jax.Array  # [T, IW] u32 — surviving types for a fresh claim
    capt: jax.Array  # [T] i32 — exact pod-units of a fresh claim


def _seq_key(count, seq, active):
    """The claim ordering key (module docstring). Smaller = earlier."""
    within = jnp.where(count == 1, seq, _SEQ_LIM - 1 - seq)
    return jnp.where(active, count * _SEQ_LIM + within, jnp.iinfo(jnp.int32).max)


def _derive_rank(st: State, seq) -> jax.Array:
    """Rank vector for a `_step` call: position of each active claim under
    the seq-key order."""
    key = _seq_key(st.count, seq, st.active)
    order = jnp.argsort(key)
    rank = jnp.zeros_like(seq).at[order].set(jnp.arange(seq.shape[0], dtype=seq.dtype))
    return rank


def _pod_units(avail, preq):
    """Exact pod-units a resource vector can absorb: min over requested
    dims of floor(avail/req); 0 if any dim is negative."""
    pos = preq > 0
    per = jnp.where(pos, avail // jnp.maximum(preq, 1), INF_I)
    units = jnp.min(per, axis=-1)
    return jnp.where(jnp.all(avail >= 0, axis=-1), jnp.maximum(units, 0), 0)


def _rows_at(r: Reqs, idx) -> Reqs:
    return Reqs(*(a[idx] for a in r))


def _set_rows(dst: Reqs, idx, rows: Reqs, pred) -> Reqs:
    """Scatter rows into dst at idx where pred; out-of-bounds writes (the
    masked-off window tail) are dropped by XLA scatter semantics."""
    n = dst.mask.shape[0]
    safe = jnp.where(pred, idx, n)
    return Reqs(*(a.at[safe].set(r) for a, r in zip(dst, rows)))


# ---------------------------------------------------------------------------
# per-window final-row derivation (topology is static for bulkable runs)


def _final_claim_rows(tb: Tables, st: State, x: PodX, slots):
    """Re-derive merged+tightened rows for a window of claim slots."""
    E = st.eavail.shape[0]
    creq_rows = _rows_at(st.creq, slots)
    merged = intersect(creq_rows, _broadcast_row(x.preq, slots.shape[0]), tb.va)
    te = _eval_topology(
        merged, st.h_cnt[:, E + slots], jnp.any(st.h_cnt > 0, axis=-1), x, st, tb
    )
    return _apply_tighten(merged, te.tight, te.touched, tb.va)


def _final_existing_rows(tb: Tables, st: State, x: PodX, slots):
    ereq_rows = _rows_at(st.ereq, slots)
    merged = intersect(ereq_rows, _broadcast_row(x.preq, slots.shape[0]), tb.va)
    te = _eval_topology(
        merged, st.h_cnt[:, slots], jnp.any(st.h_cnt > 0, axis=-1), x, st, tb
    )
    return _apply_tighten(merged, te.tight, te.touched, tb.va)


# ---------------------------------------------------------------------------
# cache construction (after the head pod of a bulkable run commits)


def _build_cache(tb: Tables, st: State, x: PodX) -> RunCache:
    E = st.eavail.shape[0]
    N = st.active.shape[0]
    T = tb.tdaemon.shape[0]
    I = tb.ialloc.shape[0]

    nonempty_h = jnp.any(st.h_cnt > 0, axis=-1)

    # claims
    merged_c = intersect(st.creq, _broadcast_row(x.preq, N), tb.va)
    compat_c = compat(st.creq, _broadcast_row(x.preq, N), tb.va, True)
    te_c = _eval_topology(merged_c, st.h_cnt[:, E:], nonempty_h, x, st, tb)
    final_c = _apply_tighten(merged_c, te_c.tight, te_c.touched, tb.va)
    ok_c = (
        x.tol_t[jnp.clip(st.tmpl, 0, max(T - 1, 0))]
        & compat_c
        & te_c.viable
        & _topo_nonempty_ok(final_c, te_c.touched, tb.va)
    )

    # existing nodes
    if E > 0:
        merged_e = intersect(st.ereq, _broadcast_row(x.preq, E), tb.va)
        compat_e = compat(st.ereq, _broadcast_row(x.preq, E), tb.va, False)
        te_e = _eval_topology(merged_e, st.h_cnt[:, :E], nonempty_h, x, st, tb)
        final_e = _apply_tighten(merged_e, te_e.tight, te_e.touched, tb.va)
        ok_e = (
            x.tol_e
            & compat_e
            & te_e.viable
            & _topo_nonempty_ok(final_e, te_e.touched, tb.va)
        )
        cape = _pod_units(st.eavail, x.prequests[None, :])
    else:
        ok_e = jnp.zeros((E,), bool)
        cape = jnp.zeros((E,), jnp.int32)

    # templates (full ladder; topology is static for bulkable classes)
    merged_t = intersect(tb.treq, _broadcast_row(x.preq, T), tb.va)
    compat_t = compat(tb.treq, _broadcast_row(x.preq, T), tb.va, True)
    te_t = _eval_topology(
        merged_t,
        jnp.zeros((st.h_cnt.shape[0], T), st.h_cnt.dtype),
        nonempty_h,
        x,
        st,
        tb,
    )
    final_t = _apply_tighten(merged_t, te_t.tight, te_t.touched, tb.va)
    tmember = jax.vmap(lambda w: _unpack(w, I))(tb.ttypes)  # [T, I]
    totals = tb.tdaemon + x.prequests
    t_final_i = jax.vmap(
        lambda f, a, tot: _type_filter(f, a, tot, tb), in_axes=(0, 0, 0)
    )(final_t, tmember, totals)
    per_type = jax.vmap(
        lambda daemon, fi: jnp.where(
            fi, _pod_units(tb.ialloc - daemon[None, :], x.prequests[None, :]), 0
        )
    )(tb.tdaemon, t_final_i)  # [T, I]
    capt = jnp.max(per_type, axis=-1, initial=0)
    ok_t = (
        compat_t
        & x.tol_t
        & te_t.viable
        & _topo_nonempty_ok(final_t, te_t.touched, tb.va)
        & jnp.any(t_final_i, axis=-1)
    )

    return RunCache(
        active=jnp.ones((), bool),
        ok_c=ok_c,
        excl_c=jnp.zeros((N,), bool),
        ok_e=ok_e,
        cape=cape,
        ok_t=ok_t,
        final_t=final_t,
        alive_t=jax.vmap(lambda b: _pack(b, st.alive.shape[1]))(t_final_i),
        capt=capt,
    )


def _empty_cache(tb: Tables, st: State) -> RunCache:
    E = st.eavail.shape[0]
    N = st.active.shape[0]
    T = tb.tdaemon.shape[0]
    treq0 = jax.tree.map(lambda a: jnp.zeros((T,) + a.shape[1:], a.dtype), tb.treq)
    return RunCache(
        active=jnp.zeros((), bool),
        ok_c=jnp.zeros((N,), bool),
        excl_c=jnp.zeros((N,), bool),
        ok_e=jnp.zeros((E,), bool),
        cape=jnp.zeros((E,), jnp.int32),
        ok_t=jnp.zeros((T,), bool),
        final_t=treq0,
        alive_t=jnp.zeros((T, st.alive.shape[1]), jnp.uint32),
        capt=jnp.zeros((T,), jnp.int32),
    )


# ---------------------------------------------------------------------------
# bulk record: the topology Record for a window of commits


def _record_window(st, tb, finals: Reqs, slots, preds, selv, selh, ownh, allow_wk):
    """Batched tpu_kernel._record over a [Wb] window. finals are per-commit
    final rows; slots are global (existing e, or E + claim slot)."""
    segbits = jax.vmap(lambda m: K._gather_bits(m, tb.v_word, tb.v_bit))(
        finals.mask
    )  # [Wb, Gv, VMAX]
    exbits = jax.vmap(lambda m: K._gather_bits(m, tb.v_word, tb.v_bit))(finals.exmask)
    other_k = finals.other[:, jnp.clip(tb.v_kid, 0, None)]  # [Wb, Gv]
    popc = jnp.sum(segbits.astype(jnp.int32), axis=-1)
    single = (popc == 1) & ~other_k
    filt_ok = jax.vmap(lambda f: K._eval_filters(tb.v_filt, f, tb, allow_wk))(finals)
    add = jnp.where(
        tb.v_anti[None, :, None],
        jnp.where(other_k[..., None], exbits, segbits),
        segbits & single[..., None],
    )
    gate_v = (preds[:, None] & selv & filt_ok)[..., None]
    v_cnt = st.v_cnt + jnp.sum((add & gate_v).astype(jnp.int32), axis=0)

    filt_ok_h = jax.vmap(lambda f: K._eval_filters(tb.h_filt, f, tb, allow_wk))(finals)
    contrib = jnp.where(tb.h_inverse[None, :], ownh, selh & filt_ok_h)  # [Wb, Gh]
    vals = (preds[:, None] & contrib).astype(jnp.int32)  # [Wb, Gh]
    h_cnt = st.h_cnt.at[:, slots].add(vals.T)
    return v_cnt, h_cnt


# ---------------------------------------------------------------------------
# the driver


@functools.partial(jax.jit, static_argnames=("relax",))
def solve_runs(
    tb: Tables, st: State, rx: RunX, seq, next_seq, n_valid,
    relax: bool = True,
):
    """Returns (state, seq, next_seq, kinds[P], slots[P], overflowed,
    odometer, ptr). Pods at index >= n_valid are shape padding and are
    never visited. `odometer` (tpu_kernel.Odometer) is this dispatch's
    device-truth counter block — steps = while-loop trips (what wave
    packing must shrink), bulk_steps the bulk-phase subset, tier counts
    from the relax ladder; write-only, so decisions are unchanged.
    `relax` is trace-time static (see tpu_kernel.solve_scan): preference-
    free problems compile the plain exact step with no tier machinery."""
    P = rx.is_head.shape[0]
    N = st.active.shape[0]
    E = st.eavail.shape[0]
    I = tb.ialloc.shape[0]
    IW = st.alive.shape[1]

    kinds0 = jnp.full(P + W, KIND_FAIL, jnp.int32)
    slots0 = jnp.full(P + W, -1, jnp.int32)

    def xrow(i) -> PodX:
        return jax.tree.map(lambda a: a[i], rx.x)

    def window_rows(ptr):
        idx = jnp.clip(ptr + jnp.arange(W), 0, P - 1)
        return rx.x.sel_v[idx], rx.x.sel_h[idx], rx.x.own_h[idx]

    def write_window(buf, ptr, vals):
        return jax.lax.dynamic_update_slice(buf, vals, (ptr,))

    # -- exact per-pod path (every run head; all pods of non-bulk classes)
    def single_step(carry):
        st, rc, seq, nseq, ptr, kinds, slots, over, odo = carry
        x = xrow(ptr)
        # the seq key is a monotone transform of the rank order, and _step
        # only ever uses rank for min-selection (its rank updates are
        # discarded here), so the key substitutes directly — no sort
        st_in = st._replace(rank=_seq_key(st.count, seq, st.active))
        if relax:
            st2, (kind, slot, oflow), tiers = K._step_relax(tb, st_in, x)
            odo = K.odo_tier_tick(odo, tiers)
        else:
            st2, (kind, slot, oflow) = K._step(tb, st_in, x)
        joined = kind == KIND_CLAIM
        created = kind == KIND_NEW
        upd = joined | created
        sslot = jnp.where(joined, slot, st.n_claims)
        seq = seq.at[sslot].set(jnp.where(upd, nseq, seq[sslot]))
        nseq = nseq + upd.astype(jnp.int32)
        kinds = kinds.at[ptr].set(kind)
        slots = slots.at[ptr].set(slot)
        odo = odo._replace(steps=odo.steps + 1)
        build = rx.bulk[ptr] & (rx.run_rem[ptr] > 1) & x.valid & ~oflow
        rc = jax.lax.cond(
            build,
            lambda: _build_cache(tb, st2, x),
            lambda: rc._replace(active=jnp.zeros((), bool)),
        )
        # a slot-overflow pod is NOT decided: ptr stays on it so the host's
        # continuation retries it against the grown state (advancing would
        # conflate it with a real failure and the stall check could end the
        # solve with the pod wrongly unschedulable)
        return (
            st2, rc, seq, nseq, ptr + (~oflow).astype(jnp.int32),
            kinds, slots, over | oflow, odo,
        )

    # -- bulk phases ------------------------------------------------------

    def bulk_step(carry):
        st, rc, seq, nseq, ptr, kinds, slots, over, odo = carry
        odo = odo._replace(
            steps=odo.steps + 1, bulk_steps=odo.bulk_steps + 1
        )
        x = xrow(ptr)
        rem = rx.run_rem[ptr]
        selv, selh, ownh = window_rows(ptr)
        jW = jnp.arange(W)

        # dynamic hostname budgets: spread-h / anti-h constraints that
        # select the pod consume one slot-unit per commit (skew - count,
        # and 1 - count respectively); everything else about hostname
        # topology is static within the run and lives in the head's ok_*
        def h_budgets(offs, n):
            bud = jnp.full((n,), INF_I, jnp.int32)
            for c in range(x.topo_kind.shape[0]):
                kind = x.topo_kind[c]
                gid = jnp.clip(x.topo_gid[c], 0, st.h_cnt.shape[0] - 1)
                dyn = x.topo_sel[c] & (
                    (kind == K.TOPO_SPREAD_H) | (kind == K.TOPO_ANTI_H)
                )
                cap0 = jnp.where(kind == K.TOPO_SPREAD_H, tb.h_skew[gid], 1)
                cnt = st.h_cnt[gid, offs + jnp.arange(n)]
                bud = jnp.minimum(bud, jnp.where(dyn, cap0 - cnt, INF_I))
            return bud

        def h_budget_fresh():
            bud = jnp.full((), INF_I, jnp.int32)
            for c in range(x.topo_kind.shape[0]):
                kind = x.topo_kind[c]
                gid = jnp.clip(x.topo_gid[c], 0, st.h_cnt.shape[0] - 1)
                dyn = x.topo_sel[c] & (
                    (kind == K.TOPO_SPREAD_H) | (kind == K.TOPO_ANTI_H)
                )
                cap0 = jnp.where(kind == K.TOPO_SPREAD_H, tb.h_skew[gid], 1)
                bud = jnp.minimum(bud, jnp.where(dyn, cap0, INF_I))
            return bud

        hb_c = h_budgets(E, N)
        hb_fresh = h_budget_fresh()
        feas_e = rc.ok_e & (rc.cape > 0) & ((h_budgets(0, E) > 0) if E > 0 else True)
        screen_fits = jnp.all(st.crequests + x.prequests <= st.cmax_alloc, axis=-1)
        screen_types = jnp.any((st.alive & x.typeok) != 0, axis=-1)
        feas_c = (
            st.active
            & rc.ok_c
            & ~rc.excl_c
            & screen_fits
            & screen_types
            & (hb_c > 0)
        )
        nfeas = jnp.sum(feas_c.astype(jnp.int32))
        viable_t = rc.ok_t & (rc.capt > 0)
        t_first = jnp.argmin(
            jnp.where(viable_t, jnp.arange(viable_t.shape[0]), INF_I)
        )
        anyt = jnp.any(viable_t)

        any_e = jnp.any(feas_e) if E > 0 else jnp.zeros((), bool)
        case = jnp.where(
            any_e,
            _CASE_EXISTING,
            jnp.where(
                nfeas > 1,
                _CASE_LEVEL,
                jnp.where(
                    nfeas == 1, _CASE_SOLO, jnp.where(anyt, _CASE_NEW, _CASE_FAIL)
                ),
            ),
        )

        def commit_claims(rc, tgt, pred, kc, finals, fis, solo_units=None):
            """tgt[j] gets pod ptr+j for j < kc; targets distinct unless
            solo_units is set (then all window rows share tgt[0]). fis are
            the surviving-type bits per window row, computed once by the
            caller (they double as the exact-feasibility verify)."""
            # windowed scatters, not whole-[N] adds: the loop carry's big
            # arrays must only be written at touched rows or every bulk
            # step pays a full-State rewrite
            if solo_units is None:
                padd = pred.astype(jnp.int32)
                safe_t = jnp.where(pred, tgt, N)
                crequests = st.crequests.at[safe_t].add(
                    x.prequests[None, :].astype(jnp.int32)
                )
                count = st.count.at[safe_t].add(1)
                seq2 = seq.at[tgt].max(jnp.where(pred, nseq + jW, -1))
                nseq2 = nseq + kc
            else:
                crequests = st.crequests.at[tgt[0]].add(
                    solo_units * x.prequests
                )
                count = st.count.at[tgt[0]].add(solo_units)
                seq2 = seq.at[tgt[0]].set(nseq + solo_units - 1)
                nseq2 = nseq + solo_units
            creq = _set_rows(st.creq, tgt, finals, pred)
            packs = jax.vmap(lambda fi: _pack(fi, IW))(fis)
            cmaxs = jnp.max(
                jnp.where(fis[..., None], tb.ialloc[None], -INF_I), axis=1
            )
            safe = jnp.where(pred, tgt, N)
            alive = st.alive.at[safe].set(packs)
            cmax_alloc = st.cmax_alloc.at[safe].set(cmaxs)
            v_cnt, h_cnt = _record_window(
                st, tb, finals, E + tgt, pred, selv, selh, ownh,
                allow_wk=jnp.ones((), bool),
            )
            st2 = st._replace(
                crequests=crequests, count=count, creq=creq, alive=alive,
                cmax_alloc=cmax_alloc, v_cnt=v_cnt, h_cnt=h_cnt,
            )
            wk = jnp.where(pred, KIND_CLAIM, KIND_FAIL)
            ws = jnp.where(pred, tgt, -1)
            return st2, rc, seq2, nseq2, kc, wk, ws, jnp.zeros((), bool)

        def case_existing(_):
            caps = jnp.where(feas_e, jnp.minimum(rc.cape, h_budgets(0, E)), 0)
            cum = jnp.cumsum(caps) - caps
            total = jnp.sum(caps)
            k = jnp.minimum(rem, jnp.minimum(total, W)).astype(jnp.int32)
            tgt = jnp.argmax(
                (jW[:, None] >= cum[None, :]) & (jW[:, None] < (cum + caps)[None, :]),
                axis=1,
            )
            pred = jW < k
            finals = _final_existing_rows(tb, st, x, tgt)
            added = jnp.zeros(E, jnp.int32).at[tgt].add(pred.astype(jnp.int32))
            safe_e = jnp.where(pred, tgt, E)
            eavail = st.eavail.at[safe_e].add(
                -x.prequests[None, :].astype(jnp.int32)
            )
            ereq = _set_rows(st.ereq, tgt, finals, pred)
            v_cnt, h_cnt = _record_window(
                st, tb, finals, tgt, pred, selv, selh, ownh,
                allow_wk=jnp.zeros((), bool),
            )
            st2 = st._replace(eavail=eavail, ereq=ereq, v_cnt=v_cnt, h_cnt=h_cnt)
            rc2 = rc._replace(cape=rc.cape - added)
            wk = jnp.where(pred, KIND_EXISTING, KIND_FAIL)
            ws = jnp.where(pred, tgt, -1)
            return st2, rc2, seq, nseq, k, wk, ws, jnp.zeros((), bool)

        def case_level(_):
            # one pod per feasible claim at the minimum count, in block
            # order (creation order at count 1, promotion recency above).
            # Only the W smallest keys are needed: top_k (stable — ties
            # break toward the lower index, which never matters here since
            # live keys are distinct event seqs) replaces a full argsort
            # of N, the dominant per-step cost at large N.
            cmin = jnp.min(jnp.where(feas_c, st.count, INF_I))
            lvl = feas_c & (st.count == cmin)
            ordkey = jnp.where(
                lvl, jnp.where(cmin == 1, seq, _SEQ_LIM - 1 - seq), INF_I
            )
            _, order_w = jax.lax.top_k(-ordkey, min(W, N))
            nlvl = jnp.sum(lvl.astype(jnp.int32))
            k = jnp.minimum(rem, jnp.minimum(nlvl, W)).astype(jnp.int32)
            # pad to W when N < W; k <= nlvl <= N keeps padding unused
            tgt = jnp.zeros(W, order_w.dtype).at[: min(W, N)].set(order_w)
            pred = jW < k
            finals = _final_claim_rows(tb, st, x, tgt)
            totals = st.crequests[tgt] + x.prequests[None, :]
            # surviving-type bits for the grown request: both the exact
            # feasibility verify (the _step while_loop equivalent) and the
            # post-commit alive/cmax refresh
            fis = jax.vmap(
                lambda f, s, tot: _type_filter(f, _unpack(st.alive[s], I), tot, tb)
            )(finals, tgt, totals)
            okv = jnp.any(fis, axis=-1) | ~pred
            newexcl = jnp.zeros(N + 1, bool).at[jnp.where(pred & ~okv, tgt, N)].set(
                True
            )[:N]
            pred = pred & okv
            kc = jnp.sum(pred.astype(jnp.int32))
            # compact verified targets to the window front so pods
            # ptr..ptr+kc-1 map onto them in block order
            vorder = jnp.argsort(jnp.where(pred, jW, INF_I))
            tgt = tgt[vorder]
            finals = _rows_at(finals, vorder)
            fis = fis[vorder]
            pred = jW < kc
            rc2 = rc._replace(excl_c=rc.excl_c | newexcl)
            return commit_claims(rc2, tgt, pred, kc, finals, fis)

        def case_solo(_):
            s = jnp.argmax(feas_c)
            finals = _final_claim_rows(tb, st, x, jnp.full((W,), s, jnp.int32))
            final_n = _row(finals, 0)
            alive_n = _unpack(st.alive[s], I)
            per = jnp.where(
                alive_n,
                _pod_units(
                    tb.ialloc - st.crequests[s][None, :], x.prequests[None, :]
                ),
                0,
            )
            tok = _type_filter(final_n, alive_n, st.crequests[s] + x.prequests, tb)
            per = jnp.where(tok, per, 0)
            cap = jnp.minimum(jnp.max(per, initial=0), hb_c[s])
            k = jnp.minimum(rem, jnp.minimum(cap, W)).astype(jnp.int32)

            def commit(_):
                pred = jW < k
                tgt = jnp.full((W,), s, jnp.int32)
                # types surviving the k-pod load on this claim
                fi_k = _type_filter(
                    final_n, alive_n, st.crequests[s] + k * x.prequests, tb
                )
                fis = jnp.broadcast_to(fi_k, (W,) + fi_k.shape)
                return commit_claims(rc, tgt, pred, k, finals, fis, solo_units=k)

            def excl(_):
                rc2 = rc._replace(excl_c=rc.excl_c.at[s].set(True))
                return (
                    st, rc2, seq, nseq, jnp.int32(0),
                    jnp.full((W,), KIND_FAIL, jnp.int32),
                    jnp.full((W,), -1, jnp.int32), jnp.zeros((), bool),
                )

            return jax.lax.cond(k > 0, commit, excl, None)

        def case_new(_):
            t = t_first
            m = st.n_claims
            oflow = m >= N

            def create(_):
                # per-claim fill: a fresh claim absorbs cstar pods (capacity
                # and hostname-budget capped), then the next pod starts the
                # next claim — so one step can create a whole batch of
                # claims: pod j lands on claim m + j//cstar. The sequential
                # order (create, fill, create, ...) is reproduced by the
                # event seqs: later-created claims promoted later sit in
                # front of their count block.
                cstar = jnp.minimum(rc.capt[t], hb_fresh).astype(jnp.int32)
                ncl = jnp.minimum(
                    jnp.minimum((rem + cstar - 1) // cstar, N - m),
                    jnp.maximum(W // cstar, 1),
                ).astype(jnp.int32)
                f = jnp.minimum(rem, jnp.minimum(ncl * cstar, W)).astype(jnp.int32)
                ncl = (f + cstar - 1) // cstar  # claims actually touched
                final_n = _row(rc.final_t, t)
                pred = jW < f
                cl_of = jnp.minimum(jW // cstar, N - 1 - 0)  # claim offset per pod
                # claims touched are the CONTIGUOUS window m..m+ncl-1; all
                # writes below scatter through this [W]-sized index so no
                # [N]-sized carry array is rewritten whole (a full-State
                # rewrite per step dominated bulk-phase cost)
                pred_c = jW < ncl  # claim lanes of the window
                idx_c = jnp.where(pred_c, m + jW, N)  # OOB drops padding
                # per-claim fill counts: full cstar except a partial last
                fills_w = jnp.clip(f - jW * cstar, 0, cstar)  # [W]
                alive_m = _unpack(rc.alive_t[t], I)
                per = jnp.where(
                    alive_m,
                    _pod_units(
                        tb.ialloc - tb.tdaemon[t][None, :], x.prequests[None, :]
                    ),
                    0,
                )
                # surviving types per touched claim depend on its fill —
                # but only TWO fill levels exist (cstar for full claims, a
                # partial remainder on the last), so compute per LEVEL and
                # select per claim instead of vmapping an O(N x I) filter
                # (at 16k slots x 1k types that intermediate dominated the
                # whole step)
                last_fill = f - (ncl - 1) * cstar
                fi_full = alive_m & (per >= cstar)
                fi_last = alive_m & (per >= last_fill)
                pack_full = _pack(fi_full, IW)
                pack_last = _pack(fi_last, IW)
                cmax_full = jnp.max(
                    jnp.where(fi_full[:, None], tb.ialloc, -INF_I), axis=0
                )
                cmax_last = jnp.max(
                    jnp.where(fi_last[:, None], tb.ialloc, -INF_I), axis=0
                )
                is_full_w = fills_w == cstar  # [W]
                crequests = st.crequests.at[idx_c].set(
                    tb.tdaemon[t][None, :]
                    + fills_w[:, None] * x.prequests[None, :]
                )
                alive = st.alive.at[idx_c].set(
                    jnp.where(is_full_w[:, None], pack_full[None], pack_last[None])
                )
                cmax_alloc = st.cmax_alloc.at[idx_c].set(
                    jnp.where(is_full_w[:, None], cmax_full[None], cmax_last[None])
                )
                finals_w = jax.tree.map(
                    lambda a: jnp.broadcast_to(a, (W,) + a.shape), final_n
                )
                creq = _set_rows(st.creq, idx_c, finals_w, pred_c)
                count = st.count.at[idx_c].set(fills_w)
                active = st.active.at[idx_c].set(True)
                tmpl = st.tmpl.at[idx_c].set(t)
                # claim q's last fill event: cumulative pods through it
                cum_w = jnp.cumsum(fills_w) - 1  # [W]
                seq2 = seq.at[idx_c].set(nseq + cum_w)
                nseq2 = nseq + f
                finals = jax.tree.map(
                    lambda a: jnp.broadcast_to(a, (W,) + a.shape), final_n
                )
                v_cnt, h_cnt = _record_window(
                    st, tb, finals, E + jnp.minimum(m + cl_of, N - 1), pred,
                    selv, selh, ownh, allow_wk=jnp.ones((), bool),
                )
                st2 = st._replace(
                    crequests=crequests, count=count, creq=creq, alive=alive,
                    cmax_alloc=cmax_alloc, active=active, tmpl=tmpl,
                    n_claims=m + ncl, v_cnt=v_cnt, h_cnt=h_cnt,
                )
                wk = jnp.where(pred, KIND_NEW, KIND_FAIL)
                ws = jnp.where(pred, m + cl_of, -1)
                return st2, rc, seq2, nseq2, f, wk, ws, jnp.zeros((), bool)

            def overflow(_):
                return (
                    st, rc, seq, nseq, jnp.int32(0),
                    jnp.full((W,), KIND_FAIL, jnp.int32),
                    jnp.full((W,), -1, jnp.int32), jnp.ones((), bool),
                )

            return jax.lax.cond(oflow, overflow, create, None)

        def case_fail(_):
            k = jnp.minimum(rem, W).astype(jnp.int32)
            return (
                st, rc, seq, nseq, k,
                jnp.full((W,), KIND_FAIL, jnp.int32),
                jnp.full((W,), -1, jnp.int32), jnp.zeros((), bool),
            )

        st2, rc2, seq2, nseq2, k, wk, ws, oflow = jax.lax.switch(
            case,
            (
                case_existing if E > 0 else case_fail,
                case_level,
                case_solo,
                case_new,
                case_fail,
            ),
            None,
        )
        kinds = write_window(kinds, ptr, wk)
        slots = write_window(slots, ptr, ws)
        return st2, rc2, seq2, nseq2, ptr + k, kinds, slots, over | oflow, odo

    def cond(carry):
        _, _, _, _, ptr, _, _, over, _ = carry
        # overflow stops the walk at the CURRENT pod: everything before
        # ptr is decided and N-invariant (slot count only gates creation),
        # so the host can pad the state to more slots and continue from
        # ptr instead of re-solving from scratch
        return (ptr < n_valid) & ~over

    def body(carry):
        st, rc, seq, nseq, ptr, kinds, slots, over, odo = carry
        # non-affinity bulk heads build the cache up front and commit their
        # own pod through the bulk machinery — one heavy evaluation per run
        # instead of two (the exact step would redo it)
        head_build = (
            rx.is_head[ptr] & rx.bulk[ptr] & ~rx.aff[ptr] & rx.x.valid[ptr]
        )
        rc = jax.lax.cond(
            head_build,
            lambda: _build_cache(tb, st, xrow(ptr)),
            lambda: rc,
        )
        inner = (st, rc, seq, nseq, ptr, kinds, slots, over, odo)
        use_bulk = rc.active & rx.bulk[ptr] & (head_build | ~rx.is_head[ptr])
        return jax.lax.cond(use_bulk, bulk_step, single_step, inner)

    rc0 = _empty_cache(tb, st)
    (
        st, rc, seq, next_seq, ptr, kinds, slots, over, odo
    ) = jax.lax.while_loop(
        cond,
        body,
        (
            st, rc0, seq, next_seq, jnp.int32(0), kinds0, slots0,
            jnp.zeros((), bool), K.odometer_zero(),
        ),
    )
    return st, seq, next_seq, kinds[:P], slots[:P], over, odo, ptr
