"""Pow-2 shape buckets: every axis the jitted entry points see is padded
to a small ladder OUTSIDE jit, so steady-state traffic reuses a handful of
compiled programs instead of recompiling per problem size.

SURVEY.md §7 names the wall ("counts vary per Solve -> recompilation
pressure. Plan: bucketed padding to fixed shapes (pow-2 pods/types/keys),
interning layer outside jit"); BENCH_r03-r05 measured it at 25-57s of
one-time compile. The ladder bounds the number of distinct compiled
shapes per axis to log2(range), which is what makes ahead-of-time
compilation (solver/aot.py) and the persistent cache
(jaxsetup.ensure_compilation_cache) a finite, enumerable artifact.

Bucketed axes and their sentinel-invisibility arguments:

- pods P: per-round index arrays pad to pow2 (TpuScheduler._pod_xs_with_idx);
  padding positions carry idx 0 and `valid=False`, the kernel never visits
  them. The per-pod class/selection columns uploaded once per solve pad
  here (`pad_rows`) — padded entries are only ever gathered by padding
  positions.
- claim slots N: pow2 since round 3 (adaptive growth doubles the bucket);
  inert slots are `active=False` rows the per-step screens skip.
- existing-node slots E: pow2 since round 5 (tpu_problem E_pad); padded
  slots carry eavail=-1 (fails every fits check) and all-False toleration
  columns.
- instance types I (`pad_types`): padded type rows are members of NO
  template (`ttypes` bits stay 0), so `tmember`/`talive` exclude them from
  every exact filter and they can never enter a claim's surviving-type
  set; ialloc/icap are zero and ireq rows empty, but both sit behind the
  membership gate.
- offerings O (`pad_offerings`): padded rows carry `ovalid=False`, which
  the kernel ANDs into the offering screen (tpu_kernel._type_filter and
  the reservation candidate mask) — a padded offering can never witness
  "an offering exists" nor hold a reservation. Host-side gates iterate
  `num_offerings_real` rows only.
- vocab words/keys: Vocab.finalize(pad_words=..., pad_keys=...) pads each
  key's word count and the key count. Phantom word bits are exactly the
  tail bits a non-multiple-of-32 value count already leaves in its last
  word: never set in full_mask, never set by any encoded row, invisible
  to every seg reduction. Phantom keys are named under a reserved prefix,
  carry one zero word and no values; every row leaves them
  defined=False, which gates all of compat/intersect semantics.
- requirement classes NR / encode classes NC / selection rows U
  (`pad_rows`, applied in TpuScheduler._upload_pod_tables): the gather
  indices (cls/srow/rcls_of columns) only ever contain real ids, so pad
  rows are dead weight shipped for shape stability.

The parity proof is tests/test_buckets.py: problems straddling each
bucket edge stay bit-identical to the oracle, and two different real
sizes in one bucket hit the identical compiled program (0 traces on the
second solve).

Opt out with KARPENTER_SHAPE_BUCKETS=0 (exact shapes, the pre-bucketing
behavior — kept for A/B debugging, not for production).
"""

from __future__ import annotations

import os

import numpy as np

# reserved prefix for phantom vocab keys — ops/vocab.py owns it (ops/
# cannot import solver/); re-exported here for bucket-layer consumers
from karpenter_tpu.ops.vocab import PAD_KEY_PREFIX


def enabled() -> bool:
    """Shape bucketing is ON by default; KARPENTER_SHAPE_BUCKETS=0/off
    restores exact shapes."""
    raw = os.environ.get("KARPENTER_SHAPE_BUCKETS", "1").strip().lower()
    return raw not in ("0", "off", "false", "")


def bucket(n: int, floor: int = 8) -> int:
    """Smallest pow2 >= n, floored (the ladder rung for a count)."""
    out = floor
    while out < n:
        out *= 2
    return out


def bucket_words(n: int) -> int:
    """Per-key word-count rung (floor 1: most keys hold <32 values)."""
    return bucket(n, floor=1)


def bucket_keys(n: int) -> int:
    """Vocab key-count rung."""
    return bucket(n, floor=8)


def bucket_lanes(n: int) -> int:
    """Fleet-lane rung (solver/fleet.py): the pow-2 lane count a
    coalesced batch window pads to (floor 2 — a single lane never
    dispatches the vmapped entry; it falls back to the solo path)."""
    return bucket(n, floor=2)


def ladder(lo: int, hi: int, floor: int = 8) -> list[int]:
    """Every rung from bucket(lo) up to bucket(hi) inclusive."""
    out = []
    r = bucket(max(1, lo), floor=floor)
    top = bucket(max(1, hi), floor=floor)
    while r <= top:
        out.append(r)
        r *= 2
    return out


def pad_rows(a: np.ndarray, n: int, fill=0) -> np.ndarray:
    """Pad axis 0 of `a` up to n rows with `fill` (no-op when already
    there). Used for the per-class upload tables — pad rows are never
    gathered (indices only reference real rows)."""
    if a.shape[0] >= n:
        return a
    pad = np.full((n - a.shape[0],) + a.shape[1:], fill, dtype=a.dtype)
    return np.concatenate([a, pad])


def pad_types(p) -> None:
    """Pad the instance-type axis I to its rung. Padded types belong to no
    template (ttypes bits stay zero), so every exact filter excludes them;
    their ireq rows are empty (all-undefined) and ialloc/icap zero."""
    from karpenter_tpu.ops.encode import Reqs, empty_reqs

    I = p.num_types
    I_pad = bucket(I)
    if I_pad <= I:
        return
    R = p.ialloc.shape[1]
    pad_req = empty_reqs(p.vocab, (I_pad - I,))
    p.ireq = Reqs(*(np.concatenate([a, b]) for a, b in zip(p.ireq, pad_req)))
    p.ialloc = np.concatenate(
        [p.ialloc, np.zeros((I_pad - I, R), np.int32)]
    )
    p.icap = np.concatenate([p.icap, np.zeros((I_pad - I, R), np.int32)])
    p.num_types = I_pad
    # membership words grow with IW = ceil(I/32); bits for padded types
    # stay zero in every template row
    from karpenter_tpu.ops.vocab import WORD_BITS

    IW = max(1, (I_pad + WORD_BITS - 1) // WORD_BITS)
    if p.ttypes.shape[1] < IW:
        p.ttypes = np.concatenate(
            [
                p.ttypes,
                np.zeros((p.ttypes.shape[0], IW - p.ttypes.shape[1]), np.uint32),
            ],
            axis=1,
        )


def pad_offerings(p) -> None:
    """Pad the offering axis O to its rung. Padded rows are screened out
    by ovalid=False in the kernel; host gates iterate only the
    `num_offerings_real` prefix."""
    O = p.otype.shape[0]
    p.num_offerings_real = O
    p.ovalid = np.ones(O, dtype=bool)
    O_pad = bucket(O)
    if O_pad <= O:
        return
    extra = O_pad - O
    p.otype = np.concatenate([p.otype, np.zeros(extra, np.int32)])
    p.oword = np.concatenate([p.oword, np.full((extra, 3), -1, np.int32)])
    p.obit = np.concatenate([p.obit, np.zeros((extra, 3), np.int32)])
    p.orid = np.concatenate([p.orid, np.full(extra, -1, np.int32)])
    p.ovalid = np.concatenate([p.ovalid, np.zeros(extra, dtype=bool)])


def pad_problem(p) -> None:
    """Apply the post-encode pads (types, offerings) to an EncodedProblem.
    Existing-node and vocab padding happen inside encode_problem/finalize
    because downstream tables are sized off them."""
    if not enabled():
        p.num_offerings_real = p.otype.shape[0]
        p.ovalid = np.ones(p.otype.shape[0], dtype=bool)
        return
    pad_types(p)
    pad_offerings(p)


def signature(p) -> tuple:
    """The bucketed shape signature of an encoded problem — the key the
    AOT manifest records per compiled combo (solver/aot.py). Two problems
    with equal signatures compile to byte-identical programs for the
    per-solve entry points."""
    vocab, table = p.vocab, p.table
    return (
        ("E", p.num_existing),
        ("I", p.num_types),
        ("O", int(p.otype.shape[0])),
        ("R", table.num_resources),
        ("T", p.num_templates),
        ("TW", vocab.total_words),
        ("K", vocab.num_keys),
        ("Gv", len(p.vgroups)),
        ("Gh", len(p.hgroups)),
        ("VMAX", p.vmax),
        ("L", p.num_tiers),
        ("HP", (p.num_host_ports + 31) // 32),
        ("NRES", p.num_reservations),
    )
