"""The Solver service boundary: a sidecar process serving Solve over a
unix-domain socket (SURVEY.md §7 M5; the reference's north star is a
Go control plane reaching a TPU solver through cgo->gRPC — this is that
boundary with the same framing discipline, minus the Go toolchain).

Wire protocol (language-neutral; the C++ client in native/solver_client.cc
speaks it too):

    frame   := magic "KTPU" | u32 kind | u32 len | payload[len]
    kind    := 1 SOLVE request   (payload = problem JSON; pods ride as
                                  per-CLASS specs + flat base64 columns,
                                  SURVEY §7 hard-part #5 — the per-pod
                                  payload is O(classes) JSON + O(pods)
                                  binary, not O(pods) JSON)
               2 RESULT response (payload = JSON header + flat base64
                                  assignment arrays: pod i -> claim index /
                                  existing-node index)
               3 ERROR response  (payload = utf-8 message)
               4 PING / 5 PONG   (health)
    u32     := little-endian

Live cluster state (StateNodeViews) crosses the wire too, so a sidecar
solve of a NON-empty cluster — provisioning onto existing capacity,
consolidation simulation — matches the in-process result
(tests/test_service.py asserts equality).

Timeout/cancellation follows provisioner.go:366-374: the request carries
`timeout_seconds`; the server passes it into SchedulerOptions so a Solve
that overruns returns partial results with timed_out=True instead of
hanging the control plane.
"""

from __future__ import annotations

import base64
import json
import os
import socket
import struct
import threading
from typing import Optional

import numpy as np

from karpenter_tpu.api import codec
from karpenter_tpu.solver.hybrid import HybridScheduler
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.solver.oracle import SchedulerOptions
from karpenter_tpu.solver.topology import Topology

MAGIC = b"KTPU"
KIND_SOLVE = 1
KIND_RESULT = 2
KIND_ERROR = 3
KIND_PING = 4
KIND_PONG = 5


def _send_frame(sock: socket.socket, kind: int, payload: bytes) -> None:
    sock.sendall(MAGIC + struct.pack("<II", kind, len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        if not got:
            raise ConnectionError("peer closed")
        buf += got
    return buf


def _recv_frame(sock: socket.socket) -> tuple[int, bytes]:
    head = _recv_exact(sock, 12)
    if head[:4] != MAGIC:
        raise ValueError(f"bad magic {head[:4]!r}")
    kind, length = struct.unpack("<II", head[4:])
    return kind, _recv_exact(sock, length)


# ---------------------------------------------------------------------------
# problem wire form


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode()


def _unb64(s: str, dtype) -> np.ndarray:
    return np.frombuffer(base64.b64decode(s), dtype=dtype)


def _encode_pods_flat(pods) -> dict:
    """Class-deduplicated pod payload: one JSON spec per scheduling class
    plus flat per-pod identity columns (SURVEY §7 hard-part #5 — the wire
    cost is O(classes) JSON + O(pods) binary)."""
    from karpenter_tpu.solver.ordering import pod_encode_class

    classes: dict[tuple, int] = {}
    reps = []
    cls = np.zeros(len(pods), np.int32)
    for i, p in enumerate(pods):
        key = pod_encode_class(p, p.requests) + (
            tuple(sorted(p.metadata.labels.items())),
            tuple(sorted(p.metadata.annotations.items())),
            p.namespace,
        )
        c = classes.get(key)
        if c is None:
            c = len(reps)
            classes[key] = c
            reps.append(p)
        cls[i] = c
    return {
        "classes": codec.to_jsonable(reps),
        "cls": _b64(cls),
        "names": [p.name for p in pods],
        "uids": [p.uid for p in pods],
        "creation": _b64(
            np.asarray([p.metadata.creation_timestamp for p in pods], np.float64)
        ),
    }


def _decode_pods_flat(d: dict):
    reps = codec.from_jsonable(d["classes"])
    cls = _unb64(d["cls"], np.int32)
    creation = _unb64(d["creation"], np.float64)
    out = []
    for i in range(len(cls)):
        p = reps[int(cls[i])].deep_copy()
        p.metadata.name = d["names"][i]
        p.metadata.uid = d["uids"][i]
        p.metadata.creation_timestamp = float(creation[i])
        out.append(p)
    return out


def _encode_views(views) -> list[dict]:
    out = []
    for v in views or []:
        out.append(
            {
                "name": v.name,
                "node_labels": v.node_labels,
                "labels": dict(v.labels),
                "taints": codec.to_jsonable(list(v.taints)),
                "available": dict(v.available),
                "capacity": dict(v.capacity),
                "daemonset_requests": dict(v.daemonset_requests),
                "initialized": v.initialized,
                "hostname": v.hostname,
                "host_ports": {
                    uid: [list(p) for p in ports]
                    for uid, ports in v.host_port_usage._by_pod.items()
                },
                "volumes": {
                    uid: sorted([list(p) for p in s])
                    for uid, s in v.volume_usage._by_pod.items()
                },
                "csi_allocatable": dict(getattr(v, "csi_allocatable", {}) or {}),
            }
        )
    return out


def _decode_views(data) -> Optional[list[StateNodeView]]:
    if data is None:
        return None
    out = []
    for d in data:
        v = StateNodeView(
            name=d["name"],
            node_labels=d["node_labels"],
            labels=d["labels"],
            taints=codec.from_jsonable(d["taints"]),
            available={k: int(x) for k, x in d["available"].items()},
            capacity={k: int(x) for k, x in d["capacity"].items()},
            daemonset_requests={
                k: int(x) for k, x in d["daemonset_requests"].items()
            },
            initialized=d["initialized"],
            hostname=d["hostname"],
            csi_allocatable={
                k: int(v2) for k, v2 in d.get("csi_allocatable", {}).items()
            },
        )
        for uid, ports in d.get("host_ports", {}).items():
            v.host_port_usage._by_pod[uid] = [tuple(p) for p in ports]
        for uid, vols in d.get("volumes", {}).items():
            v.volume_usage._by_pod[uid] = {
                tuple(p) if isinstance(p, list) else ("", p) for p in vols
            }
        out.append(v)
    return out


def encode_problem_request(
    node_pools,
    instance_types_by_pool,
    pods,
    state_node_views=None,
    daemonset_pods=None,
    options: Optional[SchedulerOptions] = None,
    force_oracle: bool = False,
    namespace_labels: Optional[dict] = None,
) -> bytes:
    req = {
        "namespace_labels": namespace_labels or {},
        "node_pools": codec.to_jsonable(node_pools),
        "instance_types_by_pool": {
            k: codec.to_jsonable(list(v)) for k, v in instance_types_by_pool.items()
        },
        "pods_flat": _encode_pods_flat(pods),
        "state_node_views": (
            _encode_views(state_node_views) if state_node_views is not None else None
        ),
        "daemonset_pods": codec.to_jsonable(daemonset_pods or []),
        "options": {
            "ignore_preferences": bool(options and options.ignore_preferences),
            "min_values_best_effort": bool(options and options.min_values_best_effort),
            "timeout_seconds": options.timeout_seconds if options else None,
        },
        "force_oracle": force_oracle,
    }
    return json.dumps(req).encode()


def _decode_problem_request(payload: bytes):
    req = json.loads(payload)
    node_pools = codec.from_jsonable(req["node_pools"])
    its_by_pool = {
        k: codec.from_jsonable(v) for k, v in req["instance_types_by_pool"].items()
    }
    pods = _decode_pods_flat(req["pods_flat"])
    views = _decode_views(req.get("state_node_views"))
    namespace_labels = req.get("namespace_labels") or {}
    daemons = codec.from_jsonable(req.get("daemonset_pods") or [])
    o = req.get("options") or {}
    options = SchedulerOptions(
        ignore_preferences=o.get("ignore_preferences", False),
        min_values_best_effort=o.get("min_values_best_effort", False),
        timeout_seconds=o.get("timeout_seconds"),
    )
    return (
        node_pools,
        its_by_pool,
        pods,
        views,
        daemons,
        options,
        req.get("force_oracle", False),
        namespace_labels,
    )


def _encode_result(results, used_tpu: bool, pods) -> bytes:
    """Flat assignment arrays: pod i (request order) -> claim index, or
    ~existing-node index; -1 = error/unscheduled."""
    claim_of = {}
    for ci, c in enumerate(results.new_node_claims):
        for p in c.pods:
            claim_of[p.uid] = ci
    enode_names = [n.name for n in results.existing_nodes]
    enode_of = {}
    for ei, n in enumerate(results.existing_nodes):
        for p in n.pods:
            enode_of[p.uid] = ei
    assign = np.full(len(pods), -1, np.int32)
    for i, p in enumerate(pods):
        if p.uid in claim_of:
            assign[i] = claim_of[p.uid]
        elif p.uid in enode_of:
            assign[i] = -2 - enode_of[p.uid]  # -2 -> node 0, -3 -> node 1, ...
    claims = [
        {
            "nodepool": c.nodepool_name,
            "instance_types": [it.name for it in c.instance_type_options],
            "requests": dict(c.requests),
        }
        for c in results.new_node_claims
    ]
    out = {
        "used_tpu": used_tpu,
        "timed_out": results.timed_out,
        "pod_errors": dict(results.pod_errors),
        "new_node_claims": claims,
        "existing_node_names": enode_names,
        "assign": _b64(assign),
    }
    return json.dumps(out).encode()


def decode_result(resp: dict, pods) -> dict:
    """Expand the flat assignment array back into per-pod maps."""
    assign = _unb64(resp["assign"], np.int32)
    claims = [dict(c, pod_uids=[]) for c in resp["new_node_claims"]]
    existing = {}
    for i, p in enumerate(pods):
        a = int(assign[i])
        if a >= 0:
            claims[a]["pod_uids"].append(p.uid)
        elif a <= -2:
            existing[p.uid] = resp["existing_node_names"][-2 - a]
    return {
        "used_tpu": resp["used_tpu"],
        "timed_out": resp["timed_out"],
        "pod_errors": resp["pod_errors"],
        "new_node_claims": claims,
        "existing_assignments": existing,
    }


# ---------------------------------------------------------------------------
# server


class SolverServer:
    """Serves SOLVE frames; one connection at a time (the control plane is a
    singleton provisioner — matching the reference's concurrency model)."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.solves = 0

    def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.socket_path)
        self._sock.listen(4)
        self._sock.settimeout(0.2)
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._sock is not None:
            self._sock.close()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                self._handle(conn)
            except (ConnectionError, ValueError):
                pass
            finally:
                conn.close()

    def _handle(self, conn: socket.socket) -> None:
        while not self._stop.is_set():
            kind, payload = _recv_frame(conn)
            if kind == KIND_PING:
                _send_frame(conn, KIND_PONG, b"")
                continue
            if kind != KIND_SOLVE:
                _send_frame(conn, KIND_ERROR, f"unknown kind {kind}".encode())
                continue
            try:
                _send_frame(conn, KIND_RESULT, self._solve(payload))
            except Exception as e:  # error frames, never a dead socket
                _send_frame(conn, KIND_ERROR, str(e).encode())

    def _solve(self, payload: bytes) -> bytes:
        (
            node_pools,
            its_by_pool,
            pods,
            views,
            daemons,
            options,
            force_oracle,
            namespace_labels,
        ) = _decode_problem_request(payload)
        from karpenter_tpu.solver.topology import ClusterSource

        topology = Topology(
            node_pools,
            its_by_pool,
            pods,
            cluster=ClusterSource(namespace_labels=namespace_labels),
            state_node_views=views,
        )
        scheduler = HybridScheduler(
            node_pools,
            its_by_pool,
            topology,
            views,
            daemons,
            options,
            force_oracle=force_oracle,
        )
        results = scheduler.solve(pods)
        self.solves += 1
        return _encode_result(results, bool(scheduler.used_tpu), pods)


# ---------------------------------------------------------------------------
# client


class SolverClient:
    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._sock: Optional[socket.socket] = None

    def connect(self, timeout: float = 5.0) -> None:
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(self.socket_path)

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def ping(self) -> bool:
        _send_frame(self._sock, KIND_PING, b"")
        kind, _ = _recv_frame(self._sock)
        return kind == KIND_PONG

    def solve(
        self,
        node_pools,
        instance_types_by_pool,
        pods,
        state_node_views=None,
        daemonset_pods=None,
        options: Optional[SchedulerOptions] = None,
        force_oracle: bool = False,
        namespace_labels: Optional[dict] = None,
    ) -> dict:
        payload = encode_problem_request(
            node_pools,
            instance_types_by_pool,
            pods,
            state_node_views,
            daemonset_pods,
            options,
            force_oracle,
            namespace_labels,
        )
        _send_frame(self._sock, KIND_SOLVE, payload)
        kind, resp = _recv_frame(self._sock)
        if kind == KIND_ERROR:
            raise RuntimeError(resp.decode())
        return decode_result(json.loads(resp), pods)
