"""The Solver service boundary: a sidecar process serving Solve over a
unix-domain socket (SURVEY.md §7 M5; the reference's north star is a
Go control plane reaching a TPU solver through cgo->gRPC — this is that
boundary with the same framing discipline, minus the Go toolchain).

Wire protocol v2 (language-neutral; the C++ client in native/solver_client.cc
speaks it too):

    frame   := magic "KTPU" | u32 kind | u32 req_id | u32 len | payload[len]
    kind    := 1 SOLVE request   (payload = problem JSON; pods ride as
                                  per-CLASS specs + flat base64 columns,
                                  SURVEY §7 hard-part #5 — the per-pod
                                  payload is O(classes) JSON + O(pods)
                                  binary, not O(pods) JSON)
               2 RESULT response (payload = JSON header + flat base64
                                  assignment arrays: pod i -> claim index /
                                  existing-node index)
               3 ERROR response  (payload = utf-8 message)
               4 PING / 5 PONG   (health)
    u32     := little-endian
    req_id  := request/response correlation: a response echoes the request's
               id. Responses are in-order per connection (the server is
               synchronous per connection), so the id is a tripwire, not a
               demultiplexer: a client that reads a response whose id is not
               the one it sent knows the stream is poisoned (e.g. it timed
               out mid-read earlier and a stale response is still in flight)
               and MUST tear the connection down — never resynchronize
               mid-stream.

Live cluster state (StateNodeViews) crosses the wire too, so a sidecar
solve of a NON-empty cluster — provisioning onto existing capacity,
consolidation simulation — matches the in-process result
(tests/test_service.py asserts equality).

Timeout/cancellation follows provisioner.go:366-374: the request carries
`timeout_seconds`; the server passes it into SchedulerOptions so a Solve
that overruns returns partial results with timed_out=True instead of
hanging the control plane. The CLIENT additionally enforces a hard
per-request deadline on the socket itself — a sidecar that stops
responding (hung solve, dead process, black-holed proxy) can never block
a control-plane call past its deadline (docs/resilience.md).

Fault envelope (tests/test_service_faults.py drives every branch):
- frames above MAX_FRAME_LEN are refused with an ERROR frame, then the
  connection closes (the stream past a refused header is untrusted);
- malformed payloads (bad JSON, bad schema) answer ERROR and keep serving;
- a bad magic closes only that connection — framing is lost, the stream
  cannot be resynchronized;
- the accept loop survives ANY exception escaping a connection handler
  (logged through karpenter_tpu.logging, never fatal);
- stop() drains: in-flight solves finish and flush their responses before
  the listener is torn down.
"""

from __future__ import annotations

import base64
import json
import os
import random
import socket
import struct
import threading
import time
from typing import Optional

import numpy as np

from karpenter_tpu import logging as klog
from karpenter_tpu import tracing
from karpenter_tpu.api import codec
from karpenter_tpu.solver.hybrid import solve_in_process
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.solver.oracle import SchedulerOptions
from karpenter_tpu.solver.topology import ClusterSource

MAGIC = b"KTPU"
HEADER_LEN = 16  # magic(4) + kind(4) + req_id(4) + len(4)
KIND_SOLVE = 1
KIND_RESULT = 2
KIND_ERROR = 3
KIND_PING = 4
KIND_PONG = 5

# Refuse frames above this size with an ERROR frame: a corrupted length
# field must not make either side try to buffer gigabytes. 64 MiB clears
# the largest measured problem payload by >100x (the pod payload is
# O(classes) JSON + O(pods) binary).
MAX_FRAME_LEN = 64 * 1024 * 1024

# A peer that starts a frame must finish it within this window; stalling
# mid-frame is a fault (truncating proxy, wedged client), not idleness.
FRAME_STALL_SECONDS = 30.0


class SolverUnavailable(ConnectionError):
    """The sidecar could not produce a response within the client's retry
    and deadline budget. The control plane treats this as 'degrade to the
    in-process solver', mirroring the reference's typed cloud-provider
    errors (provisioner.go:366-374)."""


class SolverError(RuntimeError):
    """The sidecar answered a clean ERROR frame: the solve itself failed
    server-side. Transport is healthy; retrying the same problem would
    fail the same way."""


class ProtocolError(ValueError):
    """The peer violated the framing discipline (bad magic, oversized
    frame, correlation-id mismatch). The connection is not recoverable.
    `req_id` is the offending frame's correlation id when the header was
    still readable (0 otherwise), so the server can address its final
    ERROR frame before closing."""

    def __init__(self, msg: str, req_id: int = 0):
        super().__init__(msg)
        self.req_id = req_id


def _send_frame(
    sock: socket.socket, kind: int, payload: bytes, req_id: int = 0
) -> None:
    sock.sendall(
        MAGIC + struct.pack("<III", kind, req_id & 0xFFFFFFFF, len(payload)) + payload
    )


def _recv_exact_deadline(sock: socket.socket, n: int, deadline: float) -> bytes:
    """_recv_exact under a hard wall-clock deadline: every recv() gets only
    the remaining budget, so trickling bytes cannot stretch the total past
    the deadline."""
    buf = b""
    while len(buf) < n:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise socket.timeout("deadline exceeded")
        sock.settimeout(remaining)
        got = sock.recv(n - len(buf))
        if not got:
            raise ConnectionError("peer closed")
        buf += got
    return buf


def _recv_frame_deadline(sock: socket.socket, deadline: float) -> tuple[int, int, bytes]:
    head = _recv_exact_deadline(sock, HEADER_LEN, deadline)
    if head[:4] != MAGIC:
        raise ProtocolError(f"bad magic {head[:4]!r}")
    kind, req_id, length = struct.unpack("<III", head[4:])
    if length > MAX_FRAME_LEN:
        raise ProtocolError(
            f"frame of {length} bytes exceeds max {MAX_FRAME_LEN}", req_id=req_id
        )
    return kind, req_id, _recv_exact_deadline(sock, length, deadline)


# ---------------------------------------------------------------------------
# problem wire form


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode()


def _unb64(s: str, dtype) -> np.ndarray:
    return np.frombuffer(base64.b64decode(s), dtype=dtype)


def _encode_pods_flat(pods) -> dict:
    """Class-deduplicated pod payload: one JSON spec per scheduling class
    plus flat per-pod identity columns (SURVEY §7 hard-part #5 — the wire
    cost is O(classes) JSON + O(pods) binary)."""
    from karpenter_tpu.solver.ordering import pod_encode_class

    classes: dict[tuple, int] = {}
    reps = []
    cls = np.zeros(len(pods), np.int32)
    for i, p in enumerate(pods):
        key = pod_encode_class(p, p.requests) + (
            tuple(sorted(p.metadata.labels.items())),
            tuple(sorted(p.metadata.annotations.items())),
            p.namespace,
        )
        c = classes.get(key)
        if c is None:
            c = len(reps)
            classes[key] = c
            reps.append(p)
        cls[i] = c
    return {
        "classes": codec.to_jsonable(reps),
        "cls": _b64(cls),
        "names": [p.name for p in pods],
        "uids": [p.uid for p in pods],
        "creation": _b64(
            np.asarray([p.metadata.creation_timestamp for p in pods], np.float64)
        ),
    }


def _decode_pods_flat(d: dict):
    reps = codec.from_jsonable(d["classes"])
    cls = _unb64(d["cls"], np.int32)
    creation = _unb64(d["creation"], np.float64)
    out = []
    for i in range(len(cls)):
        p = reps[int(cls[i])].deep_copy()
        p.metadata.name = d["names"][i]
        p.metadata.uid = d["uids"][i]
        p.metadata.creation_timestamp = float(creation[i])
        out.append(p)
    return out


def _encode_views(views) -> list[dict]:
    out = []
    for v in views or []:
        out.append(
            {
                "name": v.name,
                "node_labels": v.node_labels,
                "labels": dict(v.labels),
                "taints": codec.to_jsonable(list(v.taints)),
                "available": dict(v.available),
                "capacity": dict(v.capacity),
                "daemonset_requests": dict(v.daemonset_requests),
                "initialized": v.initialized,
                "hostname": v.hostname,
                "host_ports": {
                    uid: [list(p) for p in ports]
                    for uid, ports in v.host_port_usage._by_pod.items()
                },
                "volumes": {
                    uid: sorted([list(p) for p in s])
                    for uid, s in v.volume_usage._by_pod.items()
                },
                "csi_allocatable": dict(getattr(v, "csi_allocatable", {}) or {}),
            }
        )
    return out


def _decode_views(data) -> Optional[list[StateNodeView]]:
    if data is None:
        return None
    out = []
    for d in data:
        v = StateNodeView(
            name=d["name"],
            node_labels=d["node_labels"],
            labels=d["labels"],
            taints=codec.from_jsonable(d["taints"]),
            available={k: int(x) for k, x in d["available"].items()},
            capacity={k: int(x) for k, x in d["capacity"].items()},
            daemonset_requests={
                k: int(x) for k, x in d["daemonset_requests"].items()
            },
            initialized=d["initialized"],
            hostname=d["hostname"],
            csi_allocatable={
                k: int(v2) for k, v2 in d.get("csi_allocatable", {}).items()
            },
        )
        for uid, ports in d.get("host_ports", {}).items():
            v.host_port_usage._by_pod[uid] = [tuple(p) for p in ports]
        for uid, vols in d.get("volumes", {}).items():
            v.volume_usage._by_pod[uid] = {
                tuple(p) if isinstance(p, list) else ("", p) for p in vols
            }
        out.append(v)
    return out


def _encode_cluster(cluster) -> Optional[dict]:
    """The ClusterSource slice topology counting needs on the server side:
    scheduled pods by namespace (existing anti-affinity / spread-count
    state), node labels by name, and namespace labels. Without this a
    sidecar solve of a cluster with RUNNING pods would see an empty world
    and could co-locate against existing anti-affinity. O(bound pods)
    JSON — the flat-column optimization covers only the pending payload."""
    if cluster is None:
        return None
    return {
        "namespace_labels": dict(cluster.namespace_labels),
        "pods_by_namespace": {
            ns: codec.to_jsonable([p for p in pods if p.node_name])
            for ns, pods in cluster.pods_by_namespace.items()
        },
        "node_labels_by_name": {
            name: dict(node.metadata.labels)
            for name, node in cluster.nodes_by_name.items()
        },
    }


def _decode_cluster(req: dict) -> ClusterSource:
    from karpenter_tpu.api import objects as api

    cl = req.get("cluster")
    if not cl:
        return ClusterSource(namespace_labels=req.get("namespace_labels") or {})
    nodes_by_name = {
        name: api.Node(metadata=api.ObjectMeta(name=name, labels=dict(labels)))
        for name, labels in cl.get("node_labels_by_name", {}).items()
    }
    pods_by_ns = {
        ns: codec.from_jsonable(v)
        for ns, v in cl.get("pods_by_namespace", {}).items()
    }
    return ClusterSource(
        pods_by_ns, nodes_by_name, cl.get("namespace_labels") or {}
    )


def encode_problem_request(
    node_pools,
    instance_types_by_pool,
    pods,
    state_node_views=None,
    daemonset_pods=None,
    options: Optional[SchedulerOptions] = None,
    force_oracle: bool = False,
    namespace_labels: Optional[dict] = None,
    cluster=None,
) -> bytes:
    if namespace_labels is None and cluster is not None:
        namespace_labels = cluster.namespace_labels
    req = {
        "namespace_labels": namespace_labels or {},
        "cluster": _encode_cluster(cluster),
        "node_pools": codec.to_jsonable(node_pools),
        "instance_types_by_pool": {
            k: codec.to_jsonable(list(v)) for k, v in instance_types_by_pool.items()
        },
        "pods_flat": _encode_pods_flat(pods),
        "state_node_views": (
            _encode_views(state_node_views) if state_node_views is not None else None
        ),
        "daemonset_pods": codec.to_jsonable(daemonset_pods or []),
        # EVERY SchedulerOptions field crosses the wire: a sidecar solving
        # with defaults while the control plane configured otherwise is a
        # silent decision divergence (feature gates, routing thresholds)
        "options": {
            "ignore_preferences": bool(options and options.ignore_preferences),
            "min_values_best_effort": bool(options and options.min_values_best_effort),
            "reserved_capacity_enabled": bool(
                options and options.reserved_capacity_enabled
            ),
            "reserved_offering_strict": bool(
                options and options.reserved_offering_strict
            ),
            "timeout_seconds": options.timeout_seconds if options else None,
            "claim_slot_div": options.claim_slot_div if options else None,
            "tpu_min_pods": options.tpu_min_pods if options else None,
        },
        "force_oracle": force_oracle,
    }
    return json.dumps(req).encode()


def _decode_problem_request(payload: bytes):
    req = json.loads(payload)
    node_pools = codec.from_jsonable(req["node_pools"])
    its_by_pool = {
        k: codec.from_jsonable(v) for k, v in req["instance_types_by_pool"].items()
    }
    pods = _decode_pods_flat(req["pods_flat"])
    views = _decode_views(req.get("state_node_views"))
    source = _decode_cluster(req)
    daemons = codec.from_jsonable(req.get("daemonset_pods") or [])
    o = req.get("options") or {}
    defaults = SchedulerOptions()
    options = SchedulerOptions(
        ignore_preferences=o.get("ignore_preferences", False),
        min_values_best_effort=o.get("min_values_best_effort", False),
        reserved_capacity_enabled=o.get("reserved_capacity_enabled", False),
        reserved_offering_strict=o.get("reserved_offering_strict", False),
        timeout_seconds=o.get("timeout_seconds"),
        claim_slot_div=(
            o["claim_slot_div"]
            if o.get("claim_slot_div") is not None
            else defaults.claim_slot_div
        ),
        tpu_min_pods=(
            o["tpu_min_pods"]
            if o.get("tpu_min_pods") is not None
            else defaults.tpu_min_pods
        ),
    )
    return (
        node_pools,
        its_by_pool,
        pods,
        views,
        daemons,
        options,
        req.get("force_oracle", False),
        source,
    )


def _encode_result(results, used_tpu: bool, pods) -> bytes:
    """Flat assignment arrays: pod i (request order) -> claim index, or
    ~existing-node index; -1 = error/unscheduled."""
    claim_of = {}
    for ci, c in enumerate(results.new_node_claims):
        for p in c.pods:
            claim_of[p.uid] = ci
    enode_names = [n.name for n in results.existing_nodes]
    enode_of = {}
    for ei, n in enumerate(results.existing_nodes):
        for p in n.pods:
            enode_of[p.uid] = ei
    assign = np.full(len(pods), -1, np.int32)
    for i, p in enumerate(pods):
        if p.uid in claim_of:
            assign[i] = claim_of[p.uid]
        elif p.uid in enode_of:
            assign[i] = -2 - enode_of[p.uid]  # -2 -> node 0, -3 -> node 1, ...
    claims = [
        {
            "nodepool": c.nodepool_name,
            "instance_types": [it.name for it in c.instance_type_options],
            "requests": dict(c.requests),
            # the launchable form: requirements, taints, labels — everything
            # the control plane's CreateNodeClaims needs, so a REMOTE solve
            # is actionable without re-deriving template state client-side
            # (solver/hybrid.py ResilientSolver._to_results)
            "node_claim": codec.to_jsonable(c.to_node_claim()),
        }
        for c in results.new_node_claims
    ]
    out = {
        "used_tpu": used_tpu,
        "timed_out": results.timed_out,
        "pod_errors": dict(results.pod_errors),
        "new_node_claims": claims,
        "existing_node_names": enode_names,
        "assign": _b64(assign),
    }
    return json.dumps(out).encode()


def decode_result(resp: dict, pods) -> dict:
    """Expand the flat assignment array back into per-pod maps."""
    assign = _unb64(resp["assign"], np.int32)
    claims = [dict(c, pod_uids=[]) for c in resp["new_node_claims"]]
    for c in claims:
        if c.get("node_claim") is not None:
            c["node_claim"] = codec.from_jsonable(c["node_claim"])
    existing = {}
    for i, p in enumerate(pods):
        a = int(assign[i])
        if a >= 0:
            claims[a]["pod_uids"].append(p.uid)
        elif a <= -2:
            existing[p.uid] = resp["existing_node_names"][-2 - a]
    return {
        "used_tpu": resp["used_tpu"],
        "timed_out": resp["timed_out"],
        "pod_errors": resp["pod_errors"],
        "new_node_claims": claims,
        "existing_assignments": existing,
    }


# ---------------------------------------------------------------------------
# server


class SolverServer:
    """Serves SOLVE frames, one handler thread per connection (the control
    plane is a singleton provisioner, but a drained-and-replaced control
    plane briefly overlaps its successor — two live connections must both
    be served, not queued behind each other).

    Robustness contract (ISSUE: no solver-side fault may wedge the accept
    loop): solve failures answer ERROR on the same correlation id; framing
    violations close only the offending connection; anything unexpected is
    logged and the loop keeps serving. stop() drains gracefully — the
    listener closes first, in-flight handlers get `drain_seconds` to flush
    their responses.

    Concurrency contract (graftlint race tier): the two locks here are
    leaves — nothing blocking runs under either (_conns_lock guards set
    membership only; stop() snapshots the set under the lock and joins
    OUTSIDE it), and neither nests inside the other, so the server
    contributes no edges to the program's lock acquisition graph. The
    fault suite runs with racert-instrumented locks to witness exactly
    that under real handler-thread interleavings.

    Prewarm/readiness (docs/compile.md): with prewarm=True, start() kicks
    a background thread that AOT-compiles the bucket ladder into the
    persistent cache (solver/aot.py) BEFORE the server reports ready.
    SOLVE requests that arrive mid-prewarm are served immediately but
    degrade to the oracle fallback (force_oracle) — decision-identical,
    never an uncompiled device path — and PONG payloads say "prewarming"
    so orchestration readiness probes can gate traffic. The prewarm
    thread polls the server's stop flag between combos, and every
    on-disk artifact write is atomic, so a kill mid-prewarm can never
    poison the cache (tests/test_service_faults.py)."""

    def __init__(
        self,
        socket_path: str,
        drain_seconds: float = 30.0,
        prewarm: bool = False,
        prewarm_fn=None,
    ):
        self.socket_path = socket_path
        self.drain_seconds = drain_seconds
        self.prewarm = prewarm
        self._prewarm_fn = prewarm_fn
        self._prewarm_thread: Optional[threading.Thread] = None
        self._prewarm_stop: Optional[threading.Event] = None
        self._prewarm_gen = 0
        self.ready = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._conns: set[threading.Thread] = set()
        self._conns_lock = threading.Lock()
        # handler threads (one per connection) all bump the solve counter;
        # the read-modify-write needs its own lock or increments are lost
        self._stats_lock = threading.Lock()
        self.solves = 0
        self.oracle_degraded_solves = 0
        self.log = klog.root.named("solver.service")

    def start(self) -> None:
        # service startup is one of the two sanctioned call sites of the
        # persistent-cache config (the other is the solver package import)
        from karpenter_tpu.jaxsetup import ensure_compilation_cache

        ensure_compilation_cache()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._stop.clear()
        # readiness transitions BEFORE the accept loop exists: a request
        # racing start() must never observe a ready=False non-prewarming
        # server (it would spuriously degrade to the oracle). The gen
        # bump rides the stats lock so the abandoned-prewarm-thread read
        # in _run_prewarm's finally can never see a torn increment.
        with self._stats_lock:
            self._prewarm_gen += 1
        if self.prewarm:
            self.ready.clear()
        else:
            self.ready.set()
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.socket_path)
        self._sock.listen(8)
        self._sock.settimeout(0.2)
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        if self.prewarm:
            # each prewarm thread gets its OWN stop event: start() after
            # stop() clears the server-wide _stop, which must not revive
            # an abandoned thread — its private event stays set, so it
            # exits at the next combo boundary even across restarts
            self._prewarm_stop = threading.Event()
            self._prewarm_thread = threading.Thread(
                target=self._run_prewarm,
                args=(self._prewarm_gen, self._prewarm_stop),
                daemon=True,
            )
            self._prewarm_thread.start()

    def _run_prewarm(self, gen: int, stop: threading.Event) -> None:
        """Compile the bucket ladder, then report ready. A prewarm failure
        is logged and the server reports ready anyway (degraded: first
        solves pay their compiles) — a broken cache must not brick the
        sidecar. `gen` guards the ready transition: a thread abandoned by
        stop() (the join is bounded; a combo compiles for ~15s) must not
        flip readiness during a LATER start()'s prewarm."""
        try:
            if self._prewarm_fn is not None:
                self._prewarm_fn(stop)
            else:
                from karpenter_tpu.solver import aot

                out = aot.prewarm(stop=stop)
                self.log.info(
                    "prewarm complete",
                    compiled=out["compiled"],
                    skipped=out["skipped"],
                    seconds=round(out["seconds"], 1),
                )
        except Exception as e:
            self.log.error(
                "prewarm failed; serving without it",
                error=f"{type(e).__name__}: {e}",
            )
        finally:
            with self._stats_lock:
                current = gen == self._prewarm_gen
            if current:
                self.ready.set()

    def stop(self) -> None:
        """Graceful drain: stop accepting, let in-flight handlers finish
        (bounded by drain_seconds), then tear the socket down."""
        self._stop.set()
        if self._prewarm_thread is not None:
            # a combo compiles for ~15s and .compile() is uninterruptible,
            # so the bounded join deliberately abandons the daemon thread
            # rather than block shutdown; its private stop event (set
            # here, never cleared) makes it exit at the next combo
            # boundary, and the gen guard keeps its final ready-set inert
            if self._prewarm_stop is not None:
                self._prewarm_stop.set()
            self._prewarm_thread.join(timeout=1.0)
            self._prewarm_thread = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        deadline = time.monotonic() + self.drain_seconds
        with self._conns_lock:
            pending = list(self._conns)
        for t in pending:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._run_conn, args=(conn,), daemon=True)
            with self._conns_lock:
                self._conns.add(t)
            t.start()

    def _run_conn(self, conn: socket.socket) -> None:
        try:
            self._handle(conn)
        except socket.timeout:
            # a response send stalled past FRAME_STALL_SECONDS: the peer
            # stopped reading — drop the connection, keep serving
            self.log.warn("peer stopped reading mid-response, closing connection")
        except ConnectionError:
            pass  # peer went away; normal churn
        except ProtocolError as e:
            # framing is lost — the stream cannot be resynchronized; answer
            # once (best effort, the header's req_id if it was readable)
            # and close only this connection
            self.log.warn("protocol violation, closing connection", error=str(e))
            try:
                _send_frame(conn, KIND_ERROR, str(e).encode(), req_id=e.req_id)
            except OSError:
                pass
        except Exception as e:  # the accept loop must survive ANYTHING
            self.log.error(
                "unexpected error in connection handler",
                error=f"{type(e).__name__}: {e}",
            )
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.discard(threading.current_thread())

    def _recv_frame_idle(self, conn: socket.socket) -> tuple[int, int, bytes]:
        """Receive one frame, polling the stop flag only BETWEEN frames:
        the idle wait covers the first byte alone, so a poll timeout can
        never discard a partially-read header and desync the stream. Once
        a frame starts, the peer gets FRAME_STALL_SECONDS of WALL CLOCK to
        finish it (same _recv_exact_deadline discipline as the client — a
        peer trickling one byte per poll interval must not hold the
        handler thread forever); a mid-frame stall is a fault, not
        idleness."""
        while True:
            if self._stop.is_set():
                raise ConnectionError("server stopping")
            conn.settimeout(0.2)
            try:
                first = conn.recv(1)
                break
            except socket.timeout:
                continue
        if not first:
            raise ConnectionError("peer closed")
        deadline = time.monotonic() + FRAME_STALL_SECONDS
        head = first + _recv_exact_deadline(conn, HEADER_LEN - 1, deadline)
        if head[:4] != MAGIC:
            raise ProtocolError(f"bad magic {head[:4]!r}")
        kind, req_id, length = struct.unpack("<III", head[4:])
        if length > MAX_FRAME_LEN:
            raise ProtocolError(
                f"frame of {length} bytes exceeds max {MAX_FRAME_LEN}", req_id=req_id
            )
        return kind, req_id, _recv_exact_deadline(conn, length, deadline)

    def _send_response(self, conn: socket.socket, kind: int, payload: bytes, req_id: int) -> None:
        """A peer that stops READING must not wedge the handler either:
        sendall under a socket timeout enforces a total wall-clock bound
        across its internal retries (CPython tracks a deadline)."""
        conn.settimeout(FRAME_STALL_SECONDS)
        _send_frame(conn, kind, payload, req_id=req_id)

    def _handle(self, conn: socket.socket) -> None:
        while not self._stop.is_set():
            try:
                kind, req_id, payload = self._recv_frame_idle(conn)
            except socket.timeout as e:
                raise ProtocolError(f"peer stalled mid-frame: {e}") from e
            if kind == KIND_PING:
                payload = b"ready" if self.ready.is_set() else b"prewarming"
                self._send_response(conn, KIND_PONG, payload, req_id)
                continue
            if kind != KIND_SOLVE:
                self._send_response(
                    conn, KIND_ERROR, f"unknown kind {kind}".encode(), req_id
                )
                continue
            try:
                result = self._solve(payload, req_id)
            except Exception as e:  # error frames, never a dead socket
                self.log.warn("solve failed, answering ERROR", error=str(e))
                self._send_response(
                    conn, KIND_ERROR, f"{type(e).__name__}: {e}".encode(), req_id
                )
                continue
            self._send_response(conn, KIND_RESULT, result, req_id)

    def _solve(self, payload: bytes, req_id: int = 0) -> bytes:
        # the server-side half of the solve trace: same wire correlation
        # id as the client's trace, so /debug/solves/<id> shows both —
        # client wire spans and server decode/solve/encode phases — as
        # one logical trace (tracing module docstring)
        tr = tracing.new_trace("solve", side="server")
        if req_id:
            tr.set_wire_id(req_id)
        try:
            result = self._solve_traced(payload, tr)
        except BaseException:
            tr.finish("error")
            raise
        tr.finish("ok")
        return result

    def _solve_traced(self, payload: bytes, tr) -> bytes:
        with tr.span("wire_decode_request", bytes=len(payload)):
            (
                node_pools,
                its_by_pool,
                pods,
                views,
                daemons,
                options,
                force_oracle,
                source,
            ) = _decode_problem_request(payload)
        # mid-prewarm requests degrade to the (decision-identical) oracle:
        # the device path may still be compiling, and a solve must never
        # pay the compile wall nor race the prewarm for the jit caches
        degraded = not self.ready.is_set()
        if degraded:
            force_oracle = True
            tracing.record_fallback(
                tr, "prewarm_degraded",
                "mid-prewarm solve served by the oracle fallback",
            )
        results, scheduler = solve_in_process(
            node_pools,
            its_by_pool,
            pods,
            views,
            daemons,
            options,
            cluster=source,
            force_oracle=force_oracle,
            trace=tr,
        )
        with self._stats_lock:
            self.solves += 1
            if degraded:
                self.oracle_degraded_solves += 1
        with tr.span("wire_encode_result"):
            out = _encode_result(results, bool(scheduler.used_tpu), pods)
        tr.annotate(pods=len(pods), used_tpu=bool(scheduler.used_tpu))
        return out


# ---------------------------------------------------------------------------
# client


class SolverClient:
    """The control plane's side of the boundary, hardened per the failure
    ladder (docs/resilience.md):

    - requests carry a fresh correlation id; a response bearing any other
      id means the stream is poisoned — tear down, never resynchronize;
    - every call runs under a hard deadline (`request_timeout` default):
      connect, send, and every recv share one wall-clock budget, so a hung
      sidecar can never block the control plane past its deadline;
    - a timeout mid-read poisons the connection (the late response may
      still arrive) — the socket is closed, the next call reconnects;
    - transport failures (refused/reset/closed) reconnect with exponential
      backoff + jitter up to `max_retries`, inside the same deadline. A
      SOLVE is stateless server-side, so retrying a possibly-executed
      request is safe.

    Exhausting the budget raises SolverUnavailable; a clean server-side
    ERROR frame raises SolverError. Callers (ResilientSolver) treat both
    as 'degrade down the ladder'."""

    def __init__(
        self,
        socket_path: str,
        connect_timeout: float = 5.0,
        request_timeout: float = 30.0,
        max_retries: int = 2,
        backoff_base: float = 0.05,
        backoff_cap: float = 2.0,
        rng: Optional[random.Random] = None,
        sleep=time.sleep,
    ):
        self.socket_path = socket_path
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._rng = rng or random.Random()
        self._sleep = sleep
        self._sock: Optional[socket.socket] = None
        # correlation ids start at a RANDOM point in the u32 space: the id
        # is a per-connection tripwire (the server just echoes it), but it
        # doubles as the trace id on both sides — two clients (or one
        # restarted control plane) both counting 1, 2, 3... would collide
        # in the sidecar's trace ring and /debug/solves/<id> would merge
        # unrelated solves into one "joined" trace
        self._next_id = self._rng.randrange(0, 0xFFFFFFFF)
        # observability for the breaker layer / tests
        self.reconnects = 0
        self.poisoned = 0
        # correlation id of the most recent frame sent: solve() stamps it
        # onto the caller's trace so client and sidecar spans join
        self.last_req_id = 0

    # -- connection management --------------------------------------------

    def connect(self, timeout: Optional[float] = None) -> None:
        self.close()
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout if timeout is not None else self.connect_timeout)
        sock.connect(self.socket_path)
        self._sock = sock

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def _poison(self) -> None:
        """Drop a connection whose stream state is no longer trustworthy
        (partial read, stale in-flight response, framing violation)."""
        self.poisoned += 1
        self.close()

    def _ensure_connected(self, deadline: float) -> None:
        if self._sock is not None:
            return
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise socket.timeout("deadline exceeded before connect")
        self.connect(timeout=min(self.connect_timeout, remaining))
        self.reconnects += 1

    def _backoff(self, attempt: int, deadline: float) -> None:
        """Exponential backoff with full jitter, clamped to the remaining
        deadline budget (AWS-style decorrelated retries would also do; full
        jitter is the simplest schedule that avoids thundering herds)."""
        delay = min(self.backoff_cap, self.backoff_base * (2**attempt))
        delay = self._rng.uniform(0, delay)
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise socket.timeout("deadline exceeded during backoff")
        self._sleep(min(delay, remaining))

    # -- request/response --------------------------------------------------

    def _roundtrip(
        self, kind: int, payload: bytes, timeout: Optional[float]
    ) -> tuple[int, bytes]:
        """One correlated request/response under a hard deadline, with
        bounded reconnect-and-retry on transport failure."""
        budget = self.request_timeout if timeout is None else timeout
        deadline = time.monotonic() + budget
        attempt = 0
        while True:
            try:
                self._ensure_connected(deadline)
                self._next_id = (self._next_id % 0xFFFFFFFF) + 1
                req_id = self._next_id
                self.last_req_id = req_id
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise socket.timeout("deadline exceeded before send")
                self._sock.settimeout(remaining)
                _send_frame(self._sock, kind, payload, req_id=req_id)
                try:
                    rkind, rid, resp = _recv_frame_deadline(self._sock, deadline)
                except ProtocolError:
                    self._poison()  # framing lost (corrupted stream)
                    raise
                if rid != req_id:
                    self._poison()
                    raise ProtocolError(
                        f"correlation mismatch: sent {req_id}, got {rid} — "
                        "stream poisoned, tearing down"
                    )
                return rkind, resp
            except socket.timeout as e:
                # a partial read after timeout leaves the response in
                # flight: poison, never resynchronize mid-stream
                self._poison()
                raise SolverUnavailable(
                    f"no response within {budget:.3f}s deadline: {e}"
                ) from e
            except (ConnectionError, OSError) as e:
                if isinstance(e, (SolverUnavailable,)):
                    raise
                self._poison()
                attempt += 1
                if attempt > self.max_retries:
                    raise SolverUnavailable(
                        f"sidecar unreachable after {attempt} attempts: {e}"
                    ) from e
                try:
                    self._backoff(attempt - 1, deadline)
                except socket.timeout:
                    raise SolverUnavailable(
                        f"deadline exhausted retrying: {e}"
                    ) from e

    def ping(self, timeout: Optional[float] = None) -> bool:
        kind, _ = self._roundtrip(KIND_PING, b"", timeout)
        return kind == KIND_PONG

    def solve(
        self,
        node_pools,
        instance_types_by_pool,
        pods,
        state_node_views=None,
        daemonset_pods=None,
        options: Optional[SchedulerOptions] = None,
        force_oracle: bool = False,
        namespace_labels: Optional[dict] = None,
        timeout: Optional[float] = None,
        cluster=None,
        trace=None,
    ) -> dict:
        """`trace` (tracing.Trace, optional): wire-phase spans land on it
        and the SOLVE frame's correlation id becomes the trace id, joining
        this client-side trace with the sidecar's server-side one."""
        with tracing.span_of(trace, "wire_encode", pods=len(pods)):
            payload = encode_problem_request(
                node_pools,
                instance_types_by_pool,
                pods,
                state_node_views,
                daemonset_pods,
                options,
                force_oracle,
                namespace_labels,
                cluster,
            )
        with tracing.span_of(trace, "wire_roundtrip", bytes=len(payload)):
            kind, resp = self._roundtrip(KIND_SOLVE, payload, timeout)
        if trace is not None:
            # the correlation id of the attempt that ANSWERED (retries
            # re-id; last_req_id tracks the final frame on the wire)
            trace.set_wire_id(self.last_req_id)
        if kind == KIND_ERROR:
            raise SolverError(resp.decode())
        with tracing.span_of(trace, "wire_decode", bytes=len(resp)):
            return decode_result(json.loads(resp), pods)
