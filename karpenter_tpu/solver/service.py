"""The Solver service boundary: a sidecar process serving Solve over a
unix-domain socket (SURVEY.md §7 M5; the reference's north star is a
Go control plane reaching a TPU solver through cgo->gRPC — this is that
boundary with the same framing discipline, minus the Go toolchain).

Wire protocol (language-neutral; the C++ client in native/solver_client.cc
speaks it too):

    frame   := magic "KTPU" | u32 kind | u32 len | payload[len]
    kind    := 1 SOLVE request   (payload = problem JSON, api/codec.py)
               2 RESULT response (payload = result JSON + flat assignment
                                  arrays base64'd in-header for small
                                  problems; see _encode_result)
               3 ERROR response  (payload = utf-8 message)
               4 PING / 5 PONG   (health)
    u32     := little-endian

Timeout/cancellation follows provisioner.go:366-374: the request carries
`timeout_seconds`; the server passes it into SchedulerOptions so a Solve
that overruns returns partial results with timed_out=True instead of
hanging the control plane.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from typing import Optional

from karpenter_tpu.api import codec
from karpenter_tpu.solver.hybrid import HybridScheduler
from karpenter_tpu.solver.oracle import SchedulerOptions
from karpenter_tpu.solver.topology import Topology

MAGIC = b"KTPU"
KIND_SOLVE = 1
KIND_RESULT = 2
KIND_ERROR = 3
KIND_PING = 4
KIND_PONG = 5


def _send_frame(sock: socket.socket, kind: int, payload: bytes) -> None:
    sock.sendall(MAGIC + struct.pack("<II", kind, len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        if not got:
            raise ConnectionError("peer closed")
        buf += got
    return buf


def _recv_frame(sock: socket.socket) -> tuple[int, bytes]:
    head = _recv_exact(sock, 12)
    if head[:4] != MAGIC:
        raise ValueError(f"bad magic {head[:4]!r}")
    kind, length = struct.unpack("<II", head[4:])
    return kind, _recv_exact(sock, length)


# ---------------------------------------------------------------------------
# problem wire form


def encode_problem_request(
    node_pools,
    instance_types_by_pool,
    pods,
    state_node_views=None,
    daemonset_pods=None,
    options: Optional[SchedulerOptions] = None,
    force_oracle: bool = False,
) -> bytes:
    req = {
        "node_pools": codec.to_jsonable(node_pools),
        "instance_types_by_pool": {
            k: codec.to_jsonable(list(v)) for k, v in instance_types_by_pool.items()
        },
        "pods": codec.to_jsonable(pods),
        "state_node_views": None,  # views carry live handles; service solves fresh
        "daemonset_pods": codec.to_jsonable(daemonset_pods or []),
        "options": {
            "ignore_preferences": bool(options and options.ignore_preferences),
            "min_values_best_effort": bool(options and options.min_values_best_effort),
            "timeout_seconds": options.timeout_seconds if options else None,
        },
        "force_oracle": force_oracle,
    }
    return json.dumps(req).encode()


def _decode_problem_request(payload: bytes):
    req = json.loads(payload)
    node_pools = codec.from_jsonable(req["node_pools"])
    its_by_pool = {
        k: codec.from_jsonable(v) for k, v in req["instance_types_by_pool"].items()
    }
    pods = codec.from_jsonable(req["pods"])
    daemons = codec.from_jsonable(req.get("daemonset_pods") or [])
    o = req.get("options") or {}
    options = SchedulerOptions(
        ignore_preferences=o.get("ignore_preferences", False),
        min_values_best_effort=o.get("min_values_best_effort", False),
        timeout_seconds=o.get("timeout_seconds"),
    )
    return node_pools, its_by_pool, pods, daemons, options, req.get("force_oracle", False)


def _encode_result(results, used_tpu: bool) -> bytes:
    claims = []
    for c in results.new_node_claims:
        claims.append(
            {
                "nodepool": c.nodepool_name,
                "pod_uids": [p.uid for p in c.pods],
                "instance_types": [it.name for it in c.instance_type_options],
                "requests": dict(c.requests),
            }
        )
    out = {
        "used_tpu": used_tpu,
        "timed_out": results.timed_out,
        "pod_errors": dict(results.pod_errors),
        "new_node_claims": claims,
        "existing_assignments": {
            p.uid: n.name for n in results.existing_nodes for p in n.pods
        },
    }
    return json.dumps(out).encode()


# ---------------------------------------------------------------------------
# server


class SolverServer:
    """Serves SOLVE frames; one connection at a time (the control plane is a
    singleton provisioner — matching the reference's concurrency model)."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.solves = 0

    def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.socket_path)
        self._sock.listen(4)
        self._sock.settimeout(0.2)
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._sock is not None:
            self._sock.close()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                self._handle(conn)
            except (ConnectionError, ValueError):
                pass
            finally:
                conn.close()

    def _handle(self, conn: socket.socket) -> None:
        while not self._stop.is_set():
            kind, payload = _recv_frame(conn)
            if kind == KIND_PING:
                _send_frame(conn, KIND_PONG, b"")
                continue
            if kind != KIND_SOLVE:
                _send_frame(conn, KIND_ERROR, f"unknown kind {kind}".encode())
                continue
            try:
                _send_frame(conn, KIND_RESULT, self._solve(payload))
            except Exception as e:  # error frames, never a dead socket
                _send_frame(conn, KIND_ERROR, str(e).encode())

    def _solve(self, payload: bytes) -> bytes:
        (
            node_pools,
            its_by_pool,
            pods,
            daemons,
            options,
            force_oracle,
        ) = _decode_problem_request(payload)
        topology = Topology(node_pools, its_by_pool, pods)
        scheduler = HybridScheduler(
            node_pools,
            its_by_pool,
            topology,
            None,
            daemons,
            options,
            force_oracle=force_oracle,
        )
        results = scheduler.solve(pods)
        self.solves += 1
        return _encode_result(results, bool(scheduler.used_tpu))


# ---------------------------------------------------------------------------
# client


class SolverClient:
    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._sock: Optional[socket.socket] = None

    def connect(self, timeout: float = 5.0) -> None:
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(self.socket_path)

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def ping(self) -> bool:
        _send_frame(self._sock, KIND_PING, b"")
        kind, _ = _recv_frame(self._sock)
        return kind == KIND_PONG

    def solve(
        self,
        node_pools,
        instance_types_by_pool,
        pods,
        daemonset_pods=None,
        options: Optional[SchedulerOptions] = None,
        force_oracle: bool = False,
    ) -> dict:
        payload = encode_problem_request(
            node_pools,
            instance_types_by_pool,
            pods,
            None,
            daemonset_pods,
            options,
            force_oracle,
        )
        _send_frame(self._sock, KIND_SOLVE, payload)
        kind, resp = _recv_frame(self._sock)
        if kind == KIND_ERROR:
            raise RuntimeError(resp.decode())
        return json.loads(resp)
