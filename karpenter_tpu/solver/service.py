"""The Solver service boundary: a sidecar process serving Solve over a
unix-domain socket (SURVEY.md §7 M5; the reference's north star is a
Go control plane reaching a TPU solver through cgo->gRPC — this is that
boundary with the same framing discipline, minus the Go toolchain).

Wire protocol v2 (language-neutral; the C++ client in native/solver_client.cc
speaks it too):

    frame   := magic "KTPU" | u32 kind | u32 req_id | u32 len | payload[len]
    kind    := 1 SOLVE request   (payload = problem JSON; pods ride as
                                  per-CLASS specs + flat base64 columns,
                                  SURVEY §7 hard-part #5 — the per-pod
                                  payload is O(classes) JSON + O(pods)
                                  binary, not O(pods) JSON; an optional
                                  "epoch" {client, id} key asks the
                                  server to retain the cluster sections
                                  as an epoch — absent it, the frame is
                                  byte-for-byte the stateless protocol)
               2 RESULT response (payload = JSON header + flat base64
                                  assignment arrays: pod i -> claim index /
                                  existing-node index)
               3 ERROR response  (payload = utf-8 message)
               4 PING / 5 PONG   (health; an empty-payload PING keeps
                                  the legacy bare-token PONG — "ready"/
                                  "prewarming", plus "draining" during
                                  stop() — while PING payload "v2"
                                  answers JSON {status,
                                  admission_queue_depth, epoch_clients,
                                  epochs})
               6 SOLVE_DELTA     (payload = JSON {client, base_epoch,
                                  epoch, delta, pods_flat, options,
                                  force_oracle}: cluster CHANGES against
                                  a server-held epoch + the pending-pod
                                  batch — steady-state wire cost is
                                  O(churn + pending pods), not O(cluster))
               7 EPOCH_RESYNC    (retriable response: the base epoch is
                                  unknown/evicted or the delta failed to
                                  decode/apply; the client falls back to
                                  the full-snapshot SOLVE, which is
                                  always correct from scratch)
               8 RETRY           (admission rejected: payload JSON
                                  {retry_after_seconds, queue_depth};
                                  the caller degrades in-process and
                                  honors the hint before re-dialing)
    u32     := little-endian
    req_id  := request/response correlation: a response echoes the request's
               id. Responses are in-order per connection (the server is
               synchronous per connection), so the id is a tripwire, not a
               demultiplexer: a client that reads a response whose id is not
               the one it sent knows the stream is poisoned (e.g. it timed
               out mid-read earlier and a stale response is still in flight)
               and MUST tear the connection down — never resynchronize
               mid-stream.

Live cluster state (StateNodeViews) crosses the wire too, so a sidecar
solve of a NON-empty cluster — provisioning onto existing capacity,
consolidation simulation — matches the in-process result
(tests/test_service.py asserts equality).

Timeout/cancellation follows provisioner.go:366-374: the request carries
`timeout_seconds`; the server passes it into SchedulerOptions so a Solve
that overruns returns partial results with timed_out=True instead of
hanging the control plane. The CLIENT additionally enforces a hard
per-request deadline on the socket itself — a sidecar that stops
responding (hung solve, dead process, black-holed proxy) can never block
a control-plane call past its deadline (docs/resilience.md).

Fault envelope (tests/test_service_faults.py drives every branch):
- frames above MAX_FRAME_LEN are refused with an ERROR frame; up to
  OVERSIZE_DRAIN_MAX the body is drained (discarded under the stall
  deadline, never buffered) so the stream stays in sync and the
  connection KEEPS SERVING — an oversized delta costs one refusal, not
  the stream; beyond the drain cap the length field is corruption and
  the connection closes;
- malformed payloads (bad JSON, bad schema) answer ERROR and keep serving;
- a bad magic closes only that connection — framing is lost, the stream
  cannot be resynchronized;
- epoch faults (unknown/evicted base epoch, malformed or inapplicable
  delta, a materialized request that no longer decodes) answer a
  retriable EPOCH_RESYNC — the client's full-snapshot fallback is
  always correct from scratch, so no epoch fault can corrupt state;
- admission rejections answer RETRY with a backoff hint — the server
  never queues past its solve budget (solver/epochs.py AdmissionGate);
- the accept loop survives ANY exception escaping a connection handler
  (logged through karpenter_tpu.logging, never fatal);
- stop() drains: in-flight solves finish and flush their responses before
  the listener is torn down, and NEW solve frames arriving on surviving
  connections during the drain window get an immediate retriable
  "draining" ERROR instead of a silent close.
"""

from __future__ import annotations

import base64
import json
import os
import random
import socket
import struct
import threading
import time
from typing import Optional

import numpy as np

from karpenter_tpu import logging as klog
from karpenter_tpu import tracing
from karpenter_tpu.analysis import protorec
from karpenter_tpu.api import codec
from karpenter_tpu.solver import epochs
from karpenter_tpu.solver.hybrid import solve_in_process
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.solver.oracle import SchedulerOptions
from karpenter_tpu.solver.topology import ClusterSource

MAGIC = b"KTPU"
HEADER_LEN = 16  # magic(4) + kind(4) + req_id(4) + len(4)
KIND_SOLVE = 1
KIND_RESULT = 2
KIND_ERROR = 3
KIND_PING = 4
KIND_PONG = 5
KIND_SOLVE_DELTA = 6
KIND_EPOCH_RESYNC = 7
KIND_RETRY = 8

# Refuse frames above this size with an ERROR frame: a corrupted length
# field must not make either side try to buffer gigabytes. 64 MiB clears
# the largest measured problem payload by >100x (the pod payload is
# O(classes) JSON + O(pods) binary).
MAX_FRAME_LEN = 64 * 1024 * 1024

# A peer that starts a frame must finish it within this window; stalling
# mid-frame is a fault (truncating proxy, wedged client), not idleness.
FRAME_STALL_SECONDS = 30.0

# A frame above MAX_FRAME_LEN but at or below this cap is DRAINED (read
# and discarded under the frame-stall wall clock) so the stream stays in
# sync and the connection keeps serving after the ERROR answer — an
# oversized delta must not cost the client its connection. Beyond the
# cap (a corrupted length field, not a real payload) the connection
# closes as before: draining gigabytes on a liar's say-so is itself a
# denial of service.
OVERSIZE_DRAIN_MAX = 4 * MAX_FRAME_LEN


class SolverUnavailable(ConnectionError):
    """The sidecar could not produce a response within the client's retry
    and deadline budget. The control plane treats this as 'degrade to the
    in-process solver', mirroring the reference's typed cloud-provider
    errors (provisioner.go:366-374)."""


class SolverError(RuntimeError):
    """The sidecar answered a clean ERROR frame: the solve itself failed
    server-side. Transport is healthy; retrying the same problem would
    fail the same way."""


# the admission-rejection exception lives in epochs.py (hybrid.py catches
# it and cannot import this module — service imports hybrid); re-exported
# here as part of the client's public error surface
SolverOverloaded = epochs.SolverOverloaded


class _OversizedFrame(Exception):
    """Internal: an oversized frame was fully drained — the stream is
    still in sync, so the handler answers ERROR and keeps the connection
    (unlike ProtocolError, which closes it)."""

    def __init__(self, req_id: int, length: int):
        super().__init__(f"frame of {length} bytes exceeds max {MAX_FRAME_LEN}")
        self.req_id = req_id
        self.length = length


class ProtocolError(ValueError):
    """The peer violated the framing discipline (bad magic, oversized
    frame, correlation-id mismatch). The connection is not recoverable.
    `req_id` is the offending frame's correlation id when the header was
    still readable (0 otherwise), so the server can address its final
    ERROR frame before closing."""

    def __init__(self, msg: str, req_id: int = 0):
        super().__init__(msg)
        self.req_id = req_id


def _send_frame(
    sock: socket.socket, kind: int, payload: bytes, req_id: int = 0
) -> None:
    sock.sendall(
        MAGIC + struct.pack("<III", kind, req_id & 0xFFFFFFFF, len(payload)) + payload
    )


def _recv_exact_deadline(sock: socket.socket, n: int, deadline: float) -> bytes:
    """_recv_exact under a hard wall-clock deadline: every recv() gets only
    the remaining budget, so trickling bytes cannot stretch the total past
    the deadline."""
    buf = b""
    while len(buf) < n:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise socket.timeout("deadline exceeded")
        sock.settimeout(remaining)
        got = sock.recv(n - len(buf))
        if not got:
            raise ConnectionError("peer closed")
        buf += got
    return buf


def _discard_exact_deadline(sock: socket.socket, n: int, deadline: float) -> None:
    """Read and throw away n bytes under the same wall-clock discipline as
    _recv_exact_deadline, in bounded chunks — draining an oversized frame
    must never buffer it."""
    left = n
    while left > 0:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise socket.timeout("deadline exceeded draining oversized frame")
        sock.settimeout(remaining)
        got = sock.recv(min(left, 1 << 20))
        if not got:
            raise ConnectionError("peer closed")
        left -= len(got)


def _recv_frame_deadline(sock: socket.socket, deadline: float) -> tuple[int, int, bytes]:
    head = _recv_exact_deadline(sock, HEADER_LEN, deadline)
    if head[:4] != MAGIC:
        raise ProtocolError(f"bad magic {head[:4]!r}")
    kind, req_id, length = struct.unpack("<III", head[4:])
    if length > MAX_FRAME_LEN:
        raise ProtocolError(
            f"frame of {length} bytes exceeds max {MAX_FRAME_LEN}", req_id=req_id
        )
    return kind, req_id, _recv_exact_deadline(sock, length, deadline)


# ---------------------------------------------------------------------------
# problem wire form


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode()


def _unb64(s: str, dtype) -> np.ndarray:
    return np.frombuffer(base64.b64decode(s), dtype=dtype)


def _encode_pods_flat(pods) -> dict:
    """Class-deduplicated pod payload: one JSON spec per scheduling class
    plus flat per-pod identity columns (SURVEY §7 hard-part #5 — the wire
    cost is O(classes) JSON + O(pods) binary)."""
    from karpenter_tpu.solver.ordering import pod_encode_class

    classes: dict[tuple, int] = {}
    reps = []
    cls = np.zeros(len(pods), np.int32)
    for i, p in enumerate(pods):
        key = pod_encode_class(p, p.requests) + (
            tuple(sorted(p.metadata.labels.items())),
            tuple(sorted(p.metadata.annotations.items())),
            p.namespace,
        )
        c = classes.get(key)
        if c is None:
            c = len(reps)
            classes[key] = c
            reps.append(p)
        cls[i] = c
    return {
        "classes": codec.to_jsonable(reps),
        "cls": _b64(cls),
        "names": [p.name for p in pods],
        "uids": [p.uid for p in pods],
        "creation": _b64(
            np.asarray([p.metadata.creation_timestamp for p in pods], np.float64)
        ),
    }


def _decode_pods_flat(d: dict):
    reps = codec.from_jsonable(d["classes"])
    cls = _unb64(d["cls"], np.int32)
    creation = _unb64(d["creation"], np.float64)
    out = []
    for i in range(len(cls)):
        p = reps[int(cls[i])].deep_copy()
        p.metadata.name = d["names"][i]
        p.metadata.uid = d["uids"][i]
        p.metadata.creation_timestamp = float(creation[i])
        out.append(p)
    return out


def _encode_views(views) -> list[dict]:
    out = []
    for v in views or []:
        out.append(
            {
                "name": v.name,
                # copied, not aliased: the epoch client RETAINS these
                # dicts as its acked sections — an alias would make an
                # in-place caller mutation compare equal to itself in
                # diff_sections and silently desync client and server
                "node_labels": dict(v.node_labels),
                "labels": dict(v.labels),
                "taints": codec.to_jsonable(list(v.taints)),
                "available": dict(v.available),
                "capacity": dict(v.capacity),
                "daemonset_requests": dict(v.daemonset_requests),
                "initialized": v.initialized,
                "hostname": v.hostname,
                "host_ports": {
                    uid: [list(p) for p in ports]
                    for uid, ports in v.host_port_usage._by_pod.items()
                },
                "volumes": {
                    uid: sorted([list(p) for p in s])
                    for uid, s in v.volume_usage._by_pod.items()
                },
                "csi_allocatable": dict(getattr(v, "csi_allocatable", {}) or {}),
            }
        )
    return out


def _decode_views(data) -> Optional[list[StateNodeView]]:
    if data is None:
        return None
    out = []
    for d in data:
        v = StateNodeView(
            name=d["name"],
            node_labels=d["node_labels"],
            labels=d["labels"],
            taints=codec.from_jsonable(d["taints"]),
            available={k: int(x) for k, x in d["available"].items()},
            capacity={k: int(x) for k, x in d["capacity"].items()},
            daemonset_requests={
                k: int(x) for k, x in d["daemonset_requests"].items()
            },
            initialized=d["initialized"],
            hostname=d["hostname"],
            csi_allocatable={
                k: int(v2) for k, v2 in d.get("csi_allocatable", {}).items()
            },
        )
        for uid, ports in d.get("host_ports", {}).items():
            v.host_port_usage._by_pod[uid] = [tuple(p) for p in ports]
        for uid, vols in d.get("volumes", {}).items():
            v.volume_usage._by_pod[uid] = {
                tuple(p) if isinstance(p, list) else ("", p) for p in vols
            }
        out.append(v)
    return out


def _encode_cluster(cluster) -> Optional[dict]:
    """The ClusterSource slice topology counting needs on the server side:
    scheduled pods by namespace (existing anti-affinity / spread-count
    state), node labels by name, and namespace labels. Without this a
    sidecar solve of a cluster with RUNNING pods would see an empty world
    and could co-locate against existing anti-affinity. O(bound pods)
    JSON — the flat-column optimization covers only the pending payload."""
    if cluster is None:
        return None
    return {
        "namespace_labels": dict(cluster.namespace_labels),
        "pods_by_namespace": {
            ns: codec.to_jsonable([p for p in pods if p.node_name])
            for ns, pods in cluster.pods_by_namespace.items()
        },
        "node_labels_by_name": {
            name: dict(node.metadata.labels)
            for name, node in cluster.nodes_by_name.items()
        },
    }


def _decode_cluster(req: dict) -> ClusterSource:
    from karpenter_tpu.api import objects as api

    cl = req.get("cluster")
    if not cl:
        return ClusterSource(namespace_labels=req.get("namespace_labels") or {})
    nodes_by_name = {
        name: api.Node(metadata=api.ObjectMeta(name=name, labels=dict(labels)))
        for name, labels in cl.get("node_labels_by_name", {}).items()
    }
    pods_by_ns = {
        ns: codec.from_jsonable(v)
        for ns, v in cl.get("pods_by_namespace", {}).items()
    }
    return ClusterSource(
        pods_by_ns, nodes_by_name, cl.get("namespace_labels") or {}
    )


def encode_problem_dict(
    node_pools,
    instance_types_by_pool,
    pods,
    state_node_views=None,
    daemonset_pods=None,
    options: Optional[SchedulerOptions] = None,
    force_oracle: bool = False,
    namespace_labels: Optional[dict] = None,
    cluster=None,
) -> dict:
    """The full-snapshot request dict — json.dumps of this IS the legacy
    SOLVE payload (encode_problem_request), and epochs.sections_from_
    request decomposes it into the epoch sections, so the snapshot, the
    epoch-establishing snapshot, and the delta-materialized request all
    share ONE canonical schema."""
    if namespace_labels is None and cluster is not None:
        namespace_labels = cluster.namespace_labels
    req = {
        # copied for the same retained-sections reason as _encode_views:
        # the caller may mutate its namespace-labels map between solves
        "namespace_labels": dict(namespace_labels or {}),
        "cluster": _encode_cluster(cluster),
        "node_pools": codec.to_jsonable(node_pools),
        "instance_types_by_pool": {
            k: codec.to_jsonable(list(v)) for k, v in instance_types_by_pool.items()
        },
        "pods_flat": _encode_pods_flat(pods),
        "state_node_views": (
            _encode_views(state_node_views) if state_node_views is not None else None
        ),
        "daemonset_pods": codec.to_jsonable(daemonset_pods or []),
        # EVERY SchedulerOptions field crosses the wire: a sidecar solving
        # with defaults while the control plane configured otherwise is a
        # silent decision divergence (feature gates, routing thresholds)
        "options": {
            "ignore_preferences": bool(options and options.ignore_preferences),
            "min_values_best_effort": bool(options and options.min_values_best_effort),
            "reserved_capacity_enabled": bool(
                options and options.reserved_capacity_enabled
            ),
            "reserved_offering_strict": bool(
                options and options.reserved_offering_strict
            ),
            "timeout_seconds": options.timeout_seconds if options else None,
            "claim_slot_div": options.claim_slot_div if options else None,
            "tpu_min_pods": options.tpu_min_pods if options else None,
        },
        "force_oracle": force_oracle,
    }
    return req


def encode_problem_request(
    node_pools,
    instance_types_by_pool,
    pods,
    state_node_views=None,
    daemonset_pods=None,
    options: Optional[SchedulerOptions] = None,
    force_oracle: bool = False,
    namespace_labels: Optional[dict] = None,
    cluster=None,
) -> bytes:
    return json.dumps(
        encode_problem_dict(
            node_pools,
            instance_types_by_pool,
            pods,
            state_node_views,
            daemonset_pods,
            options,
            force_oracle,
            namespace_labels,
            cluster,
        )
    ).encode()


def _decode_problem_request(payload: bytes):
    return _decode_problem_dict(json.loads(payload))


def _decode_problem_dict(req: dict):
    """THE request decoder: wire snapshots and delta-materialized epoch
    requests (epochs.materialize_request) both decode here, so a delta
    solve can never diverge from its full-resync twin by construction."""
    node_pools = codec.from_jsonable(req["node_pools"])
    its_by_pool = {
        k: codec.from_jsonable(v) for k, v in req["instance_types_by_pool"].items()
    }
    pods = _decode_pods_flat(req["pods_flat"])
    views = _decode_views(req.get("state_node_views"))
    source = _decode_cluster(req)
    daemons = codec.from_jsonable(req.get("daemonset_pods") or [])
    o = req.get("options") or {}
    defaults = SchedulerOptions()
    options = SchedulerOptions(
        ignore_preferences=o.get("ignore_preferences", False),
        min_values_best_effort=o.get("min_values_best_effort", False),
        reserved_capacity_enabled=o.get("reserved_capacity_enabled", False),
        reserved_offering_strict=o.get("reserved_offering_strict", False),
        timeout_seconds=o.get("timeout_seconds"),
        claim_slot_div=(
            o["claim_slot_div"]
            if o.get("claim_slot_div") is not None
            else defaults.claim_slot_div
        ),
        tpu_min_pods=(
            o["tpu_min_pods"]
            if o.get("tpu_min_pods") is not None
            else defaults.tpu_min_pods
        ),
    )
    return (
        node_pools,
        its_by_pool,
        pods,
        views,
        daemons,
        options,
        req.get("force_oracle", False),
        source,
    )


def _encode_result(results, used_tpu: bool, pods) -> bytes:
    """Flat assignment arrays: pod i (request order) -> claim index, or
    ~existing-node index; -1 = error/unscheduled."""
    claim_of = {}
    for ci, c in enumerate(results.new_node_claims):
        for p in c.pods:
            claim_of[p.uid] = ci
    enode_names = [n.name for n in results.existing_nodes]
    enode_of = {}
    for ei, n in enumerate(results.existing_nodes):
        for p in n.pods:
            enode_of[p.uid] = ei
    assign = np.full(len(pods), -1, np.int32)
    for i, p in enumerate(pods):
        if p.uid in claim_of:
            assign[i] = claim_of[p.uid]
        elif p.uid in enode_of:
            assign[i] = -2 - enode_of[p.uid]  # -2 -> node 0, -3 -> node 1, ...
    claims = [
        {
            "nodepool": c.nodepool_name,
            "instance_types": [it.name for it in c.instance_type_options],
            "requests": dict(c.requests),
            # the launchable form: requirements, taints, labels — everything
            # the control plane's CreateNodeClaims needs, so a REMOTE solve
            # is actionable without re-deriving template state client-side
            # (solver/hybrid.py ResilientSolver._to_results)
            "node_claim": codec.to_jsonable(c.to_node_claim()),
        }
        for c in results.new_node_claims
    ]
    out = {
        "used_tpu": used_tpu,
        "timed_out": results.timed_out,
        "pod_errors": dict(results.pod_errors),
        "new_node_claims": claims,
        "existing_node_names": enode_names,
        "assign": _b64(assign),
    }
    return json.dumps(out).encode()


def decode_result(resp: dict, pods) -> dict:
    """Expand the flat assignment array back into per-pod maps."""
    assign = _unb64(resp["assign"], np.int32)
    claims = [dict(c, pod_uids=[]) for c in resp["new_node_claims"]]
    for c in claims:
        if c.get("node_claim") is not None:
            c["node_claim"] = codec.from_jsonable(c["node_claim"])
    existing = {}
    for i, p in enumerate(pods):
        a = int(assign[i])
        if a >= 0:
            claims[a]["pod_uids"].append(p.uid)
        elif a <= -2:
            existing[p.uid] = resp["existing_node_names"][-2 - a]
    return {
        "used_tpu": resp["used_tpu"],
        "timed_out": resp["timed_out"],
        "pod_errors": resp["pod_errors"],
        "new_node_claims": claims,
        "existing_assignments": existing,
    }


# ---------------------------------------------------------------------------
# server


class SolverServer:
    """Serves SOLVE frames, one handler thread per connection (the control
    plane is a singleton provisioner, but a drained-and-replaced control
    plane briefly overlaps its successor — two live connections must both
    be served, not queued behind each other).

    Robustness contract (ISSUE: no solver-side fault may wedge the accept
    loop): solve failures answer ERROR on the same correlation id; framing
    violations close only the offending connection; anything unexpected is
    logged and the loop keeps serving. stop() drains gracefully — the
    listener closes first, in-flight handlers get `drain_seconds` to flush
    their responses.

    Concurrency contract (graftlint race tier): the two locks here are
    leaves — nothing blocking runs under either (_conns_lock guards set
    membership only; stop() snapshots the set under the lock and joins
    OUTSIDE it), and neither nests inside the other, so the server
    contributes no edges to the program's lock acquisition graph. The
    fault suite runs with racert-instrumented locks to witness exactly
    that under real handler-thread interleavings.

    Prewarm/readiness (docs/compile.md): with prewarm=True, start() kicks
    a background thread that AOT-compiles the bucket ladder into the
    persistent cache (solver/aot.py) BEFORE the server reports ready.
    SOLVE requests that arrive mid-prewarm are served immediately but
    degrade to the oracle fallback (force_oracle) — decision-identical,
    never an uncompiled device path — and PONG payloads say "prewarming"
    so orchestration readiness probes can gate traffic. The prewarm
    thread polls the server's stop flag between combos, and every
    on-disk artifact write is atomic, so a kill mid-prewarm can never
    poison the cache (tests/test_service_faults.py)."""

    def __init__(
        self,
        socket_path: str,
        drain_seconds: float = 30.0,
        prewarm: bool = False,
        prewarm_fn=None,
        admission: Optional[epochs.AdmissionGate] = None,
        epoch_store: Optional[epochs.EpochStore] = None,
        table_cache: Optional[epochs.DeviceTableCache] = None,
        fleet_window_seconds: float = 0.0,
        fleet_max_lanes: int = 8,
    ):
        self.socket_path = socket_path
        self.drain_seconds = drain_seconds
        self.prewarm = prewarm
        self._prewarm_fn = prewarm_fn
        self._prewarm_thread: Optional[threading.Thread] = None
        self._prewarm_stop: Optional[threading.Event] = None
        self._prewarm_gen = 0
        self.ready = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._conns: set[threading.Thread] = set()
        self._conns_lock = threading.Lock()
        # handler threads (one per connection) all bump the solve counter;
        # the read-modify-write needs its own lock or increments are lost
        self._stats_lock = threading.Lock()
        self.solves = 0
        self.oracle_degraded_solves = 0
        # the stateful-with-epochs layer (solver/epochs.py): bounded
        # per-client epoch store, content-addressed device-table cache,
        # and the admission gate in front of every solve
        self.epochs = epoch_store or epochs.EpochStore()
        self.admission = admission or epochs.AdmissionGate()
        self.table_cache = table_cache or epochs.DeviceTableCache()
        # fleet-axis serving (solver/fleet.py): with a non-zero batch
        # window, concurrent scan-path solves coalesce onto pow-2 fleet
        # lanes and share ONE vmapped dispatch per round — the
        # multi-tenant serving shape dryrun_multichip phase 4 proves.
        # 0.0 (the default) keeps the stateless per-request dispatch:
        # a lone control plane should not pay window latency for
        # siblings that never come. Pair a fleet window with an
        # AdmissionGate whose max_inflight covers the lane budget —
        # coalescing WANTS the concurrency the default gate sheds.
        self.fleet = None
        if fleet_window_seconds > 0:
            from karpenter_tpu.solver import fleet as fleet_mod

            self.fleet = fleet_mod.FleetCoalescer(
                window_seconds=fleet_window_seconds,
                max_lanes=fleet_max_lanes,
            )
        # epoch-store writes from handler threads are generation-guarded
        # (under the stats lock, the prewarm-gen discipline): a handler
        # abandoned by stop() must not install sections into a LATER
        # start()'s serving life
        self._epoch_gen = 0
        self.log = klog.root.named("solver.service")

    def start(self) -> None:
        # service startup is one of the two sanctioned call sites of the
        # persistent-cache config (the other is the solver package import)
        from karpenter_tpu.jaxsetup import ensure_compilation_cache

        ensure_compilation_cache()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._stop.clear()
        # readiness transitions BEFORE the accept loop exists: a request
        # racing start() must never observe a ready=False non-prewarming
        # server (it would spuriously degrade to the oracle). The gen
        # bump rides the stats lock so the abandoned-prewarm-thread read
        # in _run_prewarm's finally can never see a torn increment.
        with self._stats_lock:
            self._prewarm_gen += 1
            self._epoch_gen += 1
        if self.prewarm:
            self.ready.clear()
        else:
            self.ready.set()
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.socket_path)
        self._sock.listen(8)
        self._sock.settimeout(0.2)
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        if self.prewarm:
            # each prewarm thread gets its OWN stop event: start() after
            # stop() clears the server-wide _stop, which must not revive
            # an abandoned thread — its private event stays set, so it
            # exits at the next combo boundary even across restarts
            self._prewarm_stop = threading.Event()
            self._prewarm_thread = threading.Thread(
                target=self._run_prewarm,
                args=(self._prewarm_gen, self._prewarm_stop),
                daemon=True,
            )
            self._prewarm_thread.start()

    def _run_prewarm(self, gen: int, stop: threading.Event) -> None:
        """Compile the bucket ladder, then report ready. A prewarm failure
        is logged and the server reports ready anyway (degraded: first
        solves pay their compiles) — a broken cache must not brick the
        sidecar. `gen` guards the ready transition: a thread abandoned by
        stop() (the join is bounded; a combo compiles for ~15s) must not
        flip readiness during a LATER start()'s prewarm."""
        try:
            if self._prewarm_fn is not None:
                self._prewarm_fn(stop)
            else:
                from karpenter_tpu.solver import aot

                # a fleet-serving instance also prewarms the vmapped
                # lane-batched entry — every pow-2 rung up to ITS OWN
                # lane budget, not a hardcoded ladder — so coalesced
                # steady state is as zero-compile as the solo path
                # (docs/serving.md)
                from karpenter_tpu.solver import buckets as buckets_mod

                fleet_buckets = (
                    tuple(
                        buckets_mod.ladder(2, self.fleet.max_lanes, floor=2)
                    )
                    if self.fleet is not None
                    else ()
                )
                out = aot.prewarm(
                    stop=stop,
                    include_fleet=self.fleet is not None,
                    fleet_lane_buckets=fleet_buckets,
                )
                self.log.info(
                    "prewarm complete",
                    compiled=out["compiled"],
                    skipped=out["skipped"],
                    seconds=round(out["seconds"], 1),
                )
        except Exception as e:
            self.log.error(
                "prewarm failed; serving without it",
                error=f"{type(e).__name__}: {e}",
            )
        finally:
            with self._stats_lock:
                current = gen == self._prewarm_gen
            if current:
                self.ready.set()

    def stop(self) -> None:
        """Graceful drain: stop accepting, let in-flight handlers finish
        (bounded by drain_seconds), then tear the socket down."""
        self._stop.set()
        if self._prewarm_thread is not None:
            # a combo compiles for ~15s and .compile() is uninterruptible,
            # so the bounded join deliberately abandons the daemon thread
            # rather than block shutdown; its private stop event (set
            # here, never cleared) makes it exit at the next combo
            # boundary, and the gen guard keeps its final ready-set inert
            if self._prewarm_stop is not None:
                self._prewarm_stop.set()
            self._prewarm_thread.join(timeout=1.0)
            self._prewarm_thread = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        deadline = time.monotonic() + self.drain_seconds
        with self._conns_lock:
            pending = list(self._conns)
        for t in pending:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._run_conn, args=(conn,), daemon=True)
            with self._conns_lock:
                self._conns.add(t)
            t.start()

    def _run_conn(self, conn: socket.socket) -> None:
        try:
            self._handle(conn)
        except socket.timeout:
            # a response send stalled past FRAME_STALL_SECONDS: the peer
            # stopped reading — drop the connection, keep serving
            self.log.warn("peer stopped reading mid-response, closing connection")
        except ConnectionError:
            pass  # peer went away; normal churn
        except ProtocolError as e:
            # framing is lost — the stream cannot be resynchronized; answer
            # once (best effort, the header's req_id if it was readable)
            # and close only this connection
            self.log.warn("protocol violation, closing connection", error=str(e))
            if protorec.RECORDER is not None:
                protorec.RECORDER.record(
                    ev="srv_send",
                    kind=KIND_ERROR,
                    req_id=e.req_id,
                    conn=protorec.RECORDER.conn_id(conn),
                    draining=self._stop.is_set(),
                    refusal=False,
                )
            try:
                _send_frame(conn, KIND_ERROR, str(e).encode(), req_id=e.req_id)
            except OSError:
                pass
        except Exception as e:  # the accept loop must survive ANYTHING
            self.log.error(
                "unexpected error in connection handler",
                error=f"{type(e).__name__}: {e}",
            )
        finally:
            if protorec.RECORDER is not None:
                protorec.RECORDER.record(
                    ev="srv_close",
                    conn=protorec.RECORDER.conn_closed(conn),
                    draining=self._stop.is_set(),
                )
            conn.close()
            with self._conns_lock:
                self._conns.discard(threading.current_thread())

    def _recv_frame_idle(self, conn: socket.socket) -> tuple[int, int, bytes]:
        """Receive one frame, polling the stop flag only BETWEEN frames:
        the idle wait covers the first byte alone, so a poll timeout can
        never discard a partially-read header and desync the stream. Once
        a frame starts, the peer gets FRAME_STALL_SECONDS of WALL CLOCK to
        finish it (same _recv_exact_deadline discipline as the client — a
        peer trickling one byte per poll interval must not hold the
        handler thread forever); a mid-frame stall is a fault, not
        idleness.

        During drain (stop() set) the poll becomes ONE short grace read:
        a frame already in flight is still read — _handle answers it with
        an immediate retriable "draining" ERROR instead of the silent
        close that used to leave the client waiting out its full deadline
        (docs/resilience.md drain contract) — but an idle connection
        closes at once.

        Oversized frames: above MAX_FRAME_LEN but within
        OVERSIZE_DRAIN_MAX the body is drained (discarded, never
        buffered) under the same wall-clock deadline and _OversizedFrame
        is raised — the stream is in sync, so _handle answers ERROR and
        the connection KEEPS SERVING. Beyond the drain cap the length
        field is treated as corruption and the connection closes."""
        while True:
            if self._stop.is_set():
                conn.settimeout(0.05)
                try:
                    first = conn.recv(1)
                except socket.timeout:
                    raise ConnectionError("server stopping")
                break
            conn.settimeout(0.2)
            try:
                first = conn.recv(1)
                break
            except socket.timeout:
                continue
        if not first:
            raise ConnectionError("peer closed")
        deadline = time.monotonic() + FRAME_STALL_SECONDS
        head = first + _recv_exact_deadline(conn, HEADER_LEN - 1, deadline)
        if head[:4] != MAGIC:
            raise ProtocolError(f"bad magic {head[:4]!r}")
        kind, req_id, length = struct.unpack("<III", head[4:])
        if length > MAX_FRAME_LEN:
            if length > OVERSIZE_DRAIN_MAX:
                raise ProtocolError(
                    f"frame of {length} bytes exceeds max {MAX_FRAME_LEN}",
                    req_id=req_id,
                )
            _discard_exact_deadline(conn, length, deadline)
            raise _OversizedFrame(req_id, length)
        payload = _recv_exact_deadline(conn, length, deadline)
        if protorec.RECORDER is not None:
            # a COMPLETE frame arrived: everything the server hears is on
            # the record — a received solve that closes unanswered is the
            # silent-drain-close violation the refinement acceptor hunts
            protorec.RECORDER.record(
                ev="srv_recv",
                kind=kind,
                req_id=req_id,
                conn=protorec.RECORDER.conn_id(conn),
                draining=self._stop.is_set(),
            )
        return kind, req_id, payload

    def _send_response(self, conn: socket.socket, kind: int, payload: bytes, req_id: int) -> None:
        """A peer that stops READING must not wedge the handler either:
        sendall under a socket timeout enforces a total wall-clock bound
        across its internal retries (CPython tracks a deadline)."""
        if protorec.RECORDER is not None:
            # record the INTENT before the write: a peer closed by a
            # fault mid-send must still count as "answered" — the server
            # held up its half of the contract
            protorec.RECORDER.record(
                ev="srv_send",
                kind=kind,
                req_id=req_id,
                conn=protorec.RECORDER.conn_id(conn),
                draining=self._stop.is_set(),
                refusal=kind == KIND_ERROR and payload.startswith(b"draining"),
            )
        conn.settimeout(FRAME_STALL_SECONDS)
        _send_frame(conn, kind, payload, req_id=req_id)

    def _pong_payload(self, verbose: bool) -> bytes:
        """Readiness plus backpressure observability. An EMPTY-payload
        PING (every pre-epoch client and probe) keeps the legacy bare
        token — "ready"/"prewarming" byte-for-byte as before, plus
        "draining" during stop(), which an equality-on-b"ready" probe
        correctly reads as not-ready. A PING carrying payload b"v2"
        (SolverClient.ping_status) answers the JSON form with the
        admission queue depth and resident-epoch counts."""
        if self._stop.is_set():
            status = "draining"
        elif self.ready.is_set():
            status = "ready"
        else:
            status = "prewarming"
        if not verbose:
            return status.encode()
        clients, resident = self.epochs.stats()
        return json.dumps(
            {
                "status": status,
                "admission_queue_depth": self.admission.depth(),
                "epoch_clients": clients,
                "epochs": resident,
            }
        ).encode()

    def _drain_close_check(self) -> None:
        """During drain, EVERY answered frame is that connection's last:
        the one-refusal-then-close bound on the SOLVE branch must also
        cover PING/oversized/unknown-kind traffic, or a fast-sending
        peer keeps its handler thread (and socket) alive past stop()'s
        bounded join — and a later start() would find that abandoned
        handler still serving outside the new generation."""
        if self._stop.is_set():
            raise ConnectionError("server stopping")

    def _handle(self, conn: socket.socket) -> None:
        while True:
            try:
                kind, req_id, payload = self._recv_frame_idle(conn)
            except socket.timeout as e:
                raise ProtocolError(f"peer stalled mid-frame: {e}") from e
            except _OversizedFrame as e:
                # the body was drained — framing is intact, answer and
                # keep serving this connection (fault-suite contract:
                # an oversized delta must not cost the client its stream)
                self.log.warn(
                    "oversized frame refused", bytes=e.length, req_id=e.req_id
                )
                self._send_response(conn, KIND_ERROR, str(e).encode(), e.req_id)
                self._drain_close_check()
                continue
            if kind == KIND_PING:
                self._send_response(
                    conn, KIND_PONG, self._pong_payload(bool(payload)), req_id
                )
                self._drain_close_check()
                continue
            if kind not in (KIND_SOLVE, KIND_SOLVE_DELTA):
                self._send_response(
                    conn, KIND_ERROR, f"unknown kind {kind}".encode(), req_id
                )
                self._drain_close_check()
                continue
            if self._stop.is_set():
                # graceful-drain fix: a SOLVE arriving on a surviving
                # connection during the drain window gets an immediate
                # retriable refusal instead of riding out drain_seconds
                # of silence — the caller degrades to the oracle NOW.
                # ONE refusal, then the connection closes: a peer that
                # keeps sending must not hold a handler thread (and its
                # socket) past the drain window the old stop-flag loop
                # exit used to bound.
                self._send_response(
                    conn,
                    KIND_ERROR,
                    b"draining: server stopping; degrade in-process and retry later",
                    req_id,
                )
                raise ConnectionError("server stopping")
            token, hint, depth = self.admission.try_admit(len(payload))
            if token is None:
                # never queue: answer RETRY with the backoff hint so the
                # caller's deadline budget degrades it to the in-process
                # ladder instead of cascading (docs/resilience.md)
                body = json.dumps(
                    {"retry_after_seconds": hint, "queue_depth": depth}
                ).encode()
                self.log.warn(
                    "admission rejected", queue_depth=depth, hint_seconds=hint
                )
                self._send_response(conn, KIND_RETRY, body, req_id)
                continue
            t0 = time.monotonic()
            try:
                if kind == KIND_SOLVE_DELTA:
                    out_kind, out = self._solve_delta(payload, req_id)
                else:
                    out_kind, out = KIND_RESULT, self._solve(payload, req_id)
            except Exception as e:  # error frames, never a dead socket
                self.log.warn("solve failed, answering ERROR", error=str(e))
                out_kind = KIND_ERROR
                out = f"{type(e).__name__}: {e}".encode()
            finally:
                self.admission.release(token)
            if out_kind == KIND_RESULT:
                # completed solves teach the gate what a solve actually
                # costs here — wire bytes under-state delta solves, whose
                # frames are O(churn) but whose work is O(cluster + pods)
                self.admission.observe(time.monotonic() - t0)
            self._send_response(conn, out_kind, out, req_id)

    def _solve(self, payload: bytes, req_id: int = 0) -> bytes:
        # the server-side half of the solve trace: same wire correlation
        # id as the client's trace, so /debug/solves/<id> shows both —
        # client wire spans and server decode/solve/encode phases — as
        # one logical trace (tracing module docstring)
        tr = tracing.new_trace("solve", side="server")
        if req_id:
            tr.set_wire_id(req_id)
        try:
            result = self._solve_traced(payload, tr)
        except BaseException:
            tr.finish("error")
            raise
        tr.finish("ok")
        return result

    def _solve_traced(self, payload: bytes, tr) -> bytes:
        """Full-snapshot SOLVE: byte-for-byte the stateless protocol.
        An optional "epoch" {client, id} key additionally retains the
        request's cluster sections in the epoch store AFTER a successful
        solve — the client only commits its side on RESULT, so both ends
        agree on what epoch `id` means."""
        with tr.span("wire_decode_request", bytes=len(payload)):
            req = json.loads(payload)
            epoch_info = req.pop("epoch", None)
            decoded = _decode_problem_dict(req)
        gen0 = self._current_epoch_gen()
        epochs.EPOCH_SOLVES.inc(
            {"mode": "full_resync" if epoch_info else "snapshot"}
        )
        epoch_key = None
        if isinstance(epoch_info, dict):
            epoch_key = (epoch_info.get("client"), epoch_info.get("id"))
        out = self._solve_decoded(decoded, tr, epoch_key=epoch_key)
        if isinstance(epoch_info, dict):
            self._store_epoch(
                gen0,
                epoch_info.get("client"),
                epoch_info.get("id"),
                epochs.sections_from_request(req),
            )
        return out

    def _solve_delta(self, payload: bytes, req_id: int) -> tuple[int, bytes]:
        """SOLVE_DELTA: apply cluster changes against a server-held epoch
        and solve the riding pending-pod batch. EVERY failure of the
        epoch machinery — unknown/evicted base, malformed delta, a
        materialized request that no longer decodes — answers a
        retriable EPOCH_RESYNC so the client falls back to the
        full-snapshot path; only the solve itself may raise (becoming an
        ERROR frame, exactly like the snapshot path)."""
        tr = tracing.new_trace("solve", side="server")
        if req_id:
            tr.set_wire_id(req_id)
        try:
            kind, out = self._solve_delta_traced(payload, tr)
        except BaseException:
            tr.finish("error")
            raise
        tr.finish("ok" if kind == KIND_RESULT else "resync")
        return kind, out

    def _resync(self, tr, reason: str, detail: str) -> tuple[int, bytes]:
        epochs.EPOCH_RESYNCS.inc({"reason": reason})
        tr.event("epoch_resync", reason=reason, detail=detail)
        self.log.warn("epoch resync", reason=reason, detail=detail)
        return KIND_EPOCH_RESYNC, json.dumps(
            {"reason": reason, "detail": detail}
        ).encode()

    def _solve_delta_traced(self, payload: bytes, tr) -> tuple[int, bytes]:
        try:
            with tr.span("wire_decode_request", bytes=len(payload)):
                d = json.loads(payload)
            client = d["client"]
            base_epoch = d["base_epoch"]
            new_epoch = d["epoch"]
            pods_flat = d["pods_flat"]
        except (ValueError, KeyError, TypeError) as e:
            return self._resync(tr, "decode_error", f"{type(e).__name__}: {e}")
        base = self.epochs.get(client, base_epoch)
        if base is None:
            return self._resync(
                tr,
                "unknown_epoch",
                f"client {client!r} epoch {base_epoch!r} not resident",
            )
        gen0 = self._current_epoch_gen()
        try:
            with tr.span("epoch_apply"):
                sections = epochs.apply_delta(base, d.get("delta") or {})
        except epochs.DeltaError as e:
            return self._resync(tr, "apply_error", str(e))
        try:
            with tr.span("wire_decode_request"):
                req = epochs.materialize_request(
                    sections, pods_flat, d.get("options"),
                    d.get("force_oracle", False),
                )
                decoded = _decode_problem_dict(req)
        except Exception as e:
            # a delta that applies but no longer decodes means the store
            # and the client disagree about the world — resync, never
            # store the poisoned sections
            return self._resync(
                tr, "materialize_error", f"{type(e).__name__}: {e}"
            )
        # store BEFORE the solve: on a solve ERROR the client keeps its
        # base epoch (it commits only on RESULT) and both base and new
        # stay resident, so either retry shape converges
        self._store_epoch(gen0, client, new_epoch, sections)
        epochs.EPOCH_SOLVES.inc({"mode": "delta"})
        return KIND_RESULT, self._solve_decoded(
            decoded, tr, epoch_key=(client, new_epoch)
        )

    def _current_epoch_gen(self) -> int:
        with self._stats_lock:
            return self._epoch_gen

    def _store_epoch(self, gen0: int, client, epoch_id, sections: dict) -> None:
        """Generation-guarded store write (the prewarm-gen discipline): a
        handler thread abandoned by stop() must not install sections into
        a later start()'s serving life."""
        if client is None or epoch_id is None:
            return
        with self._stats_lock:
            current = gen0 == self._epoch_gen
        if current:
            self.epochs.put(str(client), epoch_id, sections)
            if protorec.RECORDER is not None:
                protorec.RECORDER.record(
                    ev="srv_epoch_store", client=str(client), epoch=epoch_id
                )
        elif protorec.RECORDER is not None:
            # a DELIBERATE drop (stale generation) is a legal trace: the
            # client may still commit this epoch off the RESULT, and the
            # next delta heals through one EPOCH_RESYNC — the refinement
            # acceptor accepts a commit against a store OR a recorded
            # skip, but never against silence
            protorec.RECORDER.record(
                ev="srv_epoch_store_skipped", client=str(client), epoch=epoch_id
            )

    def _solve_decoded(self, decoded: tuple, tr, epoch_key=None) -> bytes:
        (
            node_pools,
            its_by_pool,
            pods,
            views,
            daemons,
            options,
            force_oracle,
            source,
        ) = decoded
        # mid-prewarm requests degrade to the (decision-identical) oracle:
        # the device path may still be compiling, and a solve must never
        # pay the compile wall nor race the prewarm for the jit caches
        degraded = not self.ready.is_set()
        if degraded:
            force_oracle = True
            tracing.record_fallback(
                tr, "prewarm_degraded",
                "mid-prewarm solve served by the oracle fallback",
            )
        results, scheduler = solve_in_process(
            node_pools,
            its_by_pool,
            pods,
            views,
            daemons,
            options,
            cluster=source,
            force_oracle=force_oracle,
            trace=tr,
            table_cache=self.table_cache,
            fleet=self.fleet,
            # the request's epoch identity (when it rode the epoch
            # machinery): a coalesced window's trace then shows which
            # epochs shared the materialization (solver/fleet.py)
            epoch_key=epoch_key,
        )
        with self._stats_lock:
            self.solves += 1
            if degraded:
                self.oracle_degraded_solves += 1
        with tr.span("wire_encode_result"):
            out = _encode_result(results, bool(scheduler.used_tpu), pods)
        tr.annotate(pods=len(pods), used_tpu=bool(scheduler.used_tpu))
        return out


# ---------------------------------------------------------------------------
# client


class SolverClient:
    """The control plane's side of the boundary, hardened per the failure
    ladder (docs/resilience.md):

    - requests carry a fresh correlation id; a response bearing any other
      id means the stream is poisoned — tear down, never resynchronize;
    - every call runs under a hard deadline (`request_timeout` default):
      connect, send, and every recv share one wall-clock budget, so a hung
      sidecar can never block the control plane past its deadline;
    - a timeout mid-read poisons the connection (the late response may
      still arrive) — the socket is closed, the next call reconnects;
    - transport failures (refused/reset/closed) reconnect with exponential
      backoff + jitter up to `max_retries`, inside the same deadline. A
      SOLVE is stateless server-side, so retrying a possibly-executed
      request is safe. A SOLVE_DELTA retry is idempotent too: re-applying
      base->new overwrites the new epoch with identical sections while
      the base stays resident.

    Exhausting the budget raises SolverUnavailable; a clean server-side
    ERROR frame raises SolverError; an admission RETRY frame raises
    SolverOverloaded. Callers (ResilientSolver) treat all three as
    'degrade down the ladder' (overload additionally carries a backoff
    hint and skips the breaker).

    Epoch mode (`epochs=True`, the default): the client keeps the last
    server-ACKNOWLEDGED cluster sections and ships only the diff
    (SOLVE_DELTA) against that epoch; any EPOCH_RESYNC answer — evicted
    epoch, restarted server, failed delta — drops the local epoch state
    and falls back to the full-snapshot request IN THE SAME CALL (one
    hop, structurally loop-free: a full snapshot is never answered with
    RESYNC). With `epochs=False` every request is the byte-for-byte
    legacy snapshot."""

    def __init__(
        self,
        socket_path: str,
        connect_timeout: float = 5.0,
        request_timeout: float = 30.0,
        max_retries: int = 2,
        backoff_base: float = 0.05,
        backoff_cap: float = 2.0,
        rng: Optional[random.Random] = None,
        sleep=time.sleep,
        epochs: bool = True,
    ):
        self.socket_path = socket_path
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._rng = rng or random.Random()
        self._sleep = sleep
        self._sock: Optional[socket.socket] = None
        # correlation ids start at a RANDOM point in the u32 space: the id
        # is a per-connection tripwire (the server just echoes it), but it
        # doubles as the trace id on both sides — two clients (or one
        # restarted control plane) both counting 1, 2, 3... would collide
        # in the sidecar's trace ring and /debug/solves/<id> would merge
        # unrelated solves into one "joined" trace
        self._next_id = self._rng.randrange(0, 0xFFFFFFFF)
        # observability for the breaker layer / tests
        self.reconnects = 0
        self.poisoned = 0
        # correlation id of the most recent frame sent: solve() stamps it
        # onto the caller's trace so client and sidecar spans join
        self.last_req_id = 0
        # -- epoch state (solver/epochs.py) -------------------------------
        # the client id keys the server's epoch store across reconnects;
        # random so a restarted control plane never aliases its
        # predecessor's epochs (a stale alias would DELTA against someone
        # else's world — the resync path would catch a missing epoch, but
        # an id collision with a matching epoch number would not)
        self.epochs_enabled = epochs
        self.client_id = f"c{self._rng.randrange(0, 16**12):012x}"
        self._epoch_seq = 0
        self._acked_epoch: Optional[int] = None
        self._acked_sections: Optional[dict] = None
        self.resyncs = 0
        self.delta_solves = 0
        self.full_solves = 0

    # -- connection management --------------------------------------------

    def connect(self, timeout: Optional[float] = None) -> None:
        self.close()
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout if timeout is not None else self.connect_timeout)
        sock.connect(self.socket_path)
        self._sock = sock

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def _poison(self) -> None:
        """Drop a connection whose stream state is no longer trustworthy
        (partial read, stale in-flight response, framing violation)."""
        self.poisoned += 1
        self.close()

    def _ensure_connected(self, deadline: float) -> None:
        if self._sock is not None:
            return
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise socket.timeout("deadline exceeded before connect")
        self.connect(timeout=min(self.connect_timeout, remaining))
        self.reconnects += 1

    def _backoff(self, attempt: int, deadline: float) -> None:
        """Exponential backoff with full jitter, clamped to the remaining
        deadline budget (AWS-style decorrelated retries would also do; full
        jitter is the simplest schedule that avoids thundering herds)."""
        delay = min(self.backoff_cap, self.backoff_base * (2**attempt))
        delay = self._rng.uniform(0, delay)
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise socket.timeout("deadline exceeded during backoff")
        self._sleep(min(delay, remaining))

    # -- request/response --------------------------------------------------

    def _roundtrip(
        self, kind: int, payload: bytes, timeout: Optional[float]
    ) -> tuple[int, bytes]:
        """One correlated request/response under a hard deadline, with
        bounded reconnect-and-retry on transport failure."""
        budget = self.request_timeout if timeout is None else timeout
        deadline = time.monotonic() + budget
        attempt = 0
        while True:
            try:
                self._ensure_connected(deadline)
                self._next_id = (self._next_id % 0xFFFFFFFF) + 1
                req_id = self._next_id
                self.last_req_id = req_id
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise socket.timeout("deadline exceeded before send")
                self._sock.settimeout(remaining)
                _send_frame(self._sock, kind, payload, req_id=req_id)
                try:
                    rkind, rid, resp = _recv_frame_deadline(self._sock, deadline)
                except ProtocolError:
                    self._poison()  # framing lost (corrupted stream)
                    raise
                if rid != req_id:
                    self._poison()
                    raise ProtocolError(
                        f"correlation mismatch: sent {req_id}, got {rid} — "
                        "stream poisoned, tearing down"
                    )
                if protorec.RECORDER is not None:
                    protorec.RECORDER.record(
                        ev="cli_roundtrip",
                        client=self.client_id,
                        kind=kind,
                        resp_kind=rkind,
                        req_id=req_id,
                    )
                return rkind, resp
            except socket.timeout as e:
                # a partial read after timeout leaves the response in
                # flight: poison, never resynchronize mid-stream
                self._poison()
                raise SolverUnavailable(
                    f"no response within {budget:.3f}s deadline: {e}"
                ) from e
            except (ConnectionError, OSError) as e:
                if isinstance(e, (SolverUnavailable,)):
                    raise
                self._poison()
                attempt += 1
                if attempt > self.max_retries:
                    raise SolverUnavailable(
                        f"sidecar unreachable after {attempt} attempts: {e}"
                    ) from e
                try:
                    self._backoff(attempt - 1, deadline)
                except socket.timeout:
                    raise SolverUnavailable(
                        f"deadline exhausted retrying: {e}"
                    ) from e

    def ping(self, timeout: Optional[float] = None) -> bool:
        kind, _ = self._roundtrip(KIND_PING, b"", timeout)
        return kind == KIND_PONG

    def ping_status(self, timeout: Optional[float] = None) -> dict:
        """The verbose PONG: {status, admission_queue_depth,
        epoch_clients, epochs}. Empty-payload pings keep the legacy bare
        token for old probes; this opts into the JSON form. A PRE-epoch
        server ignores the v2 payload and answers the bare token — that
        degrades to a status-only dict here, never an exception against
        a healthy old sidecar."""
        kind, resp = self._roundtrip(KIND_PING, b"v2", timeout)
        if kind != KIND_PONG:
            raise SolverError(f"PING answered kind {kind}")
        try:
            return json.loads(resp)
        except ValueError:
            return {"status": resp.decode(errors="replace")}

    @staticmethod
    def _overloaded(resp: bytes) -> SolverOverloaded:
        try:
            d = json.loads(resp)
            hint = float(d.get("retry_after_seconds", 0.0))
            depth = int(d.get("queue_depth", 0))
        except (ValueError, TypeError):
            hint, depth = 0.0, 0
        return SolverOverloaded(
            f"sidecar admission rejected (queue depth {depth}); "
            f"retry after {hint:.3f}s",
            backoff_hint_seconds=hint,
            queue_depth=depth,
        )

    def _finish_result(self, kind: int, resp: bytes, pods, trace) -> dict:
        if trace is not None:
            # the correlation id of the attempt that ANSWERED (retries
            # re-id; last_req_id tracks the final frame on the wire)
            trace.set_wire_id(self.last_req_id)
        if kind == KIND_RETRY:
            raise self._overloaded(resp)
        if kind == KIND_ERROR:
            raise SolverError(resp.decode())
        with tracing.span_of(trace, "wire_decode", bytes=len(resp)):
            return decode_result(json.loads(resp), pods)

    def solve(
        self,
        node_pools,
        instance_types_by_pool,
        pods,
        state_node_views=None,
        daemonset_pods=None,
        options: Optional[SchedulerOptions] = None,
        force_oracle: bool = False,
        namespace_labels: Optional[dict] = None,
        timeout: Optional[float] = None,
        cluster=None,
        trace=None,
    ) -> dict:
        """`trace` (tracing.Trace, optional): wire-phase spans land on it
        and the SOLVE frame's correlation id becomes the trace id, joining
        this client-side trace with the sidecar's server-side one.

        Epoch mode ships a SOLVE_DELTA when a server-acknowledged epoch
        exists, falling back to the full snapshot on EPOCH_RESYNC — one
        extra hop inside the same deadline, never a loop. The local epoch
        state commits only on a RESULT frame, mirroring the server (which
        stores sections before answering), so a lost response leaves both
        resident epochs intact and either retry shape converges."""
        with tracing.span_of(trace, "wire_encode", pods=len(pods)):
            req = encode_problem_dict(
                node_pools,
                instance_types_by_pool,
                pods,
                state_node_views,
                daemonset_pods,
                options,
                force_oracle,
                namespace_labels,
                cluster,
            )
            if not self.epochs_enabled:
                payload = json.dumps(req).encode()
            else:
                sections = epochs.sections_from_request(req)
        if not self.epochs_enabled:
            with tracing.span_of(trace, "wire_roundtrip", bytes=len(payload)):
                kind, resp = self._roundtrip(KIND_SOLVE, payload, timeout)
            return self._finish_result(kind, resp, pods, trace)

        if self._acked_epoch is not None:
            delta = epochs.diff_sections(self._acked_sections, sections)
            self._epoch_seq += 1
            body = {
                "client": self.client_id,
                "base_epoch": self._acked_epoch,
                "epoch": self._epoch_seq,
                "delta": delta,
                "pods_flat": req["pods_flat"],
                "options": req["options"],
                "force_oracle": req["force_oracle"],
            }
            payload = json.dumps(body).encode()
            # an oversized delta (mass churn) would be refused on arrival;
            # skip straight to the snapshot instead of burning a round trip
            if HEADER_LEN + len(payload) <= MAX_FRAME_LEN:
                with tracing.span_of(
                    trace, "wire_roundtrip", bytes=len(payload), mode="delta"
                ):
                    kind, resp = self._roundtrip(KIND_SOLVE_DELTA, payload, timeout)
                if kind == KIND_EPOCH_RESYNC:
                    # retriable by contract: drop local epoch state and
                    # fall through to the always-correct full snapshot
                    self.resyncs += 1
                    self._acked_epoch = None
                    self._acked_sections = None
                    if trace is not None:
                        trace.event("epoch_resync", server=resp.decode())
                elif kind == KIND_ERROR and resp.startswith(b"unknown kind"):
                    # a PRE-EPOCH server (mixed-version rollout: control
                    # plane upgraded first) doesn't speak SOLVE_DELTA;
                    # its snapshot path ignored our epoch key, so the
                    # acked state is a fiction. Disable epoch mode for
                    # this client's lifetime and fall through to the
                    # plain snapshot — without this, every solve would
                    # retry the delta, fail identically, and feed the
                    # breaker against a healthy old sidecar.
                    self.resyncs += 1
                    self.epochs_enabled = False
                    self._acked_epoch = None
                    self._acked_sections = None
                    if trace is not None:
                        trace.event("epoch_resync", server="pre-epoch peer")
                    payload = json.dumps(req).encode()
                    with tracing.span_of(
                        trace, "wire_roundtrip", bytes=len(payload), mode="legacy"
                    ):
                        kind, resp = self._roundtrip(KIND_SOLVE, payload, timeout)
                    return self._finish_result(kind, resp, pods, trace)
                else:
                    out = self._finish_result(kind, resp, pods, trace)
                    self._acked_epoch = body["epoch"]
                    self._acked_sections = sections
                    self.delta_solves += 1
                    if protorec.RECORDER is not None:
                        protorec.RECORDER.record(
                            ev="cli_epoch_commit",
                            client=self.client_id,
                            epoch=body["epoch"],
                            mode="delta",
                        )
                    return out

        # full snapshot, establishing (or re-establishing) an epoch
        self._epoch_seq += 1
        req["epoch"] = {"client": self.client_id, "id": self._epoch_seq}
        payload = json.dumps(req).encode()
        with tracing.span_of(
            trace, "wire_roundtrip", bytes=len(payload), mode="full"
        ):
            kind, resp = self._roundtrip(KIND_SOLVE, payload, timeout)
        out = self._finish_result(kind, resp, pods, trace)
        self._acked_epoch = self._epoch_seq
        self._acked_sections = sections
        self.full_solves += 1
        if protorec.RECORDER is not None:
            protorec.RECORDER.record(
                ev="cli_epoch_commit",
                client=self.client_id,
                epoch=self._epoch_seq,
                mode="snapshot",
            )
        return out
