"""Host-side encoding of a scheduling problem into dense tensors.

The encoder consumes a *constructed oracle Scheduler* (karpenter_tpu.solver
.oracle.Scheduler) so template filtering, daemon overhead, existing-node
ordering, and topology-group construction are byte-identical to the oracle —
the kernel then reproduces the oracle's per-pod decisions on tensors
(reference call stack: scheduler.go:377 Solve / nodeclaim.go:114 CanAdd).

Structural choices (SURVEY.md §7 "tensorization"):
- hostname is not a vocab key: a node IS its hostname domain, so hostname
  topologies count per node-slot (existing nodes then claim slots);
- every other topology key counts per vocab value id ("zone-family");
- instance types live in one global table; each template owns a bitmask of
  it; each claim carries a surviving-types bitmask.

Problems the tensor encoding can't express exactly raise UnsupportedBySolver
and the caller falls back to the oracle (the hybrid dispatch documented in
solver/tpu.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import NodeInclusionPolicy, Operator, Pod
from karpenter_tpu.ops.encode import Reqs, empty_reqs, encode_requirements
from karpenter_tpu.ops.vocab import ResourceTable, UnsupportedProblem, Vocab, WORD_BITS
from karpenter_tpu.scheduling import Requirements, Taints
from karpenter_tpu.scheduling.hostports import get_host_ports
from karpenter_tpu.solver import buckets
from karpenter_tpu.solver.oracle import Scheduler
from karpenter_tpu.solver.topology import TopologyGroup, TopologyType
from karpenter_tpu.utils import resources as res


class UnsupportedBySolver(Exception):
    """Problem uses a feature outside the tensor encoding; use the oracle."""


TERMINAL_PHASES = ("Succeeded", "Failed")


# topology-slot kinds in the per-pod constraint table
TOPO_NONE = 0
TOPO_SPREAD_V = 1  # zone-family (vocab-key) spread
TOPO_AFFINITY_V = 2
TOPO_ANTI_V = 3
TOPO_SPREAD_H = 4  # hostname-family
TOPO_AFFINITY_H = 5
TOPO_ANTI_H = 6

# hard cap on per-pod constraint slots; the encoded table is sized to the
# actual per-problem maximum (usually 1) so the kernel's unrolled topology
# evaluation stays as small as the problem allows
MAX_OWNED_TOPOLOGIES = 8
MAX_FILTER_ALTERNATIVES = 2


@dataclass
class VGroup:
    """Zone-family group: domain counts per vocab value id of its key."""

    group: TopologyGroup
    kid: int
    skew: int
    min_domains: int  # -1 = unset
    # filter alternative indices into the stacked filter Reqs (-1 = none)
    filt: tuple[int, int] = (-1, -1)


@dataclass
class HGroup:
    """Hostname-family group: domain counts per node slot."""

    group: TopologyGroup
    skew: int
    inverse: bool
    filt: tuple[int, int] = (-1, -1)


@dataclass
class EncodedProblem:
    vocab: Vocab
    table: ResourceTable
    scheduler: Scheduler  # the oracle object encoding was derived from

    # dims
    num_templates: int = 0
    num_types: int = 0
    num_existing: int = 0
    max_claims: int = 0
    vmax: int = 0

    # templates [T]
    treq: Optional[Reqs] = None
    tdaemon: Optional[np.ndarray] = None  # [T, R] i32 initial claim requests
    ttypes: Optional[np.ndarray] = None  # [T, IW] u32 type membership
    tlimit_def: Optional[np.ndarray] = None  # [T, R] bool
    tlimit_rem: Optional[np.ndarray] = None  # [T, R] i32
    thas_limits: Optional[np.ndarray] = None  # [T] bool

    # instance types [I]
    ireq: Optional[Reqs] = None
    ialloc: Optional[np.ndarray] = None  # [I, R] i32
    icap: Optional[np.ndarray] = None  # [I, R] i32

    # offerings (flattened) [O]; rows past num_offerings_real are bucket
    # padding with ovalid=False (solver/buckets.py pad_offerings)
    otype: Optional[np.ndarray] = None  # [O] i32 owning type
    oword: Optional[np.ndarray] = None  # [O, 3] i32 word of zone/ct/rid bit (-1 = n/a)
    obit: Optional[np.ndarray] = None  # [O, 3] i32
    ovalid: Optional[np.ndarray] = None  # [O] bool — real offering rows
    num_offerings_real: int = 0
    # reserved-capacity bookkeeping (reservationmanager.go:28; round 5)
    orid: Optional[np.ndarray] = None  # [O] i32 reservation index (-1 none)
    num_reservations: int = 0
    rid_names: list[str] = field(default_factory=list)  # [NRES]
    rescap0: Optional[np.ndarray] = None  # [NRES] i32 initial capacities
    # host ports (hostportusage.go:35; round 5): HP distinct triples
    num_host_ports: int = 0
    php_own_c: Optional[np.ndarray] = None  # [NC, HPW] u32 own triple bits
    php_conf_c: Optional[np.ndarray] = None  # [NC, HPW] u32 conflict mask
    thp: Optional[np.ndarray] = None  # [T, HPW] daemonset port seeds
    ehp: Optional[np.ndarray] = None  # [E, HPW] existing-node usage seeds

    # existing nodes [E]
    ereq: Optional[Reqs] = None
    eavail: Optional[np.ndarray] = None  # [E, R] i32
    ezone_seg: Optional[np.ndarray] = None  # [E, TW] — labels-derived, = ereq.mask

    # zone-family topology groups [Gv]
    vgroups: list[VGroup] = field(default_factory=list)
    v_kid: Optional[np.ndarray] = None  # [Gv] i32
    v_word: Optional[np.ndarray] = None  # [Gv, VMAX] i32 (global word; -1 pad)
    v_bit: Optional[np.ndarray] = None  # [Gv, VMAX] i32
    v_reg: Optional[np.ndarray] = None  # [Gv, VMAX] bool registered
    v_cnt: Optional[np.ndarray] = None  # [Gv, VMAX] i32 initial counts
    v_skew: Optional[np.ndarray] = None  # [Gv] i32
    v_mindom: Optional[np.ndarray] = None  # [Gv] i32 (-1 unset)
    v_filt: Optional[np.ndarray] = None  # [Gv, 2] i32 filter alt rows (-1 none)

    # hostname-family topology groups [Gh] over slots [S = E + N]
    hgroups: list[HGroup] = field(default_factory=list)
    h_seed: list[tuple[int, int, int]] = field(default_factory=list)  # (g, slot, count)
    h_skew: Optional[np.ndarray] = None  # [Gh] i32
    h_filt: Optional[np.ndarray] = None  # [Gh, 2] i32

    # stacked node-filter alternatives
    filter_reqs: Optional[Reqs] = None  # [F]

    # per-pod index tables (built per solve() call). Everything heavier
    # than an index is stored per CLASS: a 50k-pod batch dedupes into a
    # few hundred encode classes, and the per-pod Python loops + [cls]
    # broadcasts used to dominate solve wall-clock (VERDICT r3 weak #1).
    pods: list[Pod] = field(default_factory=list)
    pod_class: Optional[np.ndarray] = None  # [P] i32 — encode-class index
    srow: Optional[np.ndarray] = None  # [P] i32 — selection-row index
    class_reps: list[int] = field(default_factory=list)  # [NC] rep pod idx
    rcls_of: Optional[np.ndarray] = None  # [NC] i32 — requirement class
    rclass_creps: list[int] = field(default_factory=list)  # [NR] class idx

    # per-class tables [NC, ...]
    preq_c: Optional[Reqs] = None
    prequests_c: Optional[np.ndarray] = None  # [NC, R] i32
    ptol_t_c: Optional[np.ndarray] = None  # [NC, T] bool tolerates template
    ptol_e_c: Optional[np.ndarray] = None  # [NC, E] bool tolerates existing
    ptopo_kind_c: Optional[np.ndarray] = None  # [NC, C] i32
    ptopo_gid_c: Optional[np.ndarray] = None  # [NC, C] i32
    ptopo_sel_c: Optional[np.ndarray] = None  # [NC, C] bool selects self
    pinv_h_c: Optional[np.ndarray] = None  # [NC, Gh] bool inverse-anti applies
    pown_h_c: Optional[np.ndarray] = None  # [NC, Gh] bool owner (inverse record)

    # selection rows: unique per (namespace, labels) — per-pod record rows
    # are sel_rows_*[srow]
    sel_rows_v: Optional[np.ndarray] = None  # [U, Gv] bool
    sel_rows_h: Optional[np.ndarray] = None  # [U, Gh] bool

    # relaxation tiers (preferences.go:38 ladder, walked host-side per
    # requirement class; a pod's kernel step attempts tiers in order —
    # tpu_kernel._step_relax). Tier tables are stored only for RELAXABLE
    # rclasses (rrow_of_rcls maps into them); L = num_tiers.
    num_tiers: int = 1
    ntiers_r: Optional[np.ndarray] = None  # [NR] i32
    rrow_of_rcls: Optional[np.ndarray] = None  # [NR] i32 (0 when not relaxable)
    rt_tier_reqs: list = field(default_factory=list)  # [NRx][L] Requirements
    rt_preq: Optional[Reqs] = None  # [NRx, L, ...]
    rt_tol_t: Optional[np.ndarray] = None  # [NRx, L, T]
    rt_tol_e: Optional[np.ndarray] = None  # [NRx, L, E]
    rt_kind: Optional[np.ndarray] = None  # [NRx, L, C]
    rt_gid: Optional[np.ndarray] = None  # [NRx, L, C]
    rt_sel: Optional[np.ndarray] = None  # [NRx, L, C]


def _pow2(n: int, floor: int = 8) -> int:
    """Back-compat alias for the bucket ladder (solver/buckets.py owns
    the pow-2 rung definition; importers of _pow2 predate it)."""
    return buckets.bucket(n, floor)


def _gate(cond: bool, why: str) -> None:
    if cond:
        raise UnsupportedBySolver(why)


MAX_RELAX_TIERS = 12


def pod_unsupported_reason(
    pod: Pod, ignore_preferences: bool = False
) -> Optional[str]:
    """Why the kernel can't encode this pod (None = fully supported).

    Round 4: the relaxation ladder (preferences.go:38) rides the kernel —
    tiers are precomputed per requirement class at encode time and a pod's
    step attempts them in order (tpu_kernel._step_relax mirrors
    scheduler.go:434 trySchedule's inline relax-on-a-copy), so preferred
    affinities, ScheduleAnyway TSCs, and required OR-terms are no longer
    fallback reasons. Round 5: host ports ride the kernel too — the
    distinct (ip, proto, port) triples become bit positions, conflicts a
    precomputed relation mask, and per-slot usage a State bitmask
    (hostportusage.go:35). What remains gated: volume claims, hostname
    requirements (a node IS its hostname slot — no vocab id), and
    pathologically long ladders."""
    if pod.volume_claims:
        return "pod volume claims"
    if well_known.HOSTNAME_LABEL_KEY in pod.node_selector:
        return "hostname node selector"
    na = pod.node_affinity
    rungs = 0
    if na is not None:
        for term in na.required_terms:
            for e in term.match_expressions:
                if e.key == well_known.HOSTNAME_LABEL_KEY:
                    return "hostname affinity term"
        for w in na.preferred:
            for e in w.preference.match_expressions:
                if e.key == well_known.HOSTNAME_LABEL_KEY:
                    return "hostname preferred-affinity term"
        rungs += max(0, len(na.required_terms) - 1)
        if not ignore_preferences:
            rungs += len(na.preferred)
    if not ignore_preferences:
        # under Ignore, preference rungs don't change the strict problem —
        # the ladder walk collapses them to zero effective tiers
        rungs += len(pod.pod_affinity_preferred)
        rungs += len(pod.pod_anti_affinity_preferred)
        rungs += sum(
            1
            for t in pod.topology_spread_constraints
            if t.when_unsatisfiable != "DoNotSchedule"
        )
    if rungs + 2 > MAX_RELAX_TIERS:  # +1 tier 0, +1 PreferNoSchedule rung
        return "relaxation ladder too long"
    return None


def _check_pod_supported(pod: Pod, ignore_preferences: bool = False) -> None:
    reason = pod_unsupported_reason(pod, ignore_preferences)
    _gate(reason is not None, reason or "")


def _tier_key(pod: Pod, ignore_preferences: bool):
    """The EFFECTIVE constraint signature of a tier. Under Respect this is
    the full class key; under PreferencePolicy=Ignore only strict
    requirements and tolerations matter (preferences are dropped up front,
    so rungs that strip them are no-ops and must collapse)."""
    from karpenter_tpu.solver.ordering import pod_class_key

    if not ignore_preferences:
        return pod_class_key(pod)
    reqs = Requirements.strict_from_pod(pod)
    return (
        tuple(
            sorted(
                (r.key, str(r.operator()), tuple(sorted(r.values)), r.complement)
                for r in reqs.values()
            )
        ),
        tuple((t.key, t.operator, t.value, t.effect) for t in pod.tolerations),
    )


def _walk_ladder(scheduler, pod: Pod) -> list[Pod]:
    """Tier pod copies, tier 0 first: the oracle's own Preferences walks
    the rungs (preferences.go:38 order cannot drift between paths).
    Consecutive tiers with equal EFFECTIVE constraints collapse — an
    attempt with identical constraints against the same state returns the
    same verdict, so the duplicate rung is a no-op (this is what keeps
    PreferencePolicy=Ignore ladders short: preference rungs don't change
    the strict problem)."""
    ignore = scheduler.opts.ignore_preferences
    tiers = [pod.deep_copy()]
    keys = [_tier_key(tiers[0], ignore)]
    copy = pod.deep_copy()
    while scheduler.preferences.relax(copy):  # relax invalidates key caches
        k = _tier_key(copy, ignore)
        if k != keys[-1]:
            tiers.append(copy.deep_copy())
            keys.append(k)
        _gate(len(tiers) > MAX_RELAX_TIERS, "relaxation ladder too long")
    return tiers


def encode_problem(scheduler: Scheduler, pods: list[Pod]) -> EncodedProblem:
    """Build the full tensor problem from an oracle Scheduler + pod batch."""
    if scheduler.opts.reserved_capacity_enabled:
        # Round 5: NON-STRICT reserved capacity rides the kernel — the
        # stateful per-reservation counting (reservationmanager.go:57-98)
        # is a device-side capacity vector consumed at claim commits
        # (tpu_kernel._step reservation bookkeeping; decisions themselves
        # are unchanged in non-strict mode, only the held sets and the
        # finalize-time requirements). STRICT mode can fail a can_add on
        # reservation exhaustion (nodeclaim.go:227) — that per-candidate
        # error path stays on the oracle.
        def is_reserved(o):
            if o.requirements.has(well_known.RESERVATION_ID_LABEL_KEY):
                return True
            if o.requirements.has(well_known.CAPACITY_TYPE_LABEL_KEY):
                r = o.requirements.get(well_known.CAPACITY_TYPE_LABEL_KEY)
                if well_known.CAPACITY_TYPE_RESERVED in r.values:
                    return True
            return False

        has_reserved = any(
            is_reserved(o)
            for nct in scheduler.templates
            for it in nct.instance_type_options
            for o in it.offerings
        )
        _gate(
            has_reserved and scheduler.opts.reserved_offering_strict,
            "strict reserved-offering mode with reserved offerings present",
        )
        _gate(
            any(
                o.requirements.has(well_known.CAPACITY_TYPE_LABEL_KEY)
                and well_known.CAPACITY_TYPE_RESERVED
                in o.requirements.get(well_known.CAPACITY_TYPE_LABEL_KEY).values
                and not o.requirements.has(well_known.RESERVATION_ID_LABEL_KEY)
                for nct in scheduler.templates
                for it in nct.instance_type_options
                for o in it.offerings
            ),
            "reserved offering without a reservation id",
        )

    # the oracle handles the all-types-filtered-out case with per-pod errors
    # (scheduler.go:489); zero templates would also give zero-width tensors
    _gate(
        not scheduler.templates,
        "no templates survived nodepool requirement filtering",
    )

    p = EncodedProblem(vocab=Vocab(), table=ResourceTable(), scheduler=scheduler)
    topo = scheduler.topology

    # ---- vocab + resource universe ------------------------------------
    vocab, table = p.vocab, p.table
    all_types: list = []
    type_index: dict[int, int] = {}
    for nct in scheduler.templates:
        vocab.observe_requirements(nct.requirements)
        for it in nct.instance_type_options:
            if id(it) not in type_index:
                type_index[id(it)] = len(all_types)
                all_types.append(it)
    for it in all_types:
        vocab.observe_requirements(it.requirements)
        for o in it.offerings:
            vocab.observe_requirements(o.requirements)
        table.observe(it.allocatable())
        table.observe(it.capacity)

    # ---- pod class pass (the ONLY per-pod Python loop) -----------------
    class_reqs = _class_pass(p, scheduler, pods)
    for c, i in enumerate(p.class_reps):
        pod = pods[i]
        # every gated field is a class field
        _check_pod_supported(pod, scheduler.opts.ignore_preferences)
        for r in class_reqs[c].values():
            if r.key != well_known.HOSTNAME_LABEL_KEY:
                vocab.observe_requirement(r)
        table.observe(pod.requests)
    table.observe({res.PODS: 1000})

    # ---- relaxation ladders (per requirement class) --------------------
    # tier requirements must be in the vocab BEFORE finalize; the tier
    # TABLES are built later (_encode_pod_classes) once group ids exist
    from_pod_fn = (
        Requirements.strict_from_pod
        if scheduler.opts.ignore_preferences
        else Requirements.from_pod
    )
    ladders: list[Optional[list]] = []  # per rclass: None or [(pod, reqs)]
    for rid, c0 in enumerate(p.rclass_creps):
        rep = pods[p.class_reps[c0]]
        tiers = _walk_ladder(scheduler, rep)
        if len(tiers) == 1:
            ladders.append(None)
            continue
        tier_rows = []
        for tp in tiers:
            reqs = from_pod_fn(tp)
            _gate(
                reqs.has(well_known.HOSTNAME_LABEL_KEY),
                "hostname requirement on a relaxation tier",
            )
            for r in reqs.values():
                vocab.observe_requirement(r)
            tier_rows.append((tp, reqs))
        ladders.append(tier_rows)
    p._ladders = ladders
    for node in scheduler.existing_nodes:
        vocab.observe_labels(node.view.labels)
        table.observe(node.remaining_resources)
    for nct in scheduler.templates:
        table.observe(scheduler.daemon_overhead[nct])
        if nct.nodepool_name in scheduler.remaining_resources:
            table.observe(scheduler.remaining_resources[nct.nodepool_name])
    # topology group domains must be in vocab (they come from nodepool/type
    # requirements or live node labels)
    groups = list(topo.topology_groups.values()) + list(
        topo.inverse_topology_groups.values()
    )
    for tg in groups:
        if tg.key != well_known.HOSTNAME_LABEL_KEY:
            for d in tg.domains:
                vocab.observe_labels({tg.key: d})
        for freq in tg.node_filter.requirements:
            vocab.observe_requirements(freq)
    try:
        # bucket the vocab layout (words per key, key count) so label/key
        # churn between solves reuses compiled shapes (solver/buckets.py)
        if buckets.enabled():
            vocab.finalize(
                pad_words=buckets.bucket_words, pad_keys=buckets.bucket_keys
            )
        else:
            vocab.finalize()
        table.finalize()
    except UnsupportedProblem as e:
        raise UnsupportedBySolver(str(e)) from e
    _gate(vocab.total_words == 0, "empty requirement vocabulary")

    # ---- templates + types --------------------------------------------
    T = len(scheduler.templates)
    I = len(all_types)
    R = table.num_resources
    p.num_templates, p.num_types = T, I
    IW = max(1, (I + WORD_BITS - 1) // WORD_BITS)
    try:
        p.treq = encode_requirements(
            vocab, [nct.requirements for nct in scheduler.templates]
        )
        p.tdaemon = np.stack(
            [table.encode(scheduler.daemon_overhead[nct]) for nct in scheduler.templates]
        ) if T else np.zeros((0, R), np.int32)
        p.ireq = encode_requirements(vocab, [it.requirements for it in all_types])
        p.ialloc = (
            np.stack([table.encode(it.allocatable()) for it in all_types])
            if I
            else np.zeros((0, R), np.int32)
        )
        p.icap = (
            np.stack([table.encode(it.capacity) for it in all_types])
            if I
            else np.zeros((0, R), np.int32)
        )
    except UnsupportedProblem as e:
        raise UnsupportedBySolver(str(e)) from e

    p.ttypes = np.zeros((T, IW), dtype=np.uint32)
    for t, nct in enumerate(scheduler.templates):
        for it in nct.instance_type_options:
            i = type_index[id(it)]
            p.ttypes[t, i // WORD_BITS] |= np.uint32(1 << (i % WORD_BITS))

    p.tlimit_def = np.zeros((T, R), dtype=bool)
    p.tlimit_rem = np.zeros((T, R), dtype=np.int32)
    p.thas_limits = np.zeros(T, dtype=bool)
    for t, nct in enumerate(scheduler.templates):
        rem = scheduler.remaining_resources.get(nct.nodepool_name)
        if rem is None:
            continue
        p.thas_limits[t] = True
        for name, v in rem.items():
            ri = table.index.get(name)
            if ri is None:
                raise UnsupportedBySolver(f"limit on unobserved resource {name!r}")
            p.tlimit_def[t, ri] = True
            # limits can go negative (over-subscribed pools); clamp encode
            q, mod = divmod(int(v), table.scale[ri])
            _gate(mod != 0, f"limit {name!r} not divisible by resource scale")
            p.tlimit_rem[t, ri] = max(min(q, (1 << 30) - 1), -(1 << 30))

    # ---- offerings -----------------------------------------------------
    off_rows: list[tuple[int, list[int], list[int]]] = []
    off_rids: list[int] = []  # reservation index per offering (-1 none)
    rid_index: dict[str, int] = {}  # reservation id -> index
    p.rid_names = []
    off_keys = (
        well_known.TOPOLOGY_ZONE_LABEL_KEY,
        well_known.CAPACITY_TYPE_LABEL_KEY,
        well_known.RESERVATION_ID_LABEL_KEY,
    )
    for it in all_types:
        i = type_index[id(it)]
        for o in it.offerings:
            if not o.available:
                continue
            words, bits = [], []
            for key in off_keys:
                r = o.requirements.get(key) if o.requirements.has(key) else None
                if r is None:
                    words.append(-1)
                    bits.append(0)
                    continue
                _gate(
                    r.complement or len(r.values) != 1,
                    f"offering requirement {key!r} must be a single In value",
                )
                kid = vocab.key_index[key]
                vid = vocab.value_index[kid][next(iter(r.values))]
                words.append(vocab.word_offset[kid] + vid // WORD_BITS)
                bits.append(vid % WORD_BITS)
            for key in o.requirements.keys() - set(off_keys):
                raise UnsupportedBySolver(f"offering requirement on {key!r}")
            # reservation bookkeeping rides capacity-type == reserved
            # (nodes.py _offerings_to_reserve keys on capacity type)
            rid = -1
            if (
                scheduler.opts.reserved_capacity_enabled
                and o.capacity_type() == well_known.CAPACITY_TYPE_RESERVED
            ):
                name = o.reservation_id()
                got = rid_index.get(name)
                if got is None:
                    got = len(rid_index)
                    rid_index[name] = got
                    p.rid_names.append(name)
                rid = got
            off_rows.append((i, words, bits))
            off_rids.append(rid)
    O = len(off_rows)
    p.otype = np.array([r[0] for r in off_rows], dtype=np.int32).reshape(O)
    p.oword = np.array([r[1] for r in off_rows], dtype=np.int32).reshape(O, 3)
    p.obit = np.array([r[2] for r in off_rows], dtype=np.int32).reshape(O, 3)
    p.orid = np.array(off_rids, dtype=np.int32).reshape(O)
    p.num_reservations = len(rid_index)
    p.rescap0 = np.array(
        [
            scheduler.reservation_manager.capacity.get(name, 0)
            for name in p.rid_names
        ],
        dtype=np.int32,
    )

    # ---- existing nodes ------------------------------------------------
    E = len(scheduler.existing_nodes)
    p.num_existing = E
    try:
        p.ereq = encode_requirements(
            vocab, [n.requirements for n in scheduler.existing_nodes]
        )
    except UnsupportedProblem as e:
        raise UnsupportedBySolver(str(e)) from e
    try:
        p.eavail = (
            np.stack(
                [table.encode(n.remaining_resources) for n in scheduler.existing_nodes]
            )
            if E
            else np.zeros((0, R), np.int32)
        )
    except UnsupportedProblem as e:
        raise UnsupportedBySolver(str(e)) from e

    # Pad existing-node slots to a pow2 bucket so compiled kernel shapes
    # (and the XLA compile cache) survive cluster growth: a live control
    # plane's node count changes every tick, and exact-E shapes would
    # recompile per solve. Padded slots are inert — eavail=-1 fails every
    # fits check (tpu_kernel cand_e / tpu_runs _pod_units) and
    # encode_pod_classes leaves their toleration rows False.
    E_pad = _pow2(E) if E else 0
    if E_pad > E:
        pad_reqs = empty_reqs(vocab, (E_pad - E,))
        p.ereq = Reqs(
            *(np.concatenate([a, b]) for a, b in zip(p.ereq, pad_reqs))
        )
        p.eavail = np.concatenate(
            [p.eavail, np.full((E_pad - E, R), -1, np.int32)]
        )
        p.num_existing = E_pad

    # ---- topology groups ----------------------------------------------
    filter_sets: list[Requirements] = []

    def encode_filter(tg: TopologyGroup) -> tuple[int, int]:
        nf = tg.node_filter
        _gate(
            nf.taint_policy == NodeInclusionPolicy.HONOR,
            "nodeTaintsPolicy=Honor topology filter",
        )
        if nf.affinity_policy != NodeInclusionPolicy.HONOR or not nf.requirements:
            return (-1, -1)
        # a filter of one empty Requirements matches everything
        alts = [r for r in nf.requirements if len(r) > 0]
        if not alts:
            return (-1, -1)
        _gate(
            len(alts) > MAX_FILTER_ALTERNATIVES,
            "too many topology node-filter alternatives",
        )
        out = []
        for alt in alts:
            _gate(
                alt.has(well_known.HOSTNAME_LABEL_KEY),
                "hostname in topology node filter",
            )
            filter_sets.append(alt)
            out.append(len(filter_sets) - 1)
        while len(out) < MAX_FILTER_ALTERNATIVES:
            out.append(-1)
        return tuple(out)  # type: ignore[return-value]

    # _ordered_groups is the single source of group index order (the class
    # pass built selection rows against the same lists)
    v_tgs, h_tgs, inv_start = _ordered_groups(topo)
    group_vid: dict[int, tuple[str, int]] = {}  # id(tg) -> (family, index)
    for tg in v_tgs:
        kid = vocab.key_index.get(tg.key)
        _gate(kid is None, f"topology key {tg.key!r} has no vocab values")
        _gate(
            tg.type != TopologyType.SPREAD and tg.min_domains is not None,
            "minDomains on non-spread group",
        )
        group_vid[id(tg)] = ("v", len(p.vgroups))
        p.vgroups.append(
            VGroup(
                tg,
                kid,
                _clip_skew(tg.max_skew),
                -1 if tg.min_domains is None else tg.min_domains,
                encode_filter(tg),
            )
        )
    for g, tg in enumerate(h_tgs):
        if g < inv_start:
            group_vid[id(tg)] = ("h", len(p.hgroups))
            p.hgroups.append(
                HGroup(tg, _clip_skew(tg.max_skew), inverse=False, filt=encode_filter(tg))
            )
        else:
            _gate(
                tg.key != well_known.HOSTNAME_LABEL_KEY,
                f"inverse anti-affinity on key {tg.key!r}",
            )
            group_vid[id(tg)] = ("h", len(p.hgroups))
            p.hgroups.append(HGroup(tg, _clip_skew(tg.max_skew), inverse=True))

    Gv, Gh = len(p.vgroups), len(p.hgroups)
    p.vmax = VMAX = max(
        [len(vocab.values[g.kid]) for g in p.vgroups], default=1
    )
    p.v_kid = np.array([g.kid for g in p.vgroups], dtype=np.int32).reshape(Gv)
    p.v_skew = np.array([g.skew for g in p.vgroups], dtype=np.int32).reshape(Gv)
    p.v_mindom = np.array([g.min_domains for g in p.vgroups], dtype=np.int32).reshape(Gv)
    p.v_filt = np.array([g.filt for g in p.vgroups], dtype=np.int32).reshape(Gv, 2)
    p.v_word = np.full((Gv, VMAX), -1, dtype=np.int32)
    p.v_bit = np.zeros((Gv, VMAX), dtype=np.int32)
    p.v_reg = np.zeros((Gv, VMAX), dtype=bool)
    p.v_cnt = np.zeros((Gv, VMAX), dtype=np.int32)
    for g, vg in enumerate(p.vgroups):
        kid = vg.kid
        nvals = len(vocab.values[kid])
        for vid in range(nvals):
            p.v_word[g, vid] = vocab.word_offset[kid] + vid // WORD_BITS
            p.v_bit[g, vid] = vid % WORD_BITS
        for d, c in vg.group.domains.items():
            vid = vocab.value_index[kid].get(d)
            if vid is None:
                raise UnsupportedBySolver(f"domain {d!r} missing from vocab")
            p.v_reg[g, vid] = True
            p.v_cnt[g, vid] = c

    p.h_skew = np.array([g.skew for g in p.hgroups], dtype=np.int32).reshape(Gh)
    p.h_filt = np.array(
        [g.filt for g in p.hgroups], dtype=np.int32
    ).reshape(Gh, 2) if Gh else np.zeros((0, 2), np.int32)
    # the full h_cnt is sized at solve time (needs max_claims); seed counts
    # for existing-node hostnames here
    host_slot = {
        n.view.hostname: e for e, n in enumerate(scheduler.existing_nodes)
    }
    for g, hg in enumerate(p.hgroups):
        for d, c in hg.group.domains.items():
            if c == 0:
                continue
            slot = host_slot.get(d)
            if slot is None:
                # counts on hostnames we don't model (e.g. unmanaged nodes
                # outside the state-node set) can't be attributed to a slot
                raise UnsupportedBySolver(
                    f"hostname domain {d!r} with count outside known nodes"
                )
            p.h_seed.append((g, slot, c))

    try:
        p.filter_reqs = (
            encode_requirements(vocab, filter_sets)
            if filter_sets
            else empty_reqs(vocab, (0,))
        )
    except UnsupportedProblem as e:
        raise UnsupportedBySolver(str(e)) from e

    # ---- pods ----------------------------------------------------------
    _encode_pod_classes(p, pods, group_vid, class_reqs)
    # Best-effort minValues (MinValuesPolicy=BestEffort): the oracle's
    # can_add LOWERS an unsatisfiable floor per add and keeps packing
    # (nodes.py filter_instance_types relax_min_values —
    # scheduling/nodeclaim.go BestEffort), while the kernel's
    # _min_values_ok enforces the encoded floor strictly — a pod the
    # oracle still packs would open a fresh claim on device (found by the
    # differential fuzzer, corpus pin seed8073). Like strict reserved
    # offerings above, the policy's per-add mutation stays on the oracle.
    _gate(
        scheduler.opts.min_values_best_effort
        and bool(
            (p.treq.minv != -1).any()
            or (p.preq_c.minv != -1).any()
            or (p.num_existing and (p.ereq.minv != -1).any())
        ),
        "best-effort minValues policy with minValues floors present",
    )
    # bucket the remaining compiled axes (instance types, offerings) —
    # sentinel invisibility arguments live in solver/buckets.py
    buckets.pad_problem(p)
    return p


def _clip_skew(skew: int) -> int:
    return int(min(skew, (1 << 30)))


def _ordered_groups(topo) -> tuple[list, list, int]:
    """(v_tgs, h_tgs, inv_start): topology groups in the EXACT order the
    encode assigns vgroup/hgroup indices. The class pass (selection rows,
    inverse-anti class splits) and the group-table section both consume
    this — a single definition so they cannot drift."""
    v_tgs = [
        tg
        for tg in topo.topology_groups.values()
        if tg.key != well_known.HOSTNAME_LABEL_KEY
    ]
    h_tgs = [
        tg
        for tg in topo.topology_groups.values()
        if tg.key == well_known.HOSTNAME_LABEL_KEY
    ]
    inv_start = len(h_tgs)
    h_tgs += list(topo.inverse_topology_groups.values())
    return v_tgs, h_tgs, inv_start


def _class_pass(
    p: EncodedProblem, scheduler: Scheduler, pods: list[Pod]
) -> list[Requirements]:
    """The single per-pod Python loop of the encode: class dedup +
    selection rows, before the vocab exists. Everything downstream is per
    class (a few hundred for a 50k-pod batch) or a vectorized broadcast.

    Dedup key: (pod_class_repr bytes, request vector) — bytes cache their
    hash, so the per-pod cost is one cached-hash dict lookup, not a deep
    tuple hash. Inverse-anti selection feeds per-pod FEASIBILITY (kernel
    inv_bad) and ownership feeds in-run budget dynamics, so both split
    classes even though plain selection rows don't (selection rides the
    per-pod srow index instead).

    Returns the per-class Requirements (hostname stripped), reused for
    vocab observation and the class encode so Requirements.from_pod runs
    once per class, not once per pod."""
    topo = scheduler.topology
    v_tgs, h_tgs, inv_start = _ordered_groups(topo)
    inv_tgs = h_tgs[inv_start:]
    Gh = len(h_tgs)

    from karpenter_tpu.solver.ordering import pod_class_repr

    P = len(pods)
    sel_cache: dict[tuple, int] = {}
    rows_v: list[list[bool]] = []
    rows_h: list[list[bool]] = []
    inv_keys: list[tuple] = []  # per srow: inverse-selection tuple
    class_map: dict[tuple, int] = {}
    rkey_map: dict[bytes, int] = {}
    cls = [0] * P
    srow = [0] * P
    reps: list[int] = []
    rcls_of: list[int] = []
    inv_rows: list[tuple] = []  # per class, over inverse groups
    own_rows: list[tuple] = []
    # inverse OWNERSHIP is per-uid: invert the owner sets once instead of
    # scanning every inverse group per pod (the per-pod tuple builds were
    # ~half of encode wall-clock at 50k pods)
    owners_rev: dict[str, tuple[int, ...]] = {}
    if inv_tgs:
        tmp: dict[str, list[int]] = {}
        for k, tg in enumerate(inv_tgs):
            for uid in tg.owners:
                tmp.setdefault(uid, []).append(k)
        owners_rev = {u: tuple(ks) for u, ks in tmp.items()}
    for i, pod in enumerate(pods):
        labels = pod.metadata.labels
        skey = (pod.namespace, tuple(sorted(labels.items())) if labels else ())
        s = sel_cache.get(skey)
        if s is None:
            s = len(rows_v)
            sel_cache[skey] = s
            rows_v.append([tg.selects(pod) for tg in v_tgs])
            hrow = [tg.selects(pod) for tg in h_tgs]
            rows_h.append(hrow)
            # inverse groups act as anti-affinity on any pod they select
            # (topology.go:528) — selection is label-based, so the row is
            # a per-srow fact
            inv_keys.append(tuple(hrow[inv_start:]))
        srow[i] = s
        rkey = pod_class_repr(pod)
        rq = pod.requests
        qkey = tuple(sorted(rq.items())) if rq else ()
        if inv_tgs:
            own_t = owners_rev.get(pod.uid, ())
            key = (rkey, qkey, inv_keys[s], own_t)
        else:
            own_t = ()
            key = (rkey, qkey)
        c = class_map.get(key)
        if c is None:
            c = len(reps)
            class_map[key] = c
            reps.append(i)
            inv_rows.append(inv_keys[s] if inv_tgs else ())
            own_rows.append(own_t)
            rid = rkey_map.get(rkey)
            if rid is None:
                rid = len(p.rclass_creps)
                rkey_map[rkey] = rid
                p.rclass_creps.append(c)
            rcls_of.append(rid)
        cls[i] = c

    NC = len(reps)
    p.pods = pods
    p.pod_class = np.asarray(cls, dtype=np.int32)
    p.srow = np.asarray(srow, dtype=np.int32)
    p.class_reps = reps
    p.rcls_of = np.asarray(rcls_of, dtype=np.int32)
    Gv = len(v_tgs)
    p.sel_rows_v = (
        np.asarray(rows_v, dtype=bool)
        if Gv
        else np.zeros((max(1, len(rows_v)), 0), bool)
    )
    p.sel_rows_h = (
        np.asarray(rows_h, dtype=bool)
        if Gh
        else np.zeros((max(1, len(rows_h)), 0), bool)
    )
    p.pinv_h_c = np.zeros((NC, Gh), dtype=bool)
    p.pown_h_c = np.zeros((NC, Gh), dtype=bool)
    for c in range(NC):
        row = inv_rows[c]
        if row:
            p.pinv_h_c[c, inv_start:] = row
        for k in own_rows[c]:  # owned inverse-group indices
            p.pown_h_c[c, inv_start + k] = True

    # per-class Requirements, shared by vocab observation and encode.
    # PreferencePolicy=Ignore drops preferred terms up front
    # (scheduler.go:74-85; strict_from_pod keeps required_terms[0] only)
    from_pod = (
        Requirements.strict_from_pod
        if scheduler.opts.ignore_preferences
        else Requirements.from_pod
    )
    class_reqs: list[Requirements] = []
    for i in reps:
        reqs = from_pod(pods[i])
        reqs.pop(well_known.HOSTNAME_LABEL_KEY)
        class_reqs.append(reqs)
    return class_reqs


def _encode_pod_classes(
    p: EncodedProblem,
    pods: list[Pod],
    group_vid: dict[int, tuple[str, int]],
    class_reqs: list[Requirements],
) -> None:
    """Per-CLASS tensors (the class pass already ran): requirements,
    requests, tolerations, topology ownership. No [P]-sized array is built
    here — the kernel gathers class rows through pod_class/srow on
    device."""
    vocab, table, scheduler = p.vocab, p.table, p.scheduler
    topo = scheduler.topology
    T, E = p.num_templates, p.num_existing
    reps = p.class_reps
    NC = len(reps)

    prequests_c = np.zeros((NC, table.num_resources), dtype=np.int32)
    for c, i in enumerate(reps):
        prequests_c[c] = table.encode(res.requests_for_pods([pods[i]]))
    try:
        p.preq_c = encode_requirements(vocab, class_reqs)
    except UnsupportedProblem as e:
        raise UnsupportedBySolver(str(e)) from e
    p.prequests_c = prequests_c

    # taint toleration (static per class x template/node)
    tol_cache: dict[tuple, bool] = {}

    def tolerates(taints, pod) -> bool:
        key = (
            tuple((t.key, t.value, t.effect) for t in taints),
            tuple(
                (t.key, t.operator, t.value, t.effect) for t in pod.tolerations
            ),
        )
        got = tol_cache.get(key)
        if got is None:
            got = Taints(taints).tolerates_pod(pod) is None
            tol_cache[key] = got
        return got

    p.ptol_t_c = np.zeros((NC, T), dtype=bool)
    for t, nct in enumerate(scheduler.templates):
        for c, i in enumerate(reps):
            p.ptol_t_c[c, t] = tolerates(nct.taints, pods[i])
    p.ptol_e_c = np.zeros((NC, E), dtype=bool)
    for e, node in enumerate(scheduler.existing_nodes):
        for c, i in enumerate(reps):
            p.ptol_e_c[c, e] = tolerates(node.cached_taints, pods[i])

    # ---- host ports (hostportusage.go:35; round 5) ---------------------
    # universe = every distinct (ip, proto, port) triple observed on pods,
    # template daemonsets, and existing nodes; conflict is a precomputed
    # RELATION over triples (same proto+port, ips equal or either
    # wildcard), so the kernel's screen is one mask AND per candidate
    triples: dict = {}

    def intern(hp):
        got = triples.get(hp)
        if got is None:
            got = len(triples)
            triples[hp] = got
        return got

    class_ports = [get_host_ports(pods[i]) for i in reps]
    for ports in class_ports:
        for hp in ports:
            intern(hp)
    tmpl_ports = []
    for nct in scheduler.templates:
        usage = scheduler.daemon_host_ports.get(nct)
        ports = (
            [hp for plist in usage._by_pod.values() for hp in plist]
            if usage is not None
            else []
        )
        tmpl_ports.append(ports)
        for hp in ports:
            intern(hp)
    node_ports = []
    for node in scheduler.existing_nodes:
        ports = [
            hp for plist in node.host_port_usage._by_pod.values() for hp in plist
        ]
        node_ports.append(ports)
        for hp in ports:
            intern(hp)
    HP = len(triples)
    HPW = (HP + 31) // 32
    p.num_host_ports = HP
    all_triples = list(triples)

    def pack_bits(idxs) -> np.ndarray:
        out = np.zeros(HPW, np.uint32)
        for i in idxs:
            out[i // 32] |= np.uint32(1) << np.uint32(i % 32)
        return out

    from karpenter_tpu.scheduling.hostports import _conflicts

    conflict_of = [
        [u for u, hpu in enumerate(all_triples) if _conflicts(hpt, hpu)]
        for hpt in all_triples
    ]

    def pack_ports(ports) -> tuple[np.ndarray, np.ndarray]:
        idxs = [triples[hp] for hp in ports]
        own = pack_bits(idxs)
        conf = pack_bits([u for i in idxs for u in conflict_of[i]])
        return own, conf

    p.php_own_c = np.zeros((NC, HPW), np.uint32)
    p.php_conf_c = np.zeros((NC, HPW), np.uint32)
    for c, ports in enumerate(class_ports):
        if ports:
            p.php_own_c[c], p.php_conf_c[c] = pack_ports(ports)
    p.thp = np.zeros((T, HPW), np.uint32)
    for t, ports in enumerate(tmpl_ports):
        if ports:
            p.thp[t] = pack_ports(ports)[0]
    p.ehp = np.zeros((E, HPW), np.uint32)
    for e, ports in enumerate(node_ports):
        if ports:
            p.ehp[e] = pack_ports(ports)[0]

    # topology ownership tables (same groups for every pod of a class: the
    # Topology hashes groups by constraint spec, which the class signature
    # covers)
    kind_of = {
        ("v", TopologyType.SPREAD): TOPO_SPREAD_V,
        ("v", TopologyType.POD_AFFINITY): TOPO_AFFINITY_V,
        ("v", TopologyType.POD_ANTI_AFFINITY): TOPO_ANTI_V,
        ("h", TopologyType.SPREAD): TOPO_SPREAD_H,
        ("h", TopologyType.POD_AFFINITY): TOPO_AFFINITY_H,
        ("h", TopologyType.POD_ANTI_AFFINITY): TOPO_ANTI_H,
    }
    owned_by_uid: dict[str, list[TopologyGroup]] = {}
    for tg in topo.topology_groups.values():
        for uid in tg.owners:
            owned_by_uid.setdefault(uid, []).append(tg)
    C = max([len(owned_by_uid.get(pods[i].uid, ())) for i in reps], default=0)
    C = max(1, C)
    _gate(C > MAX_OWNED_TOPOLOGIES, "pod owns too many topology constraints")
    p.ptopo_kind_c = np.zeros((NC, C), dtype=np.int32)
    p.ptopo_gid_c = np.zeros((NC, C), dtype=np.int32)
    p.ptopo_sel_c = np.zeros((NC, C), dtype=bool)
    for c, i in enumerate(reps):
        pod = pods[i]
        s = int(p.srow[i])
        vrow, hrow = p.sel_rows_v[s], p.sel_rows_h[s]
        slot = 0
        for tg in owned_by_uid.get(pod.uid, ()):
            fam, gid = group_vid[id(tg)]
            p.ptopo_kind_c[c, slot] = kind_of[(fam, tg.type)]
            p.ptopo_gid_c[c, slot] = gid
            p.ptopo_sel_c[c, slot] = vrow[gid] if fam == "v" else hrow[gid]
            slot += 1

    # ---- relaxation tier tables (per relaxable requirement class) ------
    # tier 0 = the pod as submitted; tier t = after t effective relax
    # rungs (encode_problem walked the ladder pre-finalize and observed
    # every tier's requirement values). Tiers repeat their last row up to
    # L — the kernel's tier loop stops at ntiers, padding is unreachable.
    ladders = getattr(p, "_ladders", [])
    NR = len(p.rclass_creps)
    p.ntiers_r = np.ones(NR, np.int32)
    p.rrow_of_rcls = np.zeros(NR, np.int32)
    relax_rows: list[tuple[int, list]] = []
    for rid, ladder in enumerate(ladders):
        if ladder is None:
            continue
        p.ntiers_r[rid] = len(ladder)
        p.rrow_of_rcls[rid] = len(relax_rows)
        relax_rows.append((rid, ladder))
    NRx = len(relax_rows)
    L = max((len(ladder) for _, ladder in relax_rows), default=1)
    p.num_tiers = L
    if NRx:
        # inverse-anti rows are tier-INDEPENDENT by construction: inverse
        # group OWNERSHIP comes from required anti terms only
        # (topology.py _update_inverse_anti_affinity — required anti never
        # relaxes), and inverse SELECTION is label-based — so the class
        # rows pinv_h_c/pown_h_c stay correct at every tier
        p.rt_tol_t = np.zeros((NRx, L, T), bool)
        p.rt_tol_e = np.zeros((NRx, L, E), bool)
        p.rt_kind = np.zeros((NRx, L, C), np.int32)
        p.rt_gid = np.zeros((NRx, L, C), np.int32)
        p.rt_sel = np.zeros((NRx, L, C), bool)
        reqs_flat: list[Requirements] = []
        for x_i, (rid, ladder) in enumerate(relax_rows):
            rep_i = reps[p.rclass_creps[rid]]
            s = int(p.srow[rep_i])
            vrow, hrow = p.sel_rows_v[s], p.sel_rows_h[s]
            tier_reqs = []
            for t_i in range(L):
                tp, reqs = ladder[min(t_i, len(ladder) - 1)]
                tier_reqs.append(reqs)
                reqs_flat.append(reqs)
                for t, nct in enumerate(scheduler.templates):
                    p.rt_tol_t[x_i, t_i, t] = tolerates(nct.taints, tp)
                for e, node in enumerate(scheduler.existing_nodes):
                    p.rt_tol_e[x_i, t_i, e] = tolerates(node.cached_taints, tp)
                groups = topo._new_for_topologies(tp) + topo._new_for_affinities(tp)
                _gate(len(groups) > C, "tier owns too many topology constraints")
                slot = 0
                for tg_new in groups:
                    tg = topo.topology_groups.get(tg_new.hash_key())
                    if tg is None or id(tg) not in group_vid:
                        raise UnsupportedBySolver(
                            "relaxation tier topology group missing from encode"
                        )
                    fam, gid = group_vid[id(tg)]
                    p.rt_kind[x_i, t_i, slot] = kind_of[(fam, tg.type)]
                    p.rt_gid[x_i, t_i, slot] = gid
                    p.rt_sel[x_i, t_i, slot] = (
                        vrow[gid] if fam == "v" else hrow[gid]
                    )
                    slot += 1
            p.rt_tier_reqs.append(tier_reqs)
        try:
            flat = encode_requirements(vocab, reqs_flat)
        except UnsupportedProblem as e:
            raise UnsupportedBySolver(str(e)) from e
        p.rt_preq = Reqs(
            *(a.reshape((NRx, L) + a.shape[1:]) for a in flat)
        )
    else:
        # uniform shapes for Tables even with nothing to relax; the tier
        # branch is unreachable (every pod has ntiers == 1)
        p.rt_preq = empty_reqs(vocab, (1, 1))
        p.rt_tol_t = np.zeros((1, 1, T), bool)
        p.rt_tol_e = np.zeros((1, 1, E), bool)
        p.rt_kind = np.zeros((1, 1, C), np.int32)
        p.rt_gid = np.zeros((1, 1, C), np.int32)
        p.rt_sel = np.zeros((1, 1, C), bool)


# ---------------------------------------------------------------------------
# batched-sweep hooks (controllers/disruption/{sweep,setsweep}.py)
#
# The delta-state consolidation kernels treat FFD of a class-grouped pod
# sequence as one masked cumsum per encode class. That identity needs two
# host-side ingredients this module owns (they are properties of the
# ENCODING, not of the disruption controller): the contiguity of classes
# in the shared FFD order, and the per-group class-count matrix every
# batching scheme derives its per-lane valid-pod counts from.


def contiguous_class_seq(ordered_cls: np.ndarray):
    """Distinct encode classes in first-appearance order IF every class is
    one contiguous run of `ordered_cls` (the pod classes permuted into the
    shared FFD order, ordering.ffd_sort_key); None otherwise.

    The delta-state sweep kernels replace the per-pod FFD scan with one
    cumsum per class, which is only exact when the oracle would also place
    each class's pods consecutively — a signature collision that
    interleaves two classes in FFD order voids the identity."""
    ordered_cls = np.asarray(ordered_cls)
    if len(ordered_cls) == 0:
        return np.zeros(0, dtype=np.int64)
    change = np.flatnonzero(np.diff(ordered_cls))
    class_seq = ordered_cls[np.r_[0, change + 1]]
    if len(set(class_seq.tolist())) != len(class_seq):
        return None
    return class_seq


def group_class_counts(
    ordered_cls: np.ndarray,
    class_seq: np.ndarray,
    group: np.ndarray,
    n_groups: int,
) -> tuple[np.ndarray, np.ndarray]:
    """(base[C], M[n_groups, C]) int64 pod counts per (group,
    class-position) over a class-contiguous FFD order; group[i] < 0
    accumulates into `base` (pods valid in every lane, e.g. pending pods
    in a consolidation sweep). Groups with no pods keep zero rows.

    This is THE batching hook behind the removal-set subsystem: a lane
    with membership row m over the groups sees base + m @ M valid pods per
    class (setsweep.py, a device matmul), and the prefix sweep's per-lane
    counts are base + cumsum(M, axis=0) (sweep.py) — the lower-triangular
    special case of the same matrix. Counts stay int64 on the host; the
    callers own the documented int32 guards before any device cast."""
    ordered_cls = np.asarray(ordered_cls)
    group = np.asarray(group)
    C = len(class_seq)
    pos_of_class = {int(c): i for i, c in enumerate(class_seq)}
    base = np.zeros(C, np.int64)
    M = np.zeros((n_groups, C), np.int64)
    for g, c in zip(group, ordered_cls):
        cpos = pos_of_class[int(c)]
        if g < 0:
            base[cpos] += 1
        else:
            M[int(g), cpos] += 1
    return base, M
