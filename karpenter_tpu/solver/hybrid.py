"""HybridScheduler: the Solver dispatch — TPU first, oracle fallback.

This is the single entry point callers (provisioner, disruption simulation,
benchmarks) use. It mirrors the reference's Scheduler.Solve surface
(/root/reference/pkg/controllers/provisioning/scheduling/scheduler.go:377)
while routing the computation:

- The TPU path (karpenter_tpu.solver.tpu.TpuScheduler) encodes the problem
  into dense tensors and packs pods in a jitted scan. Problems outside the
  tensor encoding raise UnsupportedBySolver *at encode time*, before any
  state is mutated.
- On UnsupportedBySolver the dispatch falls back to the sequential oracle
  (karpenter_tpu.solver.oracle.Scheduler) — the same object the TpuScheduler
  derived its encoding from, still pristine because encode_problem only
  reads it. Callers therefore never see UnsupportedBySolver.

The fallback taxonomy (what routes to the oracle) is documented in
tpu_problem.pod_unsupported_reason: host ports, volume claims, hostname
requirements, over-long relaxation ladders — plus the whole-problem gates
(reserved capacity). Preference relaxation rides the kernel since round 4.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.api.objects import NodePool, Pod
from karpenter_tpu.cloudprovider.types import InstanceTypes
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.solver.oracle import Results, Scheduler, SchedulerOptions
from karpenter_tpu.solver.topology import Topology
from karpenter_tpu.solver.tpu import TpuScheduler
from karpenter_tpu.solver.tpu_problem import UnsupportedBySolver


class HybridScheduler:
    """Same constructor and solve() surface as oracle.Scheduler.

    After solve():
    - ``used_tpu`` is True when the TPU path produced the result;
    - ``fallback_reason`` holds the UnsupportedBySolver message when the
      oracle ran instead (None on the TPU path).
    """

    def __init__(
        self,
        node_pools: list[NodePool],
        instance_types_by_pool: dict[str, InstanceTypes],
        topology: Topology,
        state_nodes: Optional[list[StateNodeView]] = None,
        daemonset_pods: Optional[list[Pod]] = None,
        options: Optional[SchedulerOptions] = None,
        force_oracle: bool = False,
    ):
        self.force_oracle = force_oracle
        self.used_tpu: Optional[bool] = None
        self.fallback_reason: Optional[str] = None
        if force_oracle:
            self.tpu: Optional[TpuScheduler] = None
            self.oracle = Scheduler(
                node_pools,
                instance_types_by_pool,
                topology,
                state_nodes,
                daemonset_pods,
                options,
            )
        else:
            self.tpu = TpuScheduler(
                node_pools,
                instance_types_by_pool,
                topology,
                state_nodes,
                daemonset_pods,
                options,
            )
            self.oracle = self.tpu.oracle
        self.opts = self.oracle.opts

    def solve(self, pods: list[Pod]) -> Results:
        """Never raises UnsupportedBySolver.

        Per-pod partitioning (the round-2 "fallback cliff" fix): pods the
        tensor encoding supports ride the kernel; the remainder (relaxable
        preferences, ScheduleAnyway, host ports, volumes, hostname
        selectors) then run through the oracle AGAINST THE KERNEL'S
        RESULTING STATE — the decode writes claims/existing usage back onto
        the shared oracle and syncs the Topology's domain counts from the
        device, so the continuation packs into the same cluster picture.
        One odd pod no longer drags a 10k-pod batch onto the oracle.
        """
        if self.tpu is None:
            self.used_tpu = False
            return self.oracle.solve(pods)

        # Size-based routing (VERDICT r3 weak #2): below the measured
        # crossover a topology-free batch solves faster on the oracle than
        # the device launch/tunnel floor — see SchedulerOptions.tpu_min_pods
        # for the measurement. Topology-bearing problems always ride the
        # kernel (the oracle's domain tracking is the slow part there).
        topo = self.oracle.topology
        if (
            self.opts.tpu_min_pods
            and len(pods) < self.opts.tpu_min_pods
            and not topo.topology_groups
            and not topo.inverse_topology_groups
        ):
            self.used_tpu = False
            self.fallback_reason = (
                f"small topology-free batch ({len(pods)} pods < crossover "
                f"{self.opts.tpu_min_pods}) routed to oracle"
            )
            return self.oracle.solve(pods)

        from karpenter_tpu.solver.tpu_problem import pod_unsupported_reason

        ignore = self.opts.ignore_preferences
        reasons = [pod_unsupported_reason(p, ignore) for p in pods]
        supported = [p for p, r in zip(pods, reasons) if r is None]
        unsupported = [p for p, r in zip(pods, reasons) if r is not None]
        first_reason = next((r for r in reasons if r is not None), None)
        # nodepool-limit spend syncs back from the device after decode
        # (tpu.py _decode), so the oracle continuation sees the kernel's
        # accounting — partitioning is safe with limits set
        can_partition = bool(supported and unsupported)
        if unsupported and not can_partition:
            self.used_tpu = False
            self.fallback_reason = first_reason
            return self.oracle.solve(pods)
        try:
            results = self.tpu.solve(supported)
        except UnsupportedBySolver as e:
            # encode_problem raises before mutating the oracle or the
            # shared Topology, so the oracle can run on the same state
            self.fallback_reason = str(e)
            self.used_tpu = False
            return self.oracle.solve(pods)
        self.used_tpu = True
        self.fallback_reason = None
        if not unsupported:
            return results
        # continuation: the oracle packs the leftovers into the decoded
        # claims/existing nodes (state and topology already synced)
        self.fallback_reason = (
            f"{len(unsupported)} pod(s) continued on the oracle: {first_reason}"
        )
        cont = self.oracle.solve(unsupported)
        cont.pod_errors.update(results.pod_errors)
        cont.timed_out = cont.timed_out or results.timed_out
        return cont
