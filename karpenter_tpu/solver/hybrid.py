"""HybridScheduler: the Solver dispatch — TPU first, oracle fallback.

This is the single entry point callers (provisioner, disruption simulation,
benchmarks) use. It mirrors the reference's Scheduler.Solve surface
(/root/reference/pkg/controllers/provisioning/scheduling/scheduler.go:377)
while routing the computation:

- The TPU path (karpenter_tpu.solver.tpu.TpuScheduler) encodes the problem
  into dense tensors and packs pods in a jitted scan. Problems outside the
  tensor encoding raise UnsupportedBySolver *at encode time*, before any
  state is mutated.
- On UnsupportedBySolver the dispatch falls back to the sequential oracle
  (karpenter_tpu.solver.oracle.Scheduler) — the same object the TpuScheduler
  derived its encoding from, still pristine because encode_problem only
  reads it. Callers therefore never see UnsupportedBySolver.

The fallback taxonomy (what routes to the oracle) is documented in
tpu_problem.pod_unsupported_reason: host ports, volume claims, hostname
requirements, over-long relaxation ladders — plus the whole-problem gates
(reserved capacity). Preference relaxation rides the kernel since round 4.

Since the fault-tolerance PR this module also carries the top of the
failure ladder (docs/resilience.md):

    sidecar solve -> [breaker] -> in-process TPU -> [guard] -> oracle

- ResilientSolver wraps the sidecar boundary (solver/service.py) with a
  circuit breaker: after `failure_threshold` consecutive sidecar failures
  the breaker opens and solves run in-process for `cooldown_seconds`,
  then a half-open probe decides whether to close again. Breaker state
  and every fallback are recorded through karpenter_tpu.metrics.
- HybridScheduler.solve gains a last-resort guard: an UNEXPECTED error on
  the TPU path (anything beyond the typed UnsupportedBySolver taxonomy)
  degrades to a pristine oracle solve — fresh Topology, fresh Scheduler,
  untouched by whatever half-mutated state the failed kernel attempt left
  behind — instead of propagating out of the reconcile loop.
"""

from __future__ import annotations

import copy
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu import logging as klog
from karpenter_tpu import metrics
from karpenter_tpu.analysis import protorec
from karpenter_tpu.api.objects import NodePool, Pod
from karpenter_tpu.cloudprovider.types import InstanceTypes
from karpenter_tpu.solver.epochs import SolverOverloaded
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.solver.oracle import Results, Scheduler, SchedulerOptions
from karpenter_tpu.solver.topology import ClusterSource, Topology
from karpenter_tpu.solver.tpu import TpuScheduler
from karpenter_tpu.solver.tpu_problem import UnsupportedBySolver

# -- resilience metrics (reference pkg/metrics idiom) -------------------------

SOLVER_FALLBACK = metrics.REGISTRY.counter(
    "karpenter_solver_fallback_total",
    "Solves that degraded down the failure ladder, by reason.",
    ("reason",),
)
SIDECAR_REQUESTS = metrics.REGISTRY.counter(
    "karpenter_solver_sidecar_requests_total",
    "Sidecar solve attempts, by outcome.",
    ("outcome",),
)
BREAKER_STATE = metrics.REGISTRY.gauge(
    "karpenter_solver_breaker_state",
    "Sidecar circuit-breaker state (0 closed, 1 half-open, 2 open).",
    ("breaker",),
)

_BREAKER_STATE_CODES = {"closed": 0.0, "half-open": 1.0, "open": 2.0}

# Slack added on top of the server-side solve budget when deriving the
# client's wire deadline: covers serialization + transfer + scheduling
# jitter so a solve using its FULL budget still answers in time.
SOLVE_DEADLINE_GRACE_SECONDS = 15.0

_log = klog.root.named("solver")


class HybridScheduler:
    """Same constructor and solve() surface as oracle.Scheduler.

    After solve():
    - ``used_tpu`` is True when the TPU path produced the result;
    - ``fallback_reason`` holds the UnsupportedBySolver message when the
      oracle ran instead (None on the TPU path).
    """

    def __init__(
        self,
        node_pools: list[NodePool],
        instance_types_by_pool: dict[str, InstanceTypes],
        topology: Topology,
        state_nodes: Optional[list[StateNodeView]] = None,
        daemonset_pods: Optional[list[Pod]] = None,
        options: Optional[SchedulerOptions] = None,
        force_oracle: bool = False,
        table_cache=None,
        fleet=None,
        epoch_key=None,
    ):
        self.force_oracle = force_oracle
        self.used_tpu: Optional[bool] = None
        self.fallback_reason: Optional[str] = None
        # kept for the last-resort guard: a pristine oracle re-solve needs
        # the raw inputs, not the possibly half-mutated shared state
        self._node_pools = node_pools
        self._its_by_pool = instance_types_by_pool
        self._state_nodes = state_nodes
        self._daemonset_pods = daemonset_pods
        self._topology = topology
        if force_oracle:
            self.tpu: Optional[TpuScheduler] = None
            self.oracle = Scheduler(
                node_pools,
                instance_types_by_pool,
                topology,
                state_nodes,
                daemonset_pods,
                options,
            )
        else:
            self.tpu = TpuScheduler(
                node_pools,
                instance_types_by_pool,
                topology,
                state_nodes,
                daemonset_pods,
                options,
                # epochs.DeviceTableCache (optional): a repeat solve of an
                # identical table encoding skips the per-class uploads
                table_cache=table_cache,
                # fleet.FleetCoalescer (optional): scan-path solves join
                # the server's batch window and share vmapped dispatches
                fleet=fleet,
                # (client, epoch id) when the request was materialized
                # from a resident epoch — rides the fleet window event
                epoch_key=epoch_key,
            )
            self.oracle = self.tpu.oracle
        self.opts = self.oracle.opts

    def solve(self, pods: list[Pod], trace=None) -> Results:
        """Never raises UnsupportedBySolver.

        Per-pod partitioning (the round-2 "fallback cliff" fix): pods the
        tensor encoding supports ride the kernel; the remainder (relaxable
        preferences, ScheduleAnyway, host ports, volumes, hostname
        selectors) then run through the oracle AGAINST THE KERNEL'S
        RESULTING STATE — the decode writes claims/existing usage back onto
        the shared oracle and syncs the Topology's domain counts from the
        device, so the continuation packs into the same cluster picture.
        One odd pod no longer drags a 10k-pod batch onto the oracle.

        `trace` (tracing.Trace, optional) records the routing decision
        and oracle-fallback reasons as spans; threaded down into the
        kernel driver's host phases. A standalone call owns its own
        trace so every solve lands in the ring and the phase metrics.
        """
        from karpenter_tpu import tracing

        with tracing.maybe_trace(trace, "solve") as tr:
            results = self._solve_traced(pods, tr)
            tr.annotate(used_tpu=self.used_tpu)
            return results

    def _solve_traced(self, pods: list[Pod], tr) -> Results:
        from karpenter_tpu import tracing

        if self.tpu is None:
            self.used_tpu = False
            # a degrade decision made ABOVE this scheduler (the sidecar's
            # mid-prewarm force_oracle) already recorded its reason on
            # this trace; recording "forced" again would double-count the
            # same solve in the per-reason fallback totals
            if not any(s.name == "oracle_fallback" for s in tr.spans):
                tracing.record_fallback(tr, "forced", "force_oracle scheduler")
            with tr.span("oracle", pods=len(pods)):
                return self.oracle.solve(pods)

        # Size-based routing (VERDICT r3 weak #2): below the measured
        # crossover a topology-free batch solves faster on the oracle than
        # the device launch/tunnel floor — see SchedulerOptions.tpu_min_pods
        # for the measurement. Topology-bearing problems always ride the
        # kernel (the oracle's domain tracking is the slow part there).
        topo = self.oracle.topology
        if (
            self.opts.tpu_min_pods
            and len(pods) < self.opts.tpu_min_pods
            and not topo.topology_groups
            and not topo.inverse_topology_groups
        ):
            self.used_tpu = False
            self.fallback_reason = (
                f"small topology-free batch ({len(pods)} pods < crossover "
                f"{self.opts.tpu_min_pods}) routed to oracle"
            )
            tracing.record_fallback(tr, "small_batch", self.fallback_reason)
            with tr.span("oracle", pods=len(pods)):
                return self.oracle.solve(pods)

        from karpenter_tpu.solver.tpu_problem import pod_unsupported_reason

        ignore = self.opts.ignore_preferences
        reasons = [pod_unsupported_reason(p, ignore) for p in pods]
        supported = [p for p, r in zip(pods, reasons) if r is None]
        unsupported = [p for p, r in zip(pods, reasons) if r is not None]
        first_reason = next((r for r in reasons if r is not None), None)
        # nodepool-limit spend syncs back from the device after decode
        # (tpu.py _decode), so the oracle continuation sees the kernel's
        # accounting — partitioning is safe with limits set
        can_partition = bool(supported and unsupported)
        if unsupported and not can_partition:
            self.used_tpu = False
            self.fallback_reason = first_reason
            tracing.record_fallback(tr, "unsupported", first_reason or "")
            with tr.span("oracle", pods=len(pods)):
                return self.oracle.solve(pods)
        try:
            results = self.tpu.solve(supported, trace=tr)
        except UnsupportedBySolver as e:
            # encode_problem raises before mutating the oracle or the
            # shared Topology, so the oracle can run on the same state
            self.fallback_reason = str(e)
            self.used_tpu = False
            tracing.record_fallback(tr, "unsupported", str(e))
            with tr.span("oracle", pods=len(pods)):
                return self.oracle.solve(pods)
        except Exception as e:
            # Last-resort guard (ISSUE: no unexpected TPU-path error may
            # propagate out of the reconcile loop). Unlike the typed
            # UnsupportedBySolver — which is raised before any mutation —
            # an arbitrary failure may have left the shared oracle/topology
            # half-written, so degrade onto PRISTINE state.
            self.used_tpu = False
            self.fallback_reason = (
                f"unexpected TPU-path error, degraded to oracle: "
                f"{type(e).__name__}: {e}"
            )
            SOLVER_FALLBACK.inc({"reason": "tpu_error"})
            tracing.record_fallback(tr, "tpu_error", self.fallback_reason)
            _log.error(
                "TPU path raised unexpectedly; re-solving on a pristine oracle",
                error=f"{type(e).__name__}: {e}",
                pods=len(pods),
            )
            with tr.span("oracle", pods=len(pods)):
                return self._pristine_oracle_solve(pods)
        self.used_tpu = True
        self.fallback_reason = None
        if not unsupported:
            return results
        # continuation: the oracle packs the leftovers into the decoded
        # claims/existing nodes (state and topology already synced)
        self.fallback_reason = (
            f"{len(unsupported)} pod(s) continued on the oracle: {first_reason}"
        )
        tracing.record_fallback(
            tr, "partition_continuation", self.fallback_reason
        )
        with tr.span("oracle", pods=len(unsupported)):
            cont = self.oracle.solve(unsupported)
        cont.pod_errors.update(results.pod_errors)
        cont.timed_out = cont.timed_out or results.timed_out
        return cont

    def _pristine_oracle_solve(self, pods: list[Pod]) -> Results:
        """Rebuild Topology + Scheduler from the stored constructor inputs
        and solve the FULL pod set. The failed kernel attempt may have
        synced partial claims/domain counts onto the shared oracle; reusing
        it would double-count. StateNodeViews are read-only to the solve,
        so they can be shared with the fresh scheduler."""
        topology = Topology(
            self._node_pools,
            self._its_by_pool,
            pods,
            cluster=self._topology.cluster,
            state_node_views=self._state_nodes,
            ignore_preferences=self.opts.ignore_preferences,
        )
        oracle = Scheduler(
            self._node_pools,
            self._its_by_pool,
            topology,
            self._state_nodes,
            self._daemonset_pods,
            self.opts,
        )
        self.oracle = oracle  # callers introspect post-solve state here
        return oracle.solve(pods)


def solve_in_process(
    node_pools: list[NodePool],
    instance_types_by_pool: dict[str, InstanceTypes],
    pods: list[Pod],
    state_node_views: Optional[list[StateNodeView]] = None,
    daemonset_pods: Optional[list[Pod]] = None,
    options: Optional[SchedulerOptions] = None,
    cluster: Optional[ClusterSource] = None,
    force_oracle: bool = False,
    trace=None,
    table_cache=None,
    fleet=None,
    epoch_key=None,
) -> tuple[Results, HybridScheduler]:
    """THE in-process solve assembly: Topology + HybridScheduler, options
    threaded consistently. Every path that solves locally — the
    provisioning controller, the sidecar server, ResilientSolver's
    fallback — goes through here, so the three can never diverge on how
    ignore_preferences / cluster state / views reach the scheduler.
    `trace` (tracing.Trace) joins the caller's solve trace; a standalone
    call owns a local one. `table_cache` (epochs.DeviceTableCache,
    optional — the sidecar server passes its own) lets repeat solves of
    an unchanged table encoding skip the per-class device uploads;
    `fleet` (fleet.FleetCoalescer, optional — likewise server-owned)
    lets concurrent scan-path solves share one vmapped dispatch."""
    from karpenter_tpu import tracing

    with tracing.maybe_trace(trace, "solve") as tr:
        with tr.span("topology", pods=len(pods)):
            topology = Topology(
                node_pools,
                instance_types_by_pool,
                pods,
                cluster=cluster or ClusterSource(),
                state_node_views=state_node_views,
                ignore_preferences=bool(options and options.ignore_preferences),
            )
        scheduler = HybridScheduler(
            node_pools,
            instance_types_by_pool,
            topology,
            state_node_views,
            daemonset_pods,
            options,
            force_oracle=force_oracle,
            table_cache=table_cache,
            fleet=fleet,
            epoch_key=epoch_key,
        )
        return scheduler.solve(pods, trace=tr), scheduler


# ---------------------------------------------------------------------------
# the resilient service boundary (ISSUE: fault-tolerant solver service)


class CircuitBreaker:
    """Consecutive-failure circuit breaker for the sidecar boundary.

    closed -> (failure_threshold consecutive failures) -> open
    open   -> (cooldown_seconds elapse)                -> half-open
    half-open: one probe rides the sidecar; success -> closed,
               failure -> open again (fresh cooldown).

    `clock` is a zero-arg seconds source (time.monotonic by default;
    tests pass FakeClock.now so cooldowns ride simulated time). `name`
    labels this instance's gauge series — two live breakers (a drained
    control plane overlapping its successor) must not overwrite each
    other's exported state.

    Thread safety: the breaker is driven from every concurrent request
    path (SolverServer handler threads, worker-pool reconciles all
    funnel through ResilientSolver.solve), so the trip/reclose state
    machine and the failure counter mutate under one small lock —
    without it, `consecutive_failures += 1` is a lost-update race, and
    the open->half-open transition in allow() could not be made a
    single-winner decision (the lock is what lets exactly ONE racing
    caller claim the half-open probe; everyone else keeps cooling down
    in-process). Same shape as the PR 2 metrics Store/Registry fix."""

    def __init__(
        self,
        failure_threshold: int = 3,
        cooldown_seconds: float = 30.0,
        clock=None,
        name: str = "sidecar",
    ):
        self.failure_threshold = failure_threshold
        self.cooldown_seconds = cooldown_seconds
        self._clock = clock or time.monotonic
        self.name = name
        self._lock = threading.Lock()
        self.state = "closed"
        self.consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probe_at: Optional[float] = None
        # nothing races __init__, but *_locked means caller-holds — the
        # convention stays checkable only if every call site honors it
        with self._lock:
            self._publish_locked()

    def _publish_locked(self) -> None:
        BREAKER_STATE.set(
            _BREAKER_STATE_CODES[self.state], {"breaker": self.name}
        )

    def _record_locked(self, ev: str, **fields) -> None:
        """Protocol-tier conformance event, emitted under the breaker
        lock so the recorded transition order IS the real one (the
        refinement acceptor in analysis/proto.py checks each event's
        pre/post state legality; analysis/protorec.py docstring covers
        the free-when-off contract)."""
        if protorec.RECORDER is not None:
            protorec.RECORDER.record(
                ev=ev,
                state=self.state,
                failures=self.consecutive_failures,
                threshold=self.failure_threshold,
                **fields,
            )

    def allow(self) -> bool:
        """May the next solve attempt the sidecar? Half-open admits ONE
        probe: the open->half-open transition returns True exactly once
        under the lock; callers racing in behind it see half-open and go
        straight in-process until the probe's record_success/
        record_failure resolves the state. A probe that never reports
        back (its thread killed by BaseException between allow() and
        record_*) must not wedge the breaker refusing the sidecar
        forever: after a full cooldown with no verdict, half-open
        re-admits a fresh probe."""
        now = self._clock()
        with self._lock:
            if self.state == "closed":
                self._record_locked("breaker_allow", granted=True, probe=False)
                return True
            if self.state == "half-open":
                if now - self._probe_at >= self.cooldown_seconds:
                    self._probe_at = now  # lost probe; this caller takes over
                    self._record_locked(
                        "breaker_allow", granted=True, probe=True, takeover=True
                    )
                    return True
                self._record_locked("breaker_allow", granted=False, probe=False)
                return False  # a probe is already in flight
            if now - self._opened_at >= self.cooldown_seconds:
                self.state = "half-open"
                self._probe_at = now
                self._publish_locked()
                self._record_locked(
                    "breaker_allow", granted=True, probe=True, takeover=False
                )
                return True
            self._record_locked("breaker_allow", granted=False, probe=False)
            return False

    def record_success(self) -> None:
        with self._lock:
            prev = self.state
            self.state = "closed"
            self.consecutive_failures = 0
            self._opened_at = None
            self._publish_locked()
            self._record_locked("breaker_success", prev=prev)

    def record_failure(self) -> None:
        with self._lock:
            prev = self.state
            self.consecutive_failures += 1
            if (
                self.state == "half-open"
                or self.consecutive_failures >= self.failure_threshold
            ):
                self.state = "open"
                self._opened_at = self._clock()
            self._publish_locked()
            self._record_locked("breaker_failure", prev=prev)


class RemoteNodeClaim:
    """A new-node decision reconstructed from the wire (service.py RESULT
    frame). Duck-types the slice of SchedulingNodeClaim the provisioning
    controller consumes: .pods, .nodepool_name, .requests, .to_node_claim().
    The launchable NodeClaim itself crossed the wire fully formed — no
    template state is re-derived client-side."""

    def __init__(self, nodepool_name: str, node_claim, requests, pods: list[Pod]):
        self.nodepool_name = nodepool_name
        self._node_claim = node_claim
        self.requests = dict(requests)
        self.pods = pods

    def to_node_claim(self):
        return copy.deepcopy(self._node_claim)


@dataclass
class RemoteExistingNode:
    """An existing-capacity placement reconstructed from the wire; only
    .name and .pods are consumed control-plane side (_bind_to_existing)."""

    name: str
    pods: list[Pod] = field(default_factory=list)


class ResilientSolver:
    """The fault-tolerant entry point the provisioning controller calls
    when a sidecar is configured: try the remote solver under the circuit
    breaker, degrade to the in-process HybridScheduler (which itself
    degrades TPU -> oracle) on ANY sidecar-side failure — a killed sidecar
    can never stall a reconcile (chaos_test.go:48-90 expects convergence
    under exactly this churn).

    After solve():
    - ``last_used``       'sidecar' | 'tpu' | 'oracle'
    - ``fallback_reason`` why the sidecar was skipped/failed (None when the
                          sidecar answered)
    """

    def __init__(
        self,
        socket_path: Optional[str] = None,
        client=None,
        failure_threshold: int = 3,
        cooldown_seconds: float = 30.0,
        request_timeout_seconds: float = 30.0,
        clock=None,
        breaker: Optional[CircuitBreaker] = None,
    ):
        if client is None:
            # lazy import: service.py imports HybridScheduler from here
            from karpenter_tpu.solver.service import SolverClient

            client = SolverClient(socket_path, request_timeout=request_timeout_seconds)
        self.client = client
        self.request_timeout_seconds = request_timeout_seconds
        self._clock = clock or time.monotonic
        self.breaker = breaker or CircuitBreaker(
            failure_threshold, cooldown_seconds, clock=clock
        )
        # admission backpressure (service RETRY frames): the sidecar is
        # healthy but shedding, so the hint gates re-dialing WITHOUT
        # feeding the breaker — an overloaded server must not be scored
        # like a dead one (docs/resilience.md)
        self._admission_retry_at = 0.0
        self.last_used: Optional[str] = None
        self.fallback_reason: Optional[str] = None
        self.log = klog.root.named("solver.resilient")

    def solve(
        self,
        node_pools: list[NodePool],
        instance_types_by_pool: dict[str, InstanceTypes],
        pods: list[Pod],
        state_node_views: Optional[list[StateNodeView]] = None,
        daemonset_pods: Optional[list[Pod]] = None,
        options: Optional[SchedulerOptions] = None,
        cluster: Optional[ClusterSource] = None,
        namespace_labels: Optional[dict] = None,
        force_oracle: bool = False,
        trace=None,
    ) -> Results:
        """Never raises for solver-side faults; the in-process ladder is
        always available as the floor. `trace` (tracing.Trace) is the
        provisioning round's trace; sidecar attempts record their span on
        it and the wire client stamps its correlation id as the trace id,
        joining the client- and server-side spans into one trace."""
        from karpenter_tpu import tracing

        with tracing.maybe_trace(trace, "resilient_solve") as tr:
            return self._solve_traced(
                node_pools, instance_types_by_pool, pods, state_node_views,
                daemonset_pods, options, cluster, namespace_labels,
                force_oracle, tr,
            )

    def _record_attempt(self, outcome: str) -> None:
        """Protocol-tier conformance event: one per solve attempt, AFTER
        the breaker verdict that resolves it — the refinement acceptor
        requires e.g. an `overloaded` outcome to carry a breaker_success
        on the same thread (the RETRY-records-success rule this module's
        admission-rejection branch pins)."""
        if protorec.RECORDER is not None:
            protorec.RECORDER.record(
                ev="attempt", outcome=outcome, breaker=self.breaker.state
            )

    def _solve_traced(
        self,
        node_pools,
        instance_types_by_pool,
        pods,
        state_node_views,
        daemonset_pods,
        options,
        cluster,
        namespace_labels,
        force_oracle,
        tr,
    ) -> Results:
        if namespace_labels is None and cluster is not None:
            namespace_labels = cluster.namespace_labels
        # The wire deadline must COVER the server-side solve budget: a solve
        # legitimately using its full timeout_seconds (which would at worst
        # return partial results with timed_out=True) must not be cut off
        # client-side, poisoning the connection and feeding the breaker.
        # request_timeout_seconds is the floor, for transport-level stalls.
        wire_timeout = self.request_timeout_seconds
        if options is not None and options.timeout_seconds:
            wire_timeout = max(
                wire_timeout, options.timeout_seconds + SOLVE_DEADLINE_GRACE_SECONDS
            )
        # backoff is checked BEFORE breaker.allow(): allow() claims the
        # half-open probe slot as a side effect, and a caller that then
        # skips the sidecar for admission backoff would strand the probe
        # until the lost-probe cooldown recovers it
        in_backoff = self._clock() < self._admission_retry_at
        if not in_backoff and self.breaker.allow():
            try:
                with tr.span("sidecar", pods=len(pods)):
                    decoded = self.client.solve(
                        node_pools,
                        instance_types_by_pool,
                        pods,
                        state_node_views,
                        daemonset_pods,
                        options,
                        force_oracle,
                        namespace_labels,
                        timeout=wire_timeout,
                        # the FULL cluster slice (scheduled pods, node
                        # labels) crosses the wire: the sidecar must count
                        # existing anti-affinity/spread state exactly like
                        # in-process
                        cluster=cluster,
                        trace=tr,
                    )
                self.breaker.record_success()
                self._record_attempt("success")
                SIDECAR_REQUESTS.inc({"outcome": "success"})
                self.last_used = "sidecar"
                self.fallback_reason = None
                tr.annotate(solver="sidecar")
                return self._to_results(decoded, pods)
            except SolverOverloaded as e:
                # backpressure, NOT a fault: the server answered a RETRY
                # frame because its solve budget is oversubscribed. The
                # transport round-tripped, so this VERDICT must reach the
                # breaker as a success — a half-open probe that lands on
                # RETRY would otherwise be stranded (neither record_*
                # called), wedging every caller in-process for an extra
                # cooldown per lost-probe recovery. Pacing is the
                # admission backoff's job, not the breaker's.
                self.breaker.record_success()
                self._record_attempt("overloaded")
                self._admission_retry_at = self._clock() + max(
                    0.0, e.backoff_hint_seconds
                )
                SIDECAR_REQUESTS.inc({"outcome": "rejected"})
                SOLVER_FALLBACK.inc({"reason": "admission_rejected"})
                self.fallback_reason = (
                    f"sidecar admission rejected (queue depth "
                    f"{e.queue_depth}); solving in-process, next dial in "
                    f"{e.backoff_hint_seconds:.3f}s"
                )
                tr.event(
                    "admission_rejected",
                    queue_depth=e.queue_depth,
                    backoff_seconds=e.backoff_hint_seconds,
                )
                self.log.warn(
                    "sidecar admission rejected; solving in-process",
                    queue_depth=e.queue_depth,
                    backoff_seconds=e.backoff_hint_seconds,
                )
            except Exception as e:
                self.breaker.record_failure()
                self._record_attempt("failure")
                SIDECAR_REQUESTS.inc({"outcome": "failure"})
                SOLVER_FALLBACK.inc({"reason": "sidecar_unavailable"})
                self.fallback_reason = (
                    f"sidecar solve failed ({type(e).__name__}: {e}); "
                    "degrading to in-process solver"
                )
                tr.event(
                    "sidecar_failed",
                    error=f"{type(e).__name__}: {e}",
                    breaker=self.breaker.state,
                )
                self.log.warn(
                    "sidecar solve failed; degrading to in-process solver",
                    error=f"{type(e).__name__}: {e}",
                    consecutive_failures=self.breaker.consecutive_failures,
                    breaker=self.breaker.state,
                )
        elif in_backoff:
            self._record_attempt("backoff")
            SOLVER_FALLBACK.inc({"reason": "admission_rejected"})
            self.fallback_reason = (
                "sidecar admission backoff in effect; solving in-process"
            )
            tr.event("admission_backoff")
        else:
            self._record_attempt("breaker_denied")
            SOLVER_FALLBACK.inc({"reason": "circuit_open"})
            self.fallback_reason = (
                "sidecar circuit open; solving in-process during cooldown"
            )
            tr.event("circuit_open", breaker=self.breaker.state)
        results = self._solve_in_process(
            node_pools,
            instance_types_by_pool,
            pods,
            state_node_views,
            daemonset_pods,
            options,
            cluster,
            namespace_labels,
            force_oracle,
            trace=tr,
        )
        tr.annotate(solver=self.last_used)
        return results

    def _solve_in_process(
        self,
        node_pools,
        instance_types_by_pool,
        pods,
        state_node_views,
        daemonset_pods,
        options,
        cluster,
        namespace_labels,
        force_oracle,
        trace=None,
    ) -> Results:
        results, scheduler = solve_in_process(
            node_pools,
            instance_types_by_pool,
            pods,
            state_node_views,
            daemonset_pods,
            options,
            cluster=cluster or ClusterSource(namespace_labels=namespace_labels or {}),
            force_oracle=force_oracle,
            trace=trace,
        )
        self.last_used = "tpu" if scheduler.used_tpu else "oracle"
        return results

    @staticmethod
    def _to_results(decoded: dict, pods: list[Pod]) -> Results:
        """Expand the decoded wire response (service.decode_result) into
        the Results shape the provisioning controller consumes."""
        uid_to_pod = {p.uid: p for p in pods}
        claims = [
            RemoteNodeClaim(
                nodepool_name=c["nodepool"],
                node_claim=c["node_claim"],
                requests=c["requests"],
                pods=[uid_to_pod[u] for u in c["pod_uids"] if u in uid_to_pod],
            )
            for c in decoded["new_node_claims"]
        ]
        by_node: dict[str, RemoteExistingNode] = {}
        for uid, node_name in decoded["existing_assignments"].items():
            node = by_node.setdefault(node_name, RemoteExistingNode(node_name))
            if uid in uid_to_pod:
                node.pods.append(uid_to_pod[uid])
        return Results(
            new_node_claims=claims,
            existing_nodes=list(by_node.values()),
            pod_errors=dict(decoded["pod_errors"]),
            timed_out=bool(decoded["timed_out"]),
        )
