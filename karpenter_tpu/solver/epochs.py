"""Epoch-based incremental cluster state for the solver service boundary.

ROADMAP open item 3: PR 8's upload-byte spans proved that every sidecar
solve re-ships the full ClusterSource slice across the wire and
re-uploads the per-class tables to the device, so steady-state traffic
pays a per-solve cost proportional to *cluster* size, not *pending-pod*
size. This module makes the service stateful-with-epochs:

- **Sections** (`sections_from_request` / `materialize_request`): the
  non-pending-pod part of a problem request — node pools, instance
  types, StateNodeViews, daemonsets, and the ClusterSource slice — in an
  indexable form both sides of the wire share. The client derives its
  sections from the SAME `encode_problem_dict` output a full-snapshot
  request serializes, and the server materializes a full request dict
  back from them, so a delta-materialized solve decodes through the
  SAME `_decode_problem_dict` path a from-scratch snapshot does:
  decision identity with full resync is by construction, not by a
  parallel decoder (pinned by tests/test_service.py and the chaos
  soak's in-process referee).
- **Deltas** (`diff_sections` / `apply_delta`): per-entry upsert/remove
  against a server-held base epoch. Keyed sections diff by natural key
  (views by node name, bound cluster pods by uid, node labels by node
  name, instance types per pool); rare wholesale sections (node pools,
  daemonsets, namespace labels) replace-or-omit. An unchanged section
  costs zero wire bytes, so steady-state traffic ships only the
  pending-pod batch plus churn.
- **EpochStore**: the bounded per-client epoch store (LRU on both the
  client and epoch axes). Any lookup miss is answered with a retriable
  EPOCH_RESYNC frame and the client falls back to the full-snapshot
  request — a from-scratch client is always correct, so every failure
  mode (eviction, server restart, mid-delta kill, malformed delta)
  degrades to the decision-identical full-resync path instead of
  corrupting state.
- **DeviceTableCache**: content-addressed LRU of uploaded device table
  sets (`problem_fingerprint`). The CLAUDE.md invalidation invariant —
  relax mutations and any `pod_class_key`-relevant change invalidate
  the memoized `_ktpu_*` class keys — extends to the server-held device
  copies mechanically: the fingerprint hashes every encoded array the
  tables derive from, so anything the table encoding depends on
  (a relax rung, a label value, an instance-type change arriving via a
  delta) changes the key and the stale entry is never hit; eviction
  bounds the HBM the dead entries can pin. A repeat same-epoch solve
  hits the cache and uploads only the pending-pod batch (the
  `epoch[runtime]` ir-transfer budget pins the zero).
- **AdmissionGate**: queue-depth + estimated-solve-cost admission in
  front of `SolverServer._solve`. When the solve budget is
  oversubscribed the server answers a RETRY frame with a backoff hint
  instead of queueing, so `ResilientSolver` degrades callers to the
  in-process oracle instead of letting wire deadlines cascade into
  breaker trips (docs/resilience.md).

Concurrency contract (graftlint race tier): every lock in this module is
a leaf — nothing blocking, no device syncs, and no other module lock is
taken while one is held (metric gauge sets acquire the gauge's own inner
lock, the same store->gauge ordering metrics.Store documents). The fault
suite runs these paths under racert-instrumented locks.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Optional

import numpy as np

from karpenter_tpu import metrics

# -- epoch/admission metrics (docs/observability.md catalogs these) ----------

EPOCH_SOLVES = metrics.REGISTRY.counter(
    "karpenter_solver_epoch_solves_total",
    "Sidecar solves by request mode: snapshot (epoch-less client), "
    "full_resync (epoch-establishing snapshot), delta.",
    ("mode",),
)
EPOCH_RESYNCS = metrics.REGISTRY.counter(
    "karpenter_solver_epoch_resyncs_total",
    "EPOCH_RESYNC answers by reason (unknown_epoch/apply_error/"
    "decode_error/materialize_error) — each one sends the client down "
    "the full-snapshot path.",
    ("reason",),
)
EPOCHS_RESIDENT = metrics.REGISTRY.gauge(
    "karpenter_solver_epochs_resident",
    "Materialized cluster epochs currently held by the epoch store.",
)
ADMISSION_REJECTED = metrics.REGISTRY.counter(
    "karpenter_solver_admission_rejected_total",
    "Solve requests refused by the admission gate (answered RETRY with "
    "a backoff hint).",
)
ADMISSION_QUEUE_DEPTH = metrics.REGISTRY.gauge(
    "karpenter_solver_admission_queue_depth",
    "Solve requests currently admitted and in flight behind the "
    "admission gate.",
)
TABLE_CACHE = metrics.REGISTRY.counter(
    "karpenter_solver_table_cache_total",
    "Device-table cache lookups on the solve upload path, by outcome "
    "(hit skips the per-class table upload entirely).",
    ("outcome",),
)
ADMISSION_EWMA = metrics.REGISTRY.gauge(
    "karpenter_admission_ewma_solve_seconds",
    "AdmissionGate's EWMA of observed solve wall-clock — the per-request "
    "cost floor the byte estimator is maxed against (0 until the first "
    "completed solve feeds observe()).",
)
TABLE_CACHE_WAIT = metrics.REGISTRY.histogram(
    "karpenter_table_cache_wait_seconds",
    "Seconds a solve spent blocked on another lane's single-flight "
    "device-table build (DeviceTableCache.begin_tables waiters only; "
    "builders and resident hits never wait).",
)


class DeltaError(ValueError):
    """A delta frame that cannot be applied (malformed structure, missing
    keys). Retriable: the server answers EPOCH_RESYNC and the client's
    full-snapshot fallback re-establishes ground truth."""


class SolverOverloaded(RuntimeError):
    """The sidecar refused admission (RETRY frame): the solve budget is
    oversubscribed. Transport is healthy and the problem is fine — the
    caller should solve in-process NOW and honor `backoff_hint_seconds`
    before dialing the sidecar again (ResilientSolver does both, and
    deliberately does NOT count this as a breaker failure). Defined here
    rather than in service.py so hybrid.py can catch it without a
    circular import (service imports hybrid); service re-exports it."""

    def __init__(
        self, msg: str, backoff_hint_seconds: float = 0.0, queue_depth: int = 0
    ):
        super().__init__(msg)
        self.backoff_hint_seconds = float(backoff_hint_seconds)
        self.queue_depth = int(queue_depth)


# ---------------------------------------------------------------------------
# sections: the epoch-resident slice of a problem request


def _pod_uid(d: dict) -> str:
    try:
        return str(d["metadata"]["uid"])
    except (TypeError, KeyError) as e:
        raise DeltaError(f"pod payload without metadata.uid: {e}") from e


def sections_from_request(req: dict) -> dict:
    """Decompose a full-snapshot request dict (service.encode_problem_dict
    schema) into the indexable epoch sections. Values are shared by
    reference with `req` — sections are immutable once stored; apply_delta
    copies-on-write."""
    cl = req.get("cluster")
    views = req.get("state_node_views")
    cluster_pods: dict[str, list] = {}
    for ns, pods in ((cl or {}).get("pods_by_namespace") or {}).items():
        for p in pods:
            cluster_pods[_pod_uid(p)] = [ns, p]
    return {
        "node_pools": req.get("node_pools") or [],
        "instance_types_by_pool": dict(req.get("instance_types_by_pool") or {}),
        "views": None if views is None else {v["name"]: v for v in views},
        "daemonset_pods": req.get("daemonset_pods") or [],
        "namespace_labels": req.get("namespace_labels") or {},
        "has_cluster": cl is not None,
        "cluster_ns_labels": (cl or {}).get("namespace_labels") or {},
        "cluster_pods": cluster_pods,
        "node_labels": dict((cl or {}).get("node_labels_by_name") or {}),
    }


def materialize_request(
    sections: dict, pods_flat: dict, options: Optional[dict], force_oracle: bool
) -> dict:
    """Reassemble a full request dict from epoch sections + the per-solve
    payload (pending pods, options). The output feeds the SAME
    service._decode_problem_dict a wire snapshot does. Bound-pod lists
    regroup per namespace in store order — order-insensitive downstream:
    topology counts are sums and the oracle sorts existing nodes itself
    (oracle.py Scheduler.__init__ sorts state views by (initialized,
    name)); the service parity suites pin decision identity."""
    cluster = None
    if sections.get("has_cluster"):
        pods_by_ns: dict[str, list] = {}
        for ns, pod in sections["cluster_pods"].values():
            pods_by_ns.setdefault(ns, []).append(pod)
        cluster = {
            "namespace_labels": sections["cluster_ns_labels"],
            "pods_by_namespace": pods_by_ns,
            "node_labels_by_name": sections["node_labels"],
        }
    views = sections["views"]
    return {
        "namespace_labels": sections["namespace_labels"],
        "cluster": cluster,
        "node_pools": sections["node_pools"],
        "instance_types_by_pool": sections["instance_types_by_pool"],
        "pods_flat": pods_flat,
        "state_node_views": None if views is None else list(views.values()),
        "daemonset_pods": sections["daemonset_pods"],
        "options": options or {},
        "force_oracle": bool(force_oracle),
    }


# wholesale sections: rare churn, replaced in full when they change at all
_FULL_SECTIONS = (
    "node_pools",
    "daemonset_pods",
    "namespace_labels",
    "has_cluster",
    "cluster_ns_labels",
)
# keyed sections: diffed per entry by natural key
_KEYED_SECTIONS = (
    "instance_types_by_pool",  # pool name -> jsonable type list
    "views",  # node name -> view dict (None = no views at all)
    "cluster_pods",  # pod uid -> [namespace, jsonable pod]
    "node_labels",  # node name -> labels
)


def diff_sections(old: dict, new: dict) -> dict:
    """Per-section delta from `old` to `new`. Unchanged sections are
    omitted entirely (zero wire bytes). Keyed sections carry
    {"set": {key: value}, "del": [keys]}; wholesale sections and
    None-transitions carry {"full": value}."""
    delta: dict[str, Any] = {}
    for name in _FULL_SECTIONS:
        if old.get(name) != new.get(name):
            delta[name] = {"full": new.get(name)}
    for name in _KEYED_SECTIONS:
        o, n = old.get(name), new.get(name)
        if o == n:
            continue
        if o is None or n is None:
            delta[name] = {"full": n}
            continue
        upsert = {k: v for k, v in n.items() if k not in o or o[k] != v}
        gone = [k for k in o if k not in n]
        delta[name] = {"set": upsert, "del": gone}
    return delta


def apply_delta(base: dict, delta: dict) -> dict:
    """Copy-on-write application: the returned sections share untouched
    section objects with `base` (epochs are immutable once stored — a
    later resync to the base epoch must see it unmutated); touched keyed
    sections get a fresh outer mapping. Raises DeltaError on anything
    malformed — the caller answers EPOCH_RESYNC, never a corrupted
    epoch."""
    if not isinstance(delta, dict):
        raise DeltaError(f"delta must be an object, got {type(delta).__name__}")
    out = dict(base)
    for name, change in delta.items():
        if name not in _FULL_SECTIONS and name not in _KEYED_SECTIONS:
            raise DeltaError(f"unknown delta section {name!r}")
        if not isinstance(change, dict):
            raise DeltaError(f"section {name!r}: change must be an object")
        if "full" in change:
            out[name] = change["full"]
            continue
        if name in _FULL_SECTIONS:
            raise DeltaError(f"section {name!r} only supports full replacement")
        current = out.get(name)
        if current is None:
            raise DeltaError(f"section {name!r}: keyed delta against None base")
        updated = dict(current)
        for k in change.get("del") or []:
            updated.pop(k, None)
        upserts = change.get("set") or {}
        if not isinstance(upserts, dict):
            raise DeltaError(f"section {name!r}: 'set' must be an object")
        if name == "cluster_pods":
            for uid, entry in upserts.items():
                if not (isinstance(entry, list) and len(entry) == 2):
                    raise DeltaError(
                        "cluster_pods entries must be [namespace, pod]"
                    )
        updated.update(upserts)
        out[name] = updated
    return out


# ---------------------------------------------------------------------------
# the bounded per-client epoch store


class EpochStore:
    """Server-held materialized cluster sections keyed by
    (client id, epoch id), bounded LRU on both axes. Misses are the
    RESYNC path — eviction is always safe because the client's
    full-snapshot fallback re-establishes ground truth (service.py wire
    contract).

    Thread safety: handler threads get/put concurrently; one leaf lock
    guards the maps (the resident-count gauge is set under it — the same
    outer->inner ordering metrics.Store documents, and never inverted)."""

    def __init__(self, max_clients: int = 8, max_epochs: int = 4):
        self.max_clients = max_clients
        self.max_epochs = max_epochs
        self._lock = threading.Lock()
        self._clients: "OrderedDict[str, OrderedDict[int, dict]]" = OrderedDict()

    def get(self, client: Optional[str], epoch: Any) -> Optional[dict]:
        if client is None:
            return None
        with self._lock:
            epochs = self._clients.get(client)
            if epochs is None:
                return None
            sections = epochs.get(epoch)
            if sections is None:
                return None
            epochs.move_to_end(epoch)
            self._clients.move_to_end(client)
            return sections

    def put(self, client: str, epoch: Any, sections: dict) -> None:
        with self._lock:
            epochs = self._clients.setdefault(client, OrderedDict())
            epochs[epoch] = sections
            epochs.move_to_end(epoch)
            self._clients.move_to_end(client)
            while len(epochs) > self.max_epochs:
                epochs.popitem(last=False)
            while len(self._clients) > self.max_clients:
                self._clients.popitem(last=False)
            self._publish_locked()

    def stats(self) -> tuple[int, int]:
        """(clients, total resident epochs) — the PONG payload fields."""
        with self._lock:
            return len(self._clients), sum(
                len(e) for e in self._clients.values()
            )

    def clear(self) -> None:
        with self._lock:
            self._clients.clear()
            self._publish_locked()

    def _publish_locked(self) -> None:
        EPOCHS_RESIDENT.set(
            float(sum(len(e) for e in self._clients.values()))
        )


# ---------------------------------------------------------------------------
# device-resident table cache


def _feed(h, x: Any) -> None:
    if x is None:
        h.update(b"\x00N")
    elif isinstance(x, np.ndarray):
        h.update(repr((x.dtype.str, x.shape)).encode())
        h.update(np.ascontiguousarray(x).tobytes())
    elif isinstance(x, (bool, int, float, str, bytes, np.integer, np.floating)):
        h.update(repr(x).encode())
    elif isinstance(x, (list, tuple)):
        h.update(b"[")
        for v in x:
            _feed(h, v)
        h.update(b"]")
    elif isinstance(x, dict):
        h.update(b"{")
        for k in sorted(x):
            _feed(h, k)
            _feed(h, x[k])
        h.update(b"}")
    else:
        # silent skips would let two different problems share a key;
        # fail loudly so a new EncodedProblem field gets a hashing rule
        raise TypeError(f"unhashable fingerprint component {type(x).__name__}")


# EncodedProblem fields that are host objects, not table inputs: the
# scheduler/pods feed only the decode side, and the group/requirement
# OBJECTS are fully represented by the encoded arrays plus the attrs fed
# explicitly below (v_anti from group.type, h_inverse from .inverse)
_FP_SKIP = frozenset(
    {"scheduler", "pods", "vocab", "table", "vgroups", "hgroups", "rt_tier_reqs"}
)

# Additional skips for the TABLE-level fingerprint (fleet lane grouping,
# solver/fleet.py): the per-pod identity columns and per-encode-class
# tables listed here ride the per-LANE State/PodX side of a vmapped
# dispatch — they are gathered into each lane's own PodX from each
# lane's own _dev_tables — so two requests that differ only in them can
# still share ONE Tables pytree on device. Everything a shared
# tb (tpu.py _tables) or the lane State SHAPES derive from stays hashed:
# templates/types/offerings, topology group tables, the relax-tier
# tables (PodX.rrow indexes the SHARED tb.rt_* rows, so those arrays
# must be byte-equal across lanes), vocab/resource layouts, and every
# scalar dim.
_TABLE_FP_SKIP = _FP_SKIP | frozenset(
    {
        "pod_class",
        "srow",
        "class_reps",
        "rcls_of",
        "rclass_creps",
        "preq_c",
        "prequests_c",
        "ptol_t_c",
        "ptol_e_c",
        "ptopo_kind_c",
        "ptopo_gid_c",
        "ptopo_sel_c",
        "pinv_h_c",
        "pown_h_c",
        "sel_rows_v",
        "sel_rows_h",
        "php_own_c",
        "php_conf_c",
    }
)


def _field_digest(problem, name: str, cache: dict) -> bytes:
    got = cache.get(name)
    if got is None:
        h = hashlib.blake2b(digest_size=16)
        _feed(h, getattr(problem, name))
        got = h.digest()
        cache[name] = got
    return got


def _fingerprint(problem, skip: frozenset) -> str:
    """Hash-of-field-hashes with a per-problem-instance digest memo: the
    serving hot path computes TWO fingerprints with different skip sets
    per solve — problem_fingerprint for the table-cache lookup, then
    table_fingerprint for the fleet window key — so the expensive part
    (a blake2b pass over each MB-scale array) runs once per FIELD and
    the second fingerprint only combines ~a hundred cached 16-byte
    digests. Safe because an EncodedProblem is built fresh per solve and
    not mutated between the two calls (the CLAUDE.md _ktpu_* concern is
    cross-solve, and cross-solve always re-encodes)."""
    from karpenter_tpu.solver import buckets

    cache = getattr(problem, "_ktpu_fp_cache", None)
    if cache is None:
        cache = {}
        problem._ktpu_fp_cache = cache
    h = hashlib.blake2b(digest_size=16)
    _feed(h, bool(buckets.enabled()))
    for f in dataclasses.fields(problem):
        if f.name in skip:
            continue
        h.update(f.name.encode())
        h.update(_field_digest(problem, f.name, cache))
    meta = cache.get("__meta__")
    if meta is None:
        mh = hashlib.blake2b(digest_size=16)
        vocab = problem.vocab
        _feed(mh, (vocab.keys, vocab.values, vocab.words_per_key))
        table = problem.table
        _feed(mh, (table.names, table.scale))
        for g in problem.vgroups:
            _feed(
                mh,
                (g.kid, g.skew, g.min_domains, tuple(g.filt), g.group.type.value),
            )
        for g in problem.hgroups:
            _feed(mh, (g.skew, bool(g.inverse), tuple(g.filt)))
        meta = mh.digest()
        cache["__meta__"] = meta
    h.update(meta)
    return h.hexdigest()


def problem_fingerprint(problem) -> str:
    """Content hash of every encoded input the device tables derive from
    (tpu.py _tables + _upload_pod_tables + the vocab/resource layouts
    behind them). Two problems with equal fingerprints upload identical
    tables, so a cache hit is exact by construction; anything the table
    encoding depends on — a relax-rung mutation, a drifted label value,
    an instance-type change — perturbs some encoded array and misses.
    Hash cost is host memory bandwidth over a few MB of tables, orders
    below the tunnel upload + typeok dispatches a hit skips."""
    return _fingerprint(problem, _FP_SKIP)


def table_fingerprint(problem) -> str:
    """The fleet-lane grouping key (solver/fleet.py): like
    problem_fingerprint but EXCLUDING the per-pod / per-encode-class
    columns that ride each lane's own PodX. Two problems with equal
    table fingerprints share one `Tables` pytree (vmap in_axes=None) and
    produce shape-compatible States, so their solves can stack on a
    fleet axis; distinct pending-pod batches — different requests,
    names, counts within a pow-2 rung — still coalesce, which is exactly
    the phase-4 shape (__graft_entry__.py:274). Skipping MORE than tb
    reads would be unsound (lanes could share a wrong tb); skipping
    LESS only narrows coalescing, so the skip list is the conservative
    per-pod set."""
    return _fingerprint(problem, _TABLE_FP_SKIP)


class DeviceTableCache:
    """Content-addressed LRU of uploaded device table sets
    (fingerprint -> (tb, typeok, dev_tables, aff_c)). JAX device arrays
    are immutable, so entries are safely shared by concurrent solves;
    capacity bounds the HBM resident entries can pin. Invalidation is
    structural: a changed encoding changes the fingerprint, so stale
    entries are unreachable and age out of the LRU.

    Two levels (ROADMAP item 3 leftover, closed by the fleet pairing):

    - the FULL entry, keyed by `problem_fingerprint` — a hit skips every
      upload (the epoch[runtime] zero);
    - the SHARED-TABLES entry (`get_tables`/`put_tables`), keyed by
      `table_fingerprint` — the `Tables` pytree is a pure function of
      the table-hashed fields (solver/fleet.py's stacking precondition),
      so coalesced same-epoch solves whose PENDING-POD batches differ
      (different problem fingerprints, one cluster epoch) still share
      ONE tb materialization and rebuild only their per-lane pod tables.

    Builds are SINGLE-FLIGHT per table fingerprint (`begin_tables` /
    `end_tables`): concurrent misses — the fleet window's lanes all
    encoding before any put lands — elect one builder, the rest wait on
    its per-key event and take the resident tb. The wait is bounded and
    failure-safe: a builder that dies publishes None and the waiter
    builds its own copy (degraded, never wrong or stuck)."""

    # a waiting lane outlasting this means the builder thread died
    # un-Pythonically mid-upload; waiters then build their own copy
    BUILD_WAIT_SECONDS = 600.0

    def __init__(self, capacity: int = 8):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._items: "OrderedDict[str, tuple]" = OrderedDict()
        self._tables: "OrderedDict[str, Any]" = OrderedDict()
        self._building: dict[str, threading.Event] = {}

    def get(self, key: str):
        with self._lock:
            got = self._items.get(key)
            if got is not None:
                self._items.move_to_end(key)
        TABLE_CACHE.inc({"outcome": "hit" if got is not None else "miss"})
        return got

    def put(self, key: str, value: tuple) -> None:
        with self._lock:
            self._items[key] = value
            self._items.move_to_end(key)
            while len(self._items) > self.capacity:
                self._items.popitem(last=False)

    def get_tables(self, table_key: str):
        """The resident shared `Tables` pytree for a table fingerprint,
        or None. Counted as its own outcome so the serving telemetry can
        tell a tb-share (per-lane pod tables still upload) from a full
        hit."""
        with self._lock:
            tb = self._tables.get(table_key)
            if tb is not None:
                self._tables.move_to_end(table_key)
        if tb is not None:
            TABLE_CACHE.inc({"outcome": "tables_hit"})
        return tb

    def put_tables(self, table_key: str, tb) -> None:
        with self._lock:
            self._tables[table_key] = tb
            self._tables.move_to_end(table_key)
            while len(self._tables) > self.capacity:
                self._tables.popitem(last=False)

    def begin_tables(self, table_key: str):
        """Single-flight election for one tb materialization. Returns
        (tb, None) when the tables are already resident, else
        (None, token): a truthy token means THIS caller builds (and must
        end_tables in a finally); None means a sibling built while we
        waited — re-check get_tables, and on a publish failure build
        anyway. The event wait happens OUTSIDE the lock (leaf-lock
        contract, graftlint race tier)."""
        waited_since = None
        while True:
            with self._lock:
                tb = self._tables.get(table_key)
                if tb is not None:
                    self._tables.move_to_end(table_key)
                else:
                    ev = self._building.get(table_key)
                    if ev is None:
                        self._building[table_key] = threading.Event()
                        elected = True
                    else:
                        elected = False
            if tb is None and elected:
                # waited on a build that failed to publish, then won the
                # re-election: the wait still happened — record it
                if waited_since is not None:
                    TABLE_CACHE_WAIT.observe(time.monotonic() - waited_since)
                return None, table_key
            if tb is not None:
                TABLE_CACHE.inc({"outcome": "tables_hit"})
                if waited_since is not None:
                    TABLE_CACHE_WAIT.observe(time.monotonic() - waited_since)
                return tb, None
            if waited_since is None:
                waited_since = time.monotonic()
            if not ev.wait(self.BUILD_WAIT_SECONDS):
                # builder thread destroyed mid-upload: evict the stale
                # election (if it is still ours) so the KEY recovers —
                # later solves elect a fresh builder instead of each
                # stalling the full wait — wake fellow waiters, and
                # build our own copy
                with self._lock:
                    if self._building.get(table_key) is ev:
                        del self._building[table_key]
                ev.set()
                TABLE_CACHE_WAIT.observe(time.monotonic() - waited_since)
                return None, None

    def end_tables(self, token, tb) -> None:
        """Publish a single-flight build (tb=None on failure: waiters are
        woken and fall back to building their own copies)."""
        if token is None:
            return
        with self._lock:
            if tb is not None:
                self._tables[token] = tb
                self._tables.move_to_end(token)
                while len(self._tables) > self.capacity:
                    self._tables.popitem(last=False)
            ev = self._building.pop(token, None)
        if ev is not None:
            ev.set()

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def clear(self) -> None:
        with self._lock:
            self._items.clear()
            self._tables.clear()


# ---------------------------------------------------------------------------
# admission control


class AdmissionGate:
    """Queue-depth + estimated-cost admission in front of the server's
    solve path. The gate never queues: an oversubscribed request is
    answered immediately with a RETRY frame carrying a backoff hint, so
    the caller's deadline budget degrades it to the in-process ladder
    instead of cascading (ResilientSolver honors the hint before
    re-dialing; docs/resilience.md).

    Cost model: the byte estimator charges wire bytes at a conservative
    decode rate, but wire bytes UNDER-state delta solves (a delta frame
    is O(churn) while its solve is O(cluster + pods)), so the gate also
    keeps an EWMA of *observed* solve wall-clock (`observe`, fed by the
    server after each completed solve) and charges every request at
    least that much — the budget protection tracks what solves actually
    cost on this box, independent of which wire form carried them."""

    def __init__(
        self,
        max_inflight: int = 4,
        max_cost_seconds: float = 120.0,
        estimator: Optional[Callable[[int], float]] = None,
    ):
        self.max_inflight = max_inflight
        self.max_cost_seconds = max_cost_seconds
        self._estimate = estimator or self._default_estimate
        self._lock = threading.Lock()
        self._inflight: dict[int, float] = {}
        self._cost = 0.0
        self._next_token = 0
        self._ewma_seconds = 0.0

    @staticmethod
    def _default_estimate(payload_len: int) -> float:
        # ~32 MB/s of payload decode + solve work, 50 ms floor: measured
        # order-of-magnitude on the tier-1 container; deliberately
        # conservative (over-admitting is what the gate exists to stop)
        return 0.05 + payload_len / (32 * 1024 * 1024)

    def observe(self, solve_seconds: float) -> None:
        """Feed a completed solve's wall-clock into the cost EWMA."""
        s = max(0.0, float(solve_seconds))
        with self._lock:
            if self._ewma_seconds == 0.0:
                self._ewma_seconds = s
            else:
                self._ewma_seconds = 0.8 * self._ewma_seconds + 0.2 * s
            ewma = self._ewma_seconds
        # export outside the lock (leaf-lock discipline): the EWMA used
        # to be invisible — an operator could not tell WHY the gate
        # started rejecting after one slow solve
        ADMISSION_EWMA.set(ewma)

    def try_admit(self, payload_len: int):
        """(token, hint_seconds, depth): token is None on rejection, with
        `hint_seconds` the estimated wait for capacity to free up."""
        with self._lock:
            floor = self._ewma_seconds
        est = max(float(self._estimate(payload_len)), floor)
        with self._lock:
            depth = len(self._inflight)
            # an IDLE gate always admits: one in-flight solve can never
            # oversubscribe worse than serial execution, and without this
            # escape a single pathological observation (one solve slower
            # than max_cost_seconds) would push the EWMA above the budget
            # and reject everything forever — observe() only updates on
            # completed solves, so rejection would be permanent
            if depth >= self.max_inflight or (
                depth > 0 and self._cost + est > self.max_cost_seconds
            ):
                hint = max(0.05, self._cost / max(1, self.max_inflight))
                rejected = True
                token = None
            else:
                rejected = False
                self._next_token += 1
                token = self._next_token
                self._inflight[token] = est
                self._cost += est
                depth += 1
                hint = 0.0
            ADMISSION_QUEUE_DEPTH.set(float(len(self._inflight)))
        if rejected:
            ADMISSION_REJECTED.inc()
        return token, round(hint, 3), depth

    def release(self, token: Optional[int]) -> None:
        if token is None:
            return
        with self._lock:
            self._cost -= self._inflight.pop(token, 0.0)
            if not self._inflight:
                self._cost = 0.0  # clamp float drift at idle
            ADMISSION_QUEUE_DEPTH.set(float(len(self._inflight)))

    def depth(self) -> int:
        with self._lock:
            return len(self._inflight)
