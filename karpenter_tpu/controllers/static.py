"""Static-capacity NodePools: maintain a fixed replica count of nodes,
independent of pending pods (feature-gated, like the reference).

Reference /root/reference/pkg/controllers/static/:
- provisioning/controller.go:69-118 (scale up to spec.replicas)
- deprovisioning/controller.go:75-240 (scale down, emptiest first)
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import NodeClaim, NodePool, ObjectMeta
from karpenter_tpu.controllers.kube import NotFound, SimKube
from karpenter_tpu.controllers.state import Cluster
from karpenter_tpu.events import Event, Recorder
from karpenter_tpu.solver.nodes import NodeClaimTemplate
from karpenter_tpu import metrics

STATIC_NODES = metrics.REGISTRY.gauge(
    "karpenter_static_nodepool_nodes",
    "Nodes owned by static nodepools.",
    ("nodepool",),
)

_static_seq = [0]


class StaticProvisioning:
    """Scale static pools up to replicas (provisioning/controller.go:69)."""

    def __init__(self, kube: SimKube, cluster: Cluster, recorder: Optional[Recorder] = None):
        self.kube = kube
        self.cluster = cluster
        self.recorder = recorder

    def reconcile_all(self) -> int:
        created = 0
        for np in self.kube.list("NodePool"):
            if np.replicas is None:
                continue
            owned = self._owned_claims(np.name)
            STATIC_NODES.set(float(len(owned)), {"nodepool": np.name})
            deficit = np.replicas - len(owned)
            for _ in range(max(0, deficit)):
                self._create_claim(np)
                created += 1
        return created

    def _owned_claims(self, nodepool: str) -> list[NodeClaim]:
        return [
            c
            for c in self.kube.list("NodeClaim")
            if c.nodepool_name == nodepool
            and c.metadata.deletion_timestamp is None
        ]

    def _create_claim(self, np: NodePool) -> None:
        nct = NodeClaimTemplate(np)
        nc = nct.to_node_claim(nct.requirements.copy(), [])
        _static_seq[0] += 1
        nc.metadata.name = f"{np.name}-static-{_static_seq[0]:05d}"
        self.kube.create("NodeClaim", nc)
        if self.recorder:
            self.recorder.publish(
                Event(
                    "NodeClaim", nc.metadata.name, "Normal", "StaticProvisioned",
                    f"maintaining {np.replicas} replicas",
                )
            )


class StaticDeprovisioning:
    """Scale static pools down to replicas, emptiest nodes first
    (deprovisioning/controller.go:75)."""

    def __init__(self, kube: SimKube, cluster: Cluster, recorder: Optional[Recorder] = None):
        self.kube = kube
        self.cluster = cluster
        self.recorder = recorder

    def reconcile_all(self) -> int:
        deleted = 0
        for np in self.kube.list("NodePool"):
            if np.replicas is None:
                continue
            owned = [
                c
                for c in self.kube.list("NodeClaim")
                if c.nodepool_name == np.name
                and c.metadata.deletion_timestamp is None
            ]
            surplus = len(owned) - np.replicas
            if surplus <= 0:
                continue
            # emptiest (fewest pods) first, newest as tiebreak
            def pod_count(claim: NodeClaim) -> int:
                name = claim.status.node_name
                return len(self.cluster.pods_on(name)) if name else 0

            owned.sort(
                key=lambda c: (pod_count(c), -c.metadata.creation_timestamp)
            )
            for claim in owned[:surplus]:
                try:
                    self.kube.delete("NodeClaim", claim.name)
                    deleted += 1
                except NotFound:
                    continue
                if self.recorder:
                    self.recorder.publish(
                        Event(
                            "NodeClaim", claim.name, "Normal",
                            "StaticDeprovisioned",
                            f"scaling down to {np.replicas} replicas",
                        )
                    )
        return deleted
