"""Static-capacity NodePools: maintain a fixed replica count of nodes,
independent of pending pods (feature-gated, like the reference).

Reference /root/reference/pkg/controllers/static/:
- provisioning/controller.go:69-118 (scale up to spec.replicas)
- deprovisioning/controller.go:75-240 (scale down, emptiest first)
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import NodeClaim, NodePool, ObjectMeta
from karpenter_tpu.controllers.kube import NotFound, SimKube
from karpenter_tpu.controllers.state import Cluster
from karpenter_tpu.events import Event, Recorder
from karpenter_tpu.solver.nodes import NodeClaimTemplate
from karpenter_tpu import metrics

STATIC_NODES = metrics.REGISTRY.gauge(
    "karpenter_static_nodepool_nodes",
    "Nodes owned by static nodepools.",
    ("nodepool",),
)

_static_seq = [0]


def node_limit(np: NodePool) -> "float | int":
    """The pool's `nodes` limit as a node count; unlimited when absent.
    Limits are stored as integer milli-units (utils/resources.py: a limit
    of "2" is 2000), so the count conversion stays integer."""
    raw = np.limits.get("nodes")
    return float("inf") if raw is None else raw // 1000


def owned_claims(kube: SimKube, nodepool: str) -> list[NodeClaim]:
    """Non-deleting NodeClaims owned by the pool."""
    return [
        c
        for c in kube.list("NodeClaim")
        if c.nodepool_name == nodepool and c.metadata.deletion_timestamp is None
    ]


class StaticProvisioning:
    """Scale static pools up to replicas (provisioning/controller.go:69)."""

    def __init__(self, kube: SimKube, cluster: Cluster, recorder: Optional[Recorder] = None):
        self.kube = kube
        self.cluster = cluster
        self.recorder = recorder

    def reconcile_all(self) -> int:
        created = 0
        for np in self.kube.list("NodePool"):
            if np.replicas is None:
                continue
            # provisioning/controller.go:83: count via NodePoolState —
            # pending-disruption claims count as active (the disruption
            # controller is already creating their replacements)
            active, _, pending = self.cluster.nodepool_state.node_counts(np.name)
            STATIC_NODES.set(float(active), {"nodepool": np.name})
            if active + pending >= np.replicas:
                continue
            # provisioning/controller.go:93: reserve against the node limit
            # so concurrent scale decisions can't burst over it
            # pending-disruption claims count as active (their replacements
            # are already being created), so the deficit subtracts both
            grant = self.cluster.nodepool_state.reserve_node_count(
                np.name, node_limit(np), np.replicas - active - pending
            )
            for _ in range(grant):
                self._create_claim(np)
                # the create marked the claim active (informer), so the
                # reservation converts immediately (provisioner.go:166)
                self.cluster.nodepool_state.release_node_count(np.name, 1)
                created += 1
        return created

    def _create_claim(self, np: NodePool) -> None:
        nct = NodeClaimTemplate(np)
        nc = nct.to_node_claim(nct.requirements.copy(), [])
        _static_seq[0] += 1
        nc.metadata.name = f"{np.name}-static-{_static_seq[0]:05d}"
        self.kube.create("NodeClaim", nc)
        if self.recorder:
            self.recorder.publish(
                Event(
                    "NodeClaim", nc.metadata.name, "Normal", "StaticProvisioned",
                    f"maintaining {np.replicas} replicas",
                )
            )


class StaticDeprovisioning:
    """Scale static pools down to replicas, emptiest nodes first
    (deprovisioning/controller.go:75)."""

    def __init__(self, kube: SimKube, cluster: Cluster, recorder: Optional[Recorder] = None):
        self.kube = kube
        self.cluster = cluster
        self.recorder = recorder

    def reconcile_all(self) -> int:
        deleted = 0
        for np in self.kube.list("NodePool"):
            if np.replicas is None:
                continue
            owned = owned_claims(self.kube, np.name)
            # deprovisioning/controller.go:84: surplus from NodePoolState
            active, _, pending = self.cluster.nodepool_state.node_counts(np.name)
            if pending > 0:
                # a StaticDrift rollout is replacing claims; scaling down
                # now could delete the in-flight replacement and roll the
                # disruption back — wait for the rollout to finish
                continue
            surplus = min(len(owned), active) - np.replicas
            if surplus <= 0:
                continue
            # emptiest (fewest pods) first, newest as tiebreak
            def pod_count(claim: NodeClaim) -> int:
                name = claim.status.node_name
                return len(self.cluster.pods_on(name)) if name else 0

            owned.sort(
                key=lambda c: (pod_count(c), -c.metadata.creation_timestamp)
            )
            for claim in owned[:surplus]:
                try:
                    self.kube.delete("NodeClaim", claim.name)
                    deleted += 1
                except NotFound:
                    continue
                if self.recorder:
                    self.recorder.publish(
                        Event(
                            "NodeClaim", claim.name, "Normal",
                            "StaticDeprovisioned",
                            f"scaling down to {np.replicas} replicas",
                        )
                    )
        return deleted
