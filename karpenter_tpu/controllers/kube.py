"""SimKube: the in-memory API store standing in for the kube-apiserver.

Semantics mirrored from Kubernetes because the reference's correctness
leans on them (reference pkg/operator/operator.go, controller-runtime):
- optimistic concurrency: update() rejects stale resource_version (the
  conflict-requeue pattern in disruption/controller.go:146)
- finalizers: delete() only marks deletion_timestamp while finalizers
  remain; objects vanish when the last finalizer is removed
- watch: subscribers get (event_type, kind, obj) in commit order, on the
  committing thread but AFTER the store lock is released (the _pump event
  queue) — the informer layer (controllers/state.py wire_informers) builds
  the cluster cache from these, exactly like the reference's informer
  controllers (pkg/controllers/state/informer/)

Stored kinds are the framework's dataclasses (karpenter_tpu.api.objects):
Pod, Node, NodeClaim, NodePool, DaemonSet.
"""

from __future__ import annotations

import copy
import itertools
import threading
import time as time_mod
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from karpenter_tpu.api.objects import Node, Pod

ADDED = "added"
UPDATED = "updated"
DELETED = "deleted"


class Conflict(Exception):
    """Optimistic-concurrency failure (HTTP 409 equivalent)."""


class NotFound(Exception):
    pass


class AlreadyExists(Exception):
    pass


class RealClock:
    def now(self) -> float:
        return time_mod.monotonic()


class FakeClock:
    """Manually advanced clock for deterministic controller tests (the
    reference uses k8s.io/utils/clock/testing the same way)."""

    def __init__(self, start: float = 1000.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        self._now += seconds


@dataclass
class Namespace:
    """Minimal Namespace: name + labels, for affinity namespaceSelector
    resolution (reference topology.go:503 lists Namespace objects)."""

    name: str
    labels: dict = field(default_factory=dict)


@dataclass
class DaemonSet:
    """Minimal DaemonSet: the provisioner only needs the pod template for
    daemon overhead computation (reference provisioner.go:477)."""

    name: str
    pod_template: Pod = field(default_factory=Pod)


Subscriber = Callable[[str, str, object], None]


class SimKube:
    def __init__(self, clock=None) -> None:
        self._stores: dict[str, dict[str, object]] = {}
        self._version = itertools.count(1)
        self._subscribers: list[Subscriber] = []
        self.clock = clock if clock is not None else RealClock()
        # Each CRUD op (including its synchronous watch emit) is atomic
        # under this lock, so controller reconciles may run on a worker
        # pool (utils/workerpool.py) the way the reference scales its
        # reconcilers (termination/controller.go:58-60). Cross-op races
        # surface as Conflict — the same optimistic-concurrency contract
        # the real apiserver gives controller-runtime.
        self._lock = threading.RLock()
        self._events: list[tuple[str, str, object]] = []
        self._emitting = False  # guarded by self._lock

    # -- watch ------------------------------------------------------------

    def subscribe(self, fn: Subscriber) -> None:
        self._subscribers.append(fn)

    def _emit(self, event: str, kind: str, obj) -> None:
        """Queue a watch event. Called under self._lock; delivery happens
        in _pump AFTER the lock is released — a subscriber that blocks or
        takes another lock must not deadlock against worker-pool
        reconciles doing store CRUD, and subscriber work must not
        serialize the store. The queue-then-drain shape keeps the store
        lock a leaf in the program's acquisition graph: graftlint's
        race-blocking-hold flags blocking calls SimKube itself makes
        under the lock, but a subscriber's own locks live in other
        classes the static graph does not follow — keeping delivery
        outside the lock is what makes that blind spot moot."""
        self._events.append((event, kind, obj))

    def _pump(self) -> None:
        """Deliver queued events in commit order outside the lock. One
        thread drains at a time (the _emitting flag), so global ordering
        is preserved even when several workers mutate concurrently; a
        subscriber that mutates the store re-queues and the draining
        thread picks the new events up on the next loop."""
        while True:
            with self._lock:
                if self._emitting or not self._events:
                    return
                self._emitting = True
                batch = list(self._events)
                self._events.clear()
            try:
                for event, kind, obj in batch:
                    for fn in self._subscribers:
                        try:
                            fn(event, kind, obj)
                        except Exception as e:  # noqa: BLE001
                            # a broken subscriber must not swallow the rest
                            # of the batch (other commits' events) nor mask
                            # the committing caller's CRUD exception — the
                            # same contract informers get from a real
                            # apiserver watch (log and keep streaming)
                            from karpenter_tpu import logging as klog

                            klog.root.named("kube.watch").error(
                                "watch subscriber failed",
                                event=event,
                                kind=kind,
                                error=f"{type(e).__name__}: {e}",
                            )
            finally:
                with self._lock:
                    self._emitting = False

    # -- helpers ----------------------------------------------------------

    @staticmethod
    def _name(obj) -> str:
        meta = getattr(obj, "metadata", None)
        return meta.name if meta is not None else obj.name

    def _store(self, kind: str) -> dict[str, object]:
        return self._stores.setdefault(kind, {})

    # -- CRUD -------------------------------------------------------------

    def create(self, kind: str, obj):
        try:
            with self._lock:
                store = self._store(kind)
                name = self._name(obj)
                if name in store:
                    raise AlreadyExists(f"{kind}/{name}")
                obj = copy.deepcopy(obj)
                if getattr(obj, "metadata", None) is not None:
                    obj.metadata.resource_version = next(self._version)
                    # the apiserver stamps creationTimestamp at admission;
                    # age-based controllers (expiration, lifetime cost)
                    # depend on it. A 0.0 timestamp is treated as UNSET
                    # (the dataclass default) — a test modeling an old
                    # object must backdate with any positive epoch.
                    if not obj.metadata.creation_timestamp:
                        obj.metadata.creation_timestamp = self.clock.now()
                store[name] = obj
                self._emit(ADDED, kind, copy.deepcopy(obj))
                return copy.deepcopy(obj)
        finally:
            self._pump()

    def get(self, kind: str, name: str):
        with self._lock:
            obj = self._store(kind).get(name)
            if obj is None:
                raise NotFound(f"{kind}/{name}")
            return copy.deepcopy(obj)

    def try_get(self, kind: str, name: str):
        with self._lock:
            obj = self._store(kind).get(name)
            return copy.deepcopy(obj) if obj is not None else None

    def list(self, kind: str, filter: Optional[Callable[[object], bool]] = None):
        with self._lock:
            out = [copy.deepcopy(o) for o in self._store(kind).values()]
            if filter is not None:
                out = [o for o in out if filter(o)]
            return out

    def update(self, kind: str, obj):
        """Optimistic-concurrency update; finalizer-clearing completes a
        pending delete."""
        try:
            with self._lock:
                store = self._store(kind)
                name = self._name(obj)
                current = store.get(name)
                if current is None:
                    raise NotFound(f"{kind}/{name}")
                if obj.metadata.resource_version != current.metadata.resource_version:
                    raise Conflict(
                        f"{kind}/{name}: version {obj.metadata.resource_version} != "
                        f"{current.metadata.resource_version}"
                    )
                obj = copy.deepcopy(obj)
                obj.metadata.resource_version = next(self._version)
                if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
                    del store[name]
                    self._emit(DELETED, kind, copy.deepcopy(obj))
                    return None
                store[name] = obj
                self._emit(UPDATED, kind, copy.deepcopy(obj))
                return copy.deepcopy(obj)
        finally:
            self._pump()

    def delete(self, kind: str, name: str, now: Optional[float] = None):
        try:
            with self._lock:
                store = self._store(kind)
                current = store.get(name)
                if current is None:
                    raise NotFound(f"{kind}/{name}")
                if current.metadata.finalizers:
                    if current.metadata.deletion_timestamp is None:
                        current.metadata.deletion_timestamp = (
                            self.clock.now() if now is None else now
                        )
                        current.metadata.resource_version = next(self._version)
                        self._emit(UPDATED, kind, copy.deepcopy(current))
                    return None
                del store[name]
                self._emit(DELETED, kind, copy.deepcopy(current))
                return None
        finally:
            self._pump()

    # -- typed conveniences ----------------------------------------------

    def bind(self, pod_name: str, node_name: str) -> None:
        """The kube-scheduler binding equivalent."""
        try:
            with self._lock:
                pod = self._store("Pod").get(pod_name)
                if pod is None:
                    raise NotFound(f"Pod/{pod_name}")
                pod.node_name = node_name
                pod.metadata.resource_version = next(self._version)
                self._emit(UPDATED, "Pod", copy.deepcopy(pod))
        finally:
            self._pump()

    def pending_pods(self) -> list[Pod]:
        return self.list(
            "Pod",
            lambda p: not p.node_name
            and p.metadata.deletion_timestamp is None
            and not p.scheduling_gates,
        )

    def ready_nodes(self) -> list[Node]:
        return self.list(
            "Node",
            lambda n: n.ready
            and not n.unschedulable
            and n.metadata.deletion_timestamp is None,
        )
