"""The provisioning control plane: Batcher, VolumeTopology, and the
Provisioner singleton that turns pending pods into NodeClaims.

Reference:
- Provisioner   /root/reference/pkg/controllers/provisioning/provisioner.go:119-586
- Batcher       .../provisioning/batcher.go:33-110
- Trigger controllers .../provisioning/controller.go:44-125
- VolumeTopology .../provisioning/scheduling/volumetopology.go:43-226

The Solve itself goes through the HybridScheduler (TPU path with oracle
fallback), so the control plane is solver-agnostic. Pods landing on existing
ready nodes are bound directly (standing in for the kube-scheduler, which
SimKube does not model); pods landing on new claims bind on a later
reconcile once the claim's node registers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu import logging, metrics, tracing
from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    NodeClaim,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeAffinity,
    Operator,
    Pod,
)
from karpenter_tpu.controllers.kube import NotFound, SimKube
from karpenter_tpu.controllers.state import Cluster, cluster_source, is_provisionable, is_reschedulable
from karpenter_tpu.events import Event, Recorder
from karpenter_tpu.options import Options
from karpenter_tpu.solver import Results, SchedulerOptions
from karpenter_tpu.solver.hybrid import solve_in_process
from karpenter_tpu.utils import resources as res

# -- scheduler metrics (reference scheduling/metrics.go:34-95) ---------------

SCHEDULE_DURATION = metrics.REGISTRY.histogram(
    "karpenter_provisioner_scheduling_duration_seconds",
    "Duration of scheduling simulations.",
)
QUEUE_DEPTH = metrics.REGISTRY.gauge(
    "karpenter_provisioner_scheduling_queue_depth",
    "Number of pods the scheduler is attempting to schedule.",
)
IGNORED_PODS = metrics.REGISTRY.gauge(
    "karpenter_ignored_pod_count", "Pods ignored for provisioning (invalid specs)."
)
UNSCHEDULABLE_PODS = metrics.REGISTRY.gauge(
    "karpenter_pods_state", "Pods that failed to schedule.", ("state",)
)


class Batcher:
    """Dedup'd trigger batching window (batcher.go:33): the first trigger
    opens a window that closes after `idle` seconds without new triggers or
    `max_duration` seconds overall."""

    def __init__(self, clock, idle_seconds: float = 1.0, max_seconds: float = 10.0):
        self.clock = clock
        self.idle = idle_seconds
        self.max = max_seconds
        self._window_start: Optional[float] = None
        self._last_trigger: Optional[float] = None
        self._triggered_uids: set[str] = set()

    def trigger(self, uid: str = "") -> None:
        now = self.clock.now()
        if uid and uid in self._triggered_uids:
            # duplicate triggers don't extend the window (batcher.go:62)
            return
        if uid:
            self._triggered_uids.add(uid)
        if self._window_start is None:
            self._window_start = now
        self._last_trigger = now

    def ready(self) -> bool:
        """Window closed -> a provisioning run should start."""
        if self._window_start is None:
            return False
        now = self.clock.now()
        if now - self._window_start >= self.max:
            return True
        return now - self._last_trigger >= self.idle

    def reset(self) -> None:
        self._window_start = None
        self._last_trigger = None
        self._triggered_uids.clear()


class VolumeTopology:
    """PVC zone injection (volumetopology.go:43): before scheduling, rewrite
    each pod's node affinity with the zones its bound/zonal volumes demand."""

    def __init__(self, kube: SimKube):
        self.kube = kube

    def inject(self, pod: Pod) -> None:
        requirements: list[NodeSelectorRequirement] = []
        for claim_name in pod.volume_claims:
            pvc = self.kube.try_get("PersistentVolumeClaim", claim_name)
            if pvc is None:
                continue
            req = self._requirement_for(pvc)
            if req is not None:
                requirements.append(req)
            # resolve the claim's CSI driver for per-driver volume-limit
            # accounting (volumeusage.go:187: pod -> PVC -> StorageClass
            # provisioner), from the same PVC fetch as the zone resolution
            driver = self.driver_for(pvc)
            if driver:
                pod.volume_drivers[claim_name] = driver
        if not requirements:
            return
        if pod.node_affinity is None:
            pod.node_affinity = NodeAffinity()
        if not pod.node_affinity.required_terms:
            pod.node_affinity.required_terms = [NodeSelectorTerm([])]
        # the reference appends to EVERY required term (OR-semantics keep
        # each alternative zone-correct, volumetopology.go:78)
        for term in pod.node_affinity.required_terms:
            term.match_expressions = list(term.match_expressions) + requirements

    def driver_for(self, pvc) -> str:
        """The claim's CSI driver via StorageClass.provisioner ("" when
        unresolvable). Also used by the cluster cache when it tallies
        BOUND pods' volumes (state.py) — attribution must agree between
        the solve-time inject and the bound-pod accounting or per-driver
        budgets double-count into the default bucket."""
        if not pvc.storage_class_name:
            return ""
        sc = self.kube.try_get("StorageClass", pvc.storage_class_name)
        return sc.provisioner if sc is not None else ""

    def resolve_drivers(self, pod: Pod) -> None:
        """Fill pod.volume_drivers in place (claim -> CSI driver)."""
        for claim_name in pod.volume_claims:
            if claim_name in pod.volume_drivers:
                continue
            pvc = self.kube.try_get("PersistentVolumeClaim", claim_name)
            if pvc is not None:
                driver = self.driver_for(pvc)
                if driver:
                    pod.volume_drivers[claim_name] = driver

    def _requirement_for(self, pvc) -> Optional[NodeSelectorRequirement]:
        zones: list[str] = []
        if pvc.volume_zones:
            zones = list(pvc.volume_zones)  # bound volume wins
        elif pvc.storage_class_name:
            sc = self.kube.try_get("StorageClass", pvc.storage_class_name)
            if sc is not None and sc.zones:
                zones = list(sc.zones)
        if not zones:
            return None
        return NodeSelectorRequirement(
            well_known.TOPOLOGY_ZONE_LABEL_KEY, Operator.IN, zones
        )

    def validate(self, pod: Pod) -> Optional[str]:
        """volumetopology.go:162 ValidatePersistentVolumeClaims: pods whose
        PVCs don't resolve are not schedulable."""
        for claim_name in pod.volume_claims:
            try:
                pvc = self.kube.get("PersistentVolumeClaim", claim_name)
            except NotFound:
                return f"missing persistent volume claim {claim_name!r}"
            if not pvc.volume_name and pvc.storage_class_name:
                sc = self.kube.try_get("StorageClass", pvc.storage_class_name)
                if sc is None:
                    return (
                        f"missing storage class {pvc.storage_class_name!r} "
                        f"for claim {claim_name!r}"
                    )
        return None


@dataclass
class ProvisioningResult:
    results: Optional[Results] = None
    created_claims: list[NodeClaim] = field(default_factory=list)
    bound_pods: dict[str, str] = field(default_factory=dict)  # pod name -> node
    skipped: bool = False
    reason: str = ""


_claim_name_seq = [0]


class Provisioner:
    """provisioner.go:119 Reconcile: batch -> Synced barrier -> Schedule ->
    CreateNodeClaims. Driven manually (tests/operator call reconcile());
    the Batcher gates when a run is due."""

    def __init__(
        self,
        kube: SimKube,
        cluster: Cluster,
        cloud_provider,
        clock,
        options: Optional[Options] = None,
        recorder: Optional[Recorder] = None,
        force_oracle: bool = False,
        solver=None,
    ):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud_provider
        self.clock = clock
        self.opts = options or Options()
        self.recorder = recorder or Recorder(clock)
        self.volume_topology = VolumeTopology(kube)
        self.batcher = Batcher(
            clock,
            self.opts.batch_idle_duration_seconds,
            self.opts.batch_max_duration_seconds,
        )
        self.force_oracle = force_oracle
        # Optional sidecar boundary: a ResilientSolver (solver/hybrid.py).
        # When set, Schedule routes solves through it — remote sidecar
        # under a circuit breaker, in-process HybridScheduler as the floor.
        # None = solve in-process directly (tests, benchmarks, default).
        self.solver = solver
        self.log = logging.root.named("provisioner")
        self.last_solver_used: Optional[str] = None
        self.last_trace = None  # the most recent schedule()'s solve trace

    # -- triggers (provisioning/controller.go:44) ------------------------

    def trigger_pod(self, pod: Pod) -> None:
        if is_provisionable(pod):
            self.batcher.trigger(pod.uid)

    def trigger_node_deletion(self, node_name: str) -> None:
        self.batcher.trigger(f"node-deleting/{node_name}")

    # -- pending pods -----------------------------------------------------

    def get_pending_pods(self) -> list[Pod]:
        """provisioner.go:172 GetPendingPods + pod validation
        (provisioner.go:504)."""
        out = []
        ignored = 0
        for pod in self.kube.list("Pod"):
            if not is_provisionable(pod):
                continue
            err = self._validate(pod)
            if err is not None:
                ignored += 1
                self.recorder.publish(
                    Event("Pod", pod.name, "Warning", "FailedScheduling", err)
                )
                continue
            out.append(pod)
        IGNORED_PODS.set(float(ignored))
        return out

    def _validate(self, pod: Pod) -> Optional[str]:
        """provisioner.go:504 Validate: the karpenter-managed-label opt-out,
        node selector + required-affinity requirement validation (restricted
        labels/domains, operators, value shapes — validateNodeSelector /
        validateAffinity via v1.ValidateRequirement), then PVC checks."""
        from karpenter_tpu.controllers.nodepool_aux import validate_requirement

        # karpenter.sh/nodepool DoesNotExist opt-out (provisioner.go:538)
        na = pod.node_affinity
        terms = na.required_terms if na is not None else []
        for term in terms:
            for e in term.match_expressions:
                if (
                    e.key == well_known.NODEPOOL_LABEL_KEY
                    and e.operator == Operator.DOES_NOT_EXIST
                ):
                    return "pod opted out of provisioning (nodepool DoesNotExist)"
        for k, v in pod.node_selector.items():
            err = validate_requirement(
                NodeSelectorRequirement(k, Operator.IN, [v])
            )
            if err is not None:
                return err
        for term in terms:
            for e in term.match_expressions:
                err = validate_requirement(e)
                if err is not None:
                    return err
        return self.volume_topology.validate(pod)

    def _reschedulable_from_deleting_nodes(self) -> list[Pod]:
        """Pods on deleting/marked nodes get re-solved so replacements are
        sized before the node drains (provisioner.go:330 & helpers.go:84)."""
        out = []
        for sn in self.cluster.state_nodes():
            if not (sn.marked_for_deletion or sn.deleting()):
                continue
            for pod in self.cluster.pods_on(sn.name):
                if is_reschedulable(pod):
                    out.append(pod)
        return out

    # -- the loop ---------------------------------------------------------

    def reconcile(self, ignore_batcher: bool = False) -> ProvisioningResult:
        if not ignore_batcher and not self.batcher.ready():
            return ProvisioningResult(skipped=True, reason="batch window open")
        self.batcher.reset()
        if not self.cluster.synced(self.kube):
            return ProvisioningResult(skipped=True, reason="cluster state not synced")
        pods = self.get_pending_pods() + self._reschedulable_from_deleting_nodes()
        if not pods:
            return ProvisioningResult(skipped=True, reason="no pending pods")
        QUEUE_DEPTH.set(float(len(pods)))
        try:
            with SCHEDULE_DURATION.measure():
                results = self.schedule(pods)
        finally:
            QUEUE_DEPTH.set(0.0)
        created = self.create_node_claims(results)
        bound = self._bind_to_existing(results)
        self.log.info(
            "provisioning round complete",
            pods=len(pods),
            new_claims=len(created),
            bound_to_existing=len(bound),
            errors=len(results.pod_errors),
            solver=self.last_solver_used,
            timed_out=results.timed_out,
        )
        UNSCHEDULABLE_PODS.set(float(len(results.pod_errors)), {"state": "unschedulable"})
        for uid, reason in results.pod_errors.items():
            pod = next((p for p in pods if p.uid == uid), None)
            if pod is not None:
                self.recorder.publish(
                    Event("Pod", pod.name, "Warning", "FailedScheduling", reason)
                )
        return ProvisioningResult(results=results, created_claims=created, bound_pods=bound)

    def schedule(self, pods: list[Pod]) -> Results:
        """provisioner.go:303 Schedule: build scheduler inputs from live
        cluster state and run one Solve. The whole Solve rides ONE trace
        (karpenter_tpu.tracing) from here down — through ResilientSolver,
        the wire client, and the kernel driver's host phases — landing in
        the /debug/solves ring; `last_trace` exposes it to tests."""
        with tracing.maybe_trace(None, "provisioning") as tr:
            self.last_trace = tr
            tr.annotate(pods=len(pods))
            with tr.span("build_inputs"):
                node_pools = [
                    np
                    for np in self.kube.list("NodePool")
                    if np.replicas is None  # static pools have their own loop
                ]
                its_by_pool = {
                    np.name: self.cloud.get_instance_types(np)
                    for np in node_pools
                }
                daemonset_pods = [
                    ds.pod_template for ds in self.kube.list("DaemonSet")
                ]
                pods = [p.deep_copy() for p in pods]
                for p in pods:
                    self.volume_topology.inject(p)  # provisioner.go:286
                views = self.cluster.schedulable_node_views()

                scheduler_options = SchedulerOptions(
                    ignore_preferences=self.opts.preference_policy == "Ignore",
                    min_values_best_effort=self.opts.min_values_policy
                    == "BestEffort",
                    reserved_capacity_enabled=(
                        self.opts.feature_gates.reserved_capacity
                    ),
                    timeout_seconds=self.opts.solve_timeout_seconds,
                    claim_slot_div=self.opts.tpu_claim_slot_div,
                    tpu_min_pods=self.opts.tpu_min_pods,
                )
                source = cluster_source(self.kube, self.cluster)

            if self.solver is not None:
                # The resilient sidecar boundary: remote solve under a
                # circuit breaker, in-process ladder as the floor. Never
                # raises for solver-side faults — every pending pod gets a
                # decision (or a pod_error) in THIS reconcile.
                results = self.solver.solve(
                    node_pools,
                    its_by_pool,
                    pods,
                    state_node_views=views,
                    daemonset_pods=daemonset_pods,
                    options=scheduler_options,
                    cluster=source,
                    force_oracle=self.force_oracle,
                    trace=tr,
                )
                self.last_solver_used = self.solver.last_used
                tr.annotate(solver=self.last_solver_used)
                if self.solver.fallback_reason:
                    self.log.info(
                        "solver degraded",
                        reason=self.solver.fallback_reason,
                        solver=self.last_solver_used,
                    )
                return results

            results, scheduler = solve_in_process(
                node_pools,
                its_by_pool,
                pods,
                views,
                daemonset_pods,
                scheduler_options,
                cluster=source,
                force_oracle=self.force_oracle,
                trace=tr,
            )
            self.last_solver_used = "tpu" if scheduler.used_tpu else "oracle"
            tr.annotate(solver=self.last_solver_used)
            return results

    def create_node_claims(self, results: Results) -> list[NodeClaim]:
        """provisioner.go:407 Create: persist NodeClaims for the solver's
        new nodes, update state pre-watch (provisioner.go:448)."""
        created = []
        for claim in results.new_node_claims:
            if not claim.pods:
                continue
            _claim_name_seq[0] += 1
            nc = claim.to_node_claim()
            nc.metadata.name = f"{claim.nodepool_name}-{_claim_name_seq[0]:05d}"
            stored = self.kube.create("NodeClaim", nc)
            created.append(stored)
            # informers already saw the create event synchronously; nominate
            # the in-flight capacity so disruption keeps its hands off
            sn = self.cluster.node_by_claim_name(stored.name)
            if sn is not None:
                sn.nominate(self.clock.now())
            self.recorder.publish(
                Event(
                    "NodeClaim",
                    stored.name,
                    "Normal",
                    "Launched",
                    f"claim for {len(claim.pods)} pods",
                )
            )
        return created

    def _bind_to_existing(self, results: Results) -> dict[str, str]:
        """Bind pods the solver placed on ready existing nodes (standing in
        for the kube-scheduler; reference nominates and lets kube-scheduler
        bind). Only provisionable (unbound) pods bind — pods from deleting
        nodes are in the solve for replacement sizing and must go through
        the drain/eviction path, never teleport."""
        bound: dict[str, str] = {}
        assignments: dict[str, str] = {}
        for node in results.existing_nodes:
            if not node.pods:
                continue
            # in-flight claim-only views resolve by claim name
            sn = self.cluster.node_by_name(node.name) or (
                self.cluster.node_by_claim_name(node.name)
            )
            if sn is None:
                continue
            sn.nominate(self.clock.now())
            if sn.node is None or not sn.node.ready:
                # in-flight capacity: the placement is a DECISION (keeps
                # the nomination window fresh + the undecided metric
                # honest) but binding waits for the node to be ready
                for pod in node.pods:
                    assignments[pod.uid] = node.name
                continue
            for pod in node.pods:
                stored = self.kube.try_get("Pod", pod.name)
                if stored is None or not is_provisionable(stored):
                    continue
                try:
                    self.kube.bind(pod.name, node.name)
                except NotFound:
                    continue
                bound[pod.name] = node.name
                assignments[pod.uid] = node.name
                self.recorder.publish(
                    Event("Pod", pod.name, "Normal", "Nominated", node.name)
                )
        self.cluster.mark_pod_scheduling_decisions(assignments)
        return bound
