"""The control plane (reference pkg/controllers): a set of controllers
sharing an in-memory cluster-state cache, driving the scheduling core, and
talking to a cloud provider.

The reference's distributed-coordination backend is the kube-apiserver
(watch/list/update with optimistic concurrency, SURVEY.md §5.8). This
framework keeps that architecture with `SimKube` as the API store —
in-process here; the same controller code runs against a real apiserver by
swapping the store implementation. The solve plane (karpenter_tpu.solver)
receives problems through the HybridScheduler dispatch.
"""

from karpenter_tpu.controllers.kube import Conflict, FakeClock, RealClock, SimKube

__all__ = ["SimKube", "Conflict", "FakeClock", "RealClock"]
