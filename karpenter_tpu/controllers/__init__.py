"""The control plane (reference pkg/controllers): a set of controllers
sharing an in-memory cluster-state cache, driving the scheduling core, and
talking to a cloud provider.

The reference's distributed-coordination backend is the kube-apiserver
(watch/list/update with optimistic concurrency, SURVEY.md §5.8). This
framework keeps that architecture with `SimKube` as the API store —
in-process here; the same controller code runs against a real apiserver by
swapping the store implementation. The solve plane (karpenter_tpu.solver)
receives problems through the HybridScheduler dispatch.
"""

from karpenter_tpu.controllers.kube import Conflict, FakeClock, RealClock, SimKube
from karpenter_tpu.controllers.lifecycle import NodeClaimLifecycle
from karpenter_tpu.controllers.operator import Operator
from karpenter_tpu.controllers.provisioning import Batcher, Provisioner, VolumeTopology
from karpenter_tpu.controllers.state import Cluster, StateNode, wire_informers
from karpenter_tpu.controllers.termination import NodeTermination

__all__ = [
    "Batcher",
    "Cluster",
    "Conflict",
    "FakeClock",
    "NodeClaimLifecycle",
    "NodeTermination",
    "Operator",
    "Provisioner",
    "RealClock",
    "SimKube",
    "StateNode",
    "VolumeTopology",
    "wire_informers",
]
