"""Node termination: the finalizer-driven drain.

Reference /root/reference/pkg/controllers/node/termination/:
- controller.go:91-289 (taint -> drain -> volume detach -> instance delete)
- terminator/terminator.go:96-176 (priority-grouped eviction, grace periods)
- terminator/eviction.go:93-230 (PDB-aware eviction queue)

Flow per reconcile of a deleting Node:
1. ensure the disrupted NoSchedule taint,
2. evict evictable pods in priority groups (PDB-gated), daemonsets last,
3. once drained, await VolumeAttachment deletion (the external
   attach-detach controller's job; skipped once terminationGracePeriod
   elapses — controller.go:223-252),
4. delete the cloud instance and drop the finalizer (the Node object then
   vanishes; the claim's finalizer completes next).
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import Node, Pod, PodPhase
from karpenter_tpu.cloudprovider.types import NodeClaimNotFoundError
from karpenter_tpu.controllers.kube import Conflict, NotFound, SimKube
from karpenter_tpu.controllers.state import DISRUPTED_TAINT, Cluster
from karpenter_tpu.events import Event, Recorder
from karpenter_tpu import logging, metrics

NODES_DRAINED = metrics.REGISTRY.counter(
    "karpenter_nodes_drained_total", "Nodes fully drained by termination.", ("nodepool",)
)
PODS_EVICTED = metrics.REGISTRY.counter(
    "karpenter_nodes_evicted_pods_total", "Pods evicted during node drain."
)


def is_evictable(pod: Pod) -> bool:
    """terminator.go:96 groupPodsByPriority candidates: running/pending pods
    that aren't already terminal or terminating."""
    return (
        pod.phase in (PodPhase.PENDING, PodPhase.RUNNING)
        and pod.metadata.deletion_timestamp is None
        and not pod.terminating
    )


def is_daemonset(pod: Pod) -> bool:
    return bool(pod.metadata.annotations.get("karpenter.sh/daemonset"))


class NodeTermination:
    def __init__(
        self,
        kube: SimKube,
        cluster: Cluster,
        cloud_provider,
        clock,
        recorder: Optional[Recorder] = None,
        workers: int = 1,
    ):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud_provider
        self.clock = clock
        self.recorder = recorder or Recorder(clock)
        self.log = logging.root.named("node.termination")
        # reconciler pool width (reference termination/controller.go:58-60
        # scales 100->5000). Per-node reconciles are independent; SimKube
        # ops are atomic and cross-reconcile races surface as Conflict,
        # which reconcile() already treats as requeue-next-tick. PDB
        # accounting lives OUTSIDE that optimistic concurrency, so
        # evictions serialize under _evict_lock — the analog of the
        # reference's single eviction queue (terminator/eviction.go:93),
        # which exists for exactly this reason. Acquisition order is
        # _evict_lock -> SimKube._lock (evictions do CRUD under the evict
        # lock; SimKube never calls out while holding its own lock), so
        # the pair is acyclic. NOTE: this direction is argued, not
        # mechanically pinned — the graftlint race tier's static graph
        # follows same-class/same-module calls only, so an edge through
        # self.kube.* is invisible to it, and the racert witness only
        # rides the `faults` suite, which does not drive concurrent
        # evictions. Re-argue this ordering when touching either lock.
        import threading

        self._evict_lock = threading.Lock()
        self.workers = workers

    def reconcile_all(self) -> None:
        from karpenter_tpu.utils.workerpool import parallelize_until

        names = [
            node.name
            for node in self.kube.list("Node")
            if node.metadata.deletion_timestamp is not None
        ]
        errs = parallelize_until(
            self.workers, len(names), lambda i: self.reconcile(names[i])
        )
        for name, err in zip(names, errs):
            if err is not None:
                self.log.error(
                    "termination reconcile failed", node=name, error=str(err)
                )

    def reconcile(self, name: str) -> Optional[str]:
        node = self.kube.try_get("Node", name)
        if node is None:
            return None
        if node.metadata.deletion_timestamp is None:
            return None
        if well_known.TERMINATION_FINALIZER not in node.metadata.finalizers:
            return None

        # 1. taint (terminator.go Taint; statenode.go:483 RequireNoScheduleTaint)
        if DISRUPTED_TAINT not in node.taints:
            node.taints = list(node.taints) + [DISRUPTED_TAINT]
            try:
                node = self.kube.update("Node", node)
            except (Conflict, NotFound):
                return None
            if node is None:
                return None

        # enforce terminationGracePeriod on the claim if set
        claim = self._claim_for(node)
        force = False
        if (
            claim is not None
            and claim.termination_grace_period_seconds is not None
            and node.metadata.deletion_timestamp is not None
        ):
            force = (
                self.clock.now() - node.metadata.deletion_timestamp
                > claim.termination_grace_period_seconds
            )

        # 2. drain: evict in ascending priority groups, workload pods before
        # daemonset pods (terminator.go:96 groupPodsByPriority)
        pods = [p for p in self.kube.list("Pod") if p.node_name == name]
        workload = [p for p in pods if is_evictable(p) and not is_daemonset(p)]
        if workload:
            lowest = min(p.priority for p in workload)
            group = [p for p in workload if p.priority == lowest]
            evicted = self._evict(group, force)
            if evicted:
                return "draining"
            if not force:
                return "drain-blocked"
        daemons = [p for p in pods if is_evictable(p) and is_daemonset(p)]
        if daemons:
            if self._evict(daemons, force):
                return "draining"
            if not force:
                return "drain-blocked"
        # terminating pods still exiting?
        if any(
            p.terminating or p.metadata.deletion_timestamp is not None
            for p in pods
        ):
            self._finish_evictions(name)
            if any(p.node_name == name for p in self.kube.list("Pod")):
                return "awaiting-pod-exit"

        nodepool = node.metadata.labels.get(well_known.NODEPOOL_LABEL_KEY, "")
        NODES_DRAINED.inc({"nodepool": nodepool})

        # 3. await volume detachment (controller.go:223-252): the external
        # attach-detach controller deletes VolumeAttachments after unmount;
        # instance deletion blocks until the node's attachments are gone —
        # unless the claim's terminationGracePeriod has elapsed (force),
        # matching hasTerminationGracePeriodElapsed's skip.
        if not force:
            pending = self._pending_volume_attachments(name)
            if pending:
                self.recorder.publish(
                    Event(
                        "Node", name, "Normal", "AwaitingVolumeDetachment",
                        f"awaiting deletion of {len(pending)} volume "
                        "attachment(s)",
                    )
                )
                return "awaiting-volume-detachment"

        # 4. instance deletion + finalizer removal (controller.go:269)
        if claim is not None:
            try:
                self.cloud.delete(claim)
            except NodeClaimNotFoundError:
                pass
        node = self.kube.try_get("Node", name)
        if node is None:
            return "terminated"
        node.metadata.finalizers = [
            f for f in node.metadata.finalizers if f != well_known.TERMINATION_FINALIZER
        ]
        try:
            self.kube.update("Node", node)
        except (Conflict, NotFound):
            return None
        self.recorder.publish(
            Event("Node", name, "Normal", "Terminated", "node drained and removed")
        )
        self.log.info("terminated node", node=name, nodepool=nodepool)
        return "terminated"

    def _pending_volume_attachments(self, node_name: str) -> list:
        """controller.go:296 pendingVolumeAttachments: the node's
        VolumeAttachments minus those belonging to non-drainable pods
        (filterVolumeAttachments — pods termination won't evict keep their
        volumes mounted forever; waiting on them would deadlock)."""
        vas = self.kube.list(
            "VolumeAttachment", lambda va: va.node_name == node_name
        )
        if not vas:
            return []
        undrainable_vols: set[str] = set()
        for p in self.kube.list("Pod"):
            if p.node_name == node_name and not is_evictable(p):
                undrainable_vols.update(p.volume_claims)
        return [va for va in vas if va.volume_name not in undrainable_vols]

    # -- eviction ---------------------------------------------------------

    def _evict(self, pods: list[Pod], force: bool) -> int:
        """PDB-aware evictions (eviction.go:93). Returns how many started.
        Snapshot-to-mark is atomic under _evict_lock: two workers evicting
        different pods under one PDB would otherwise both act on a stale
        allowed-count and jointly overrun the budget."""
        with self._evict_lock:
            return self._evict_locked(pods, force)

    def _evict_locked(self, pods: list[Pod], force: bool) -> int:
        from karpenter_tpu.utils.pdb import PDBLimits

        limits = PDBLimits.from_kube(self.kube)
        count = 0
        for pod in pods:
            if not force:
                blocked = limits.is_fully_blocked(pod)
                ok, reason = limits.can_evict(pod)
                if blocked is not None or not ok:
                    self.recorder.publish(
                        Event(
                            "Pod", pod.name, "Warning", "EvictionBlocked",
                            blocked or reason or "",
                        )
                    )
                    continue
                limits.record_eviction(pod)
            pod.terminating = True
            try:
                self.kube.update("Pod", pod)
            except (Conflict, NotFound):
                continue
            PODS_EVICTED.inc()
            count += 1
        return count

    def _finish_evictions(self, node_name: str) -> None:
        """Terminating pods exit after their grace period (the kubelet's
        role, simulated)."""
        for pod in self.kube.list("Pod"):
            if pod.node_name != node_name or not pod.terminating:
                continue
            try:
                self.kube.delete("Pod", pod.name)
            except NotFound:
                pass

    def _claim_for(self, node: Node):
        sn = self.cluster.node_by_name(node.name)
        if sn is not None and sn.node_claim is not None:
            return self.kube.try_get("NodeClaim", sn.node_claim.name)
        return None
