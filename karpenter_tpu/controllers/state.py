"""Cluster-state cache (L3): the in-memory mirror every controller reads.

Reference: Cluster (/root/reference/pkg/controllers/state/cluster.go:54-210),
StateNode (statenode.go:119-560), informer controllers
(state/informer/{pod,node,nodeclaim,nodepool,daemonset}.go).

`wire_informers` subscribes the cluster to SimKube watch events, exactly like
the reference's informer controllers feed Cluster from apiserver watches. The
`synced` barrier replicates cluster.go:118 Synced(): no scheduling or
disruption decision may run until the cache reflects every NodeClaim/Node in
the store — the logical-race guard that makes solver state safely ephemeral
(SURVEY.md §5.4).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    COND_INITIALIZED,
    COND_REGISTERED,
    Node,
    NodeClaim,
    NodePool,
    Pod,
    PodPhase,
    Taint,
)
from karpenter_tpu.scheduling.hostports import HostPortUsage, get_host_ports
from karpenter_tpu.scheduling.volumeusage import VolumeUsage
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.resources import ResourceList

# The taint the lifecycle controller removes at registration
# (reference apis/v1/taints.go UnregisteredNoExecuteTaint)
UNREGISTERED_TAINT = Taint(
    key="karpenter.sh/unregistered", effect="NoExecute", value=""
)
# Disruption's "disrupting" taint (reference apis/v1/taints.go DisruptedNoScheduleTaint)
DISRUPTED_TAINT = Taint(key="karpenter.sh/disrupted", effect="NoSchedule", value="")

NOMINATION_WINDOW_SECONDS = 20.0  # statenode.go:431 nomination window


def is_provisionable(pod: Pod) -> bool:
    """pod.IsProvisionable (reference pkg/utils/pod/scheduling.go:42): pending,
    unbound, not gated, not terminating."""
    return (
        not pod.node_name
        and pod.phase == PodPhase.PENDING
        and not pod.scheduling_gates
        and pod.metadata.deletion_timestamp is None
        and not pod.terminating
    )


def is_reschedulable(pod: Pod) -> bool:
    """Pods worth rescheduling when their node goes away (reference
    pkg/utils/pod/scheduling.go IsReschedulable): running/pending workload
    pods, not terminal, not terminating, and not owned by a node (daemonset
    pods are re-created by their controller on the replacement node)."""
    return (
        pod.phase in (PodPhase.PENDING, PodPhase.RUNNING)
        and pod.metadata.deletion_timestamp is None
        and not pod.terminating
        and not pod.metadata.annotations.get("karpenter.sh/daemonset")
    )


def has_required_anti_affinity(pod: Pod) -> bool:
    return bool(pod.pod_anti_affinity)


class StateNode:
    """A Node+NodeClaim pair keyed by provider id (statenode.go:119)."""

    def __init__(self) -> None:
        self.node: Optional[Node] = None
        self.node_claim: Optional[NodeClaim] = None
        self.marked_for_deletion: bool = False
        self.nominated_until: float = 0.0
        # pod uid -> requests (bound pods), split daemonset vs workload
        self.pod_requests: dict[str, ResourceList] = {}
        self.daemonset_requests: dict[str, ResourceList] = {}
        self.host_port_usage = HostPortUsage()
        self.volume_usage = VolumeUsage()

    # -- identity ---------------------------------------------------------

    @property
    def name(self) -> str:
        if self.node is not None:
            return self.node.name
        return self.node_claim.status.node_name or self.node_claim.name

    @property
    def provider_id(self) -> str:
        if self.node is not None and self.node.provider_id:
            return self.node.provider_id
        if self.node_claim is not None:
            return self.node_claim.status.provider_id or f"claim://{self.node_claim.name}"
        return ""

    @property
    def nodepool_name(self) -> Optional[str]:
        return self.labels().get(well_known.NODEPOOL_LABEL_KEY)

    def owned(self) -> bool:
        """Managed by this autoscaler (has a NodeClaim or the nodepool label)."""
        return self.node_claim is not None or (
            self.node is not None
            and well_known.NODEPOOL_LABEL_KEY in self.node.metadata.labels
        )

    # -- shape ------------------------------------------------------------

    def labels(self) -> dict[str, str]:
        if self.node is not None:
            return dict(self.node.metadata.labels)
        if self.node_claim is not None:
            out = dict(self.node_claim.metadata.labels)
            for r in self.node_claim.requirements:
                if r.operator == "In" and len(r.values) == 1:
                    out.setdefault(r.key, r.values[0])
            return out
        return {}

    def taints(self) -> list[Taint]:
        """Registered nodes: real node taints minus the bootstrap taint.

        UNINITIALIZED nodes that are MANAGED (node_claim present —
        statenode.go:439 Managed) reject the well-known ephemeral taints
        (not-ready/unreachable/...) and the claim's startup taints
        (statenode.go:311-325): those are expected to clear before
        initialization, so the scheduler assumes pods can land once they
        do. The same rejection applies to in-flight claims that have no
        node yet — their startup taints never block scheduling before
        initialization. After initialization every taint is taken at face
        value (a re-appearing not-ready then means cordoned); claim-less
        labeled nodes always are (the reference treats them unmanaged)."""
        from karpenter_tpu.scheduling.taints import KNOWN_EPHEMERAL_TAINTS

        managed = self.node_claim is not None
        assume_boot = managed and not self.initialized()

        def reject_boot(taints: list[Taint]) -> list[Taint]:
            # MatchTaint semantics: key + effect (value ignored)
            reject = {
                (t.key, t.effect)
                for t in list(KNOWN_EPHEMERAL_TAINTS)
                + list(self.node_claim.startup_taints)
            }
            return [t for t in taints if (t.key, t.effect) not in reject]

        if self.node is not None and self.registered():
            taints = [t for t in self.node.taints if t != UNREGISTERED_TAINT]
            if assume_boot:
                taints = reject_boot(taints)
            return taints
        # remaining cases all carry a claim: claim-only, or a joined node
        # that hasn't registered (registered() is True whenever node is
        # present WITHOUT a claim, so that combination never reaches here)
        if self.node_claim is not None:
            out = list(self.node_claim.taints) + list(
                self.node_claim.startup_taints
            )
            return reject_boot(out) if assume_boot else out
        return []

    def capacity(self) -> ResourceList:
        if self.node is not None and self.node.capacity:
            return dict(self.node.capacity)
        if self.node_claim is not None:
            return dict(self.node_claim.status.capacity)
        return {}

    def allocatable(self) -> ResourceList:
        if self.node is not None and self.node.allocatable:
            return dict(self.node.allocatable)
        if self.node_claim is not None:
            return dict(self.node_claim.status.allocatable)
        return {}

    # -- lifecycle --------------------------------------------------------

    def registered(self) -> bool:
        if self.node_claim is not None:
            return self.node_claim.status.conditions.get(COND_REGISTERED) == "True"
        return self.node is not None  # unmanaged nodes are registered by definition

    def initialized(self) -> bool:
        if self.node_claim is not None:
            return self.node_claim.status.conditions.get(COND_INITIALIZED) == "True"
        return self.node is not None and self.node.ready

    def deleting(self) -> bool:
        if self.node is not None and self.node.metadata.deletion_timestamp is not None:
            return True
        if (
            self.node_claim is not None
            and self.node_claim.metadata.deletion_timestamp is not None
        ):
            return True
        return False

    def nominate(self, now: float) -> None:
        self.nominated_until = now + NOMINATION_WINDOW_SECONDS

    def nominated(self, now: float) -> bool:
        return now < self.nominated_until

    # -- resources --------------------------------------------------------

    def pods_requests_total(self) -> ResourceList:
        out: ResourceList = {}
        for r in self.pod_requests.values():
            out = res.merge(out, r)
        return out

    def daemonset_requests_total(self) -> ResourceList:
        out: ResourceList = {}
        for r in self.daemonset_requests.values():
            out = res.merge(out, r)
        return out

    def available(self) -> ResourceList:
        """allocatable minus all bound pod requests (workload + daemon)."""
        used = res.merge(self.pods_requests_total(), self.daemonset_requests_total())
        return res.subtract(self.allocatable(), used)

    # -- views ------------------------------------------------------------

    def to_view(self) -> StateNodeView:
        return StateNodeView(
            name=self.name,
            node_labels=dict(self.node.metadata.labels) if self.node else None,
            labels=self.labels(),
            taints=self.taints(),
            available=self.available(),
            capacity=self.capacity(),
            daemonset_requests=self.daemonset_requests_total(),
            initialized=self.initialized(),
            hostname=self.labels().get(well_known.HOSTNAME_LABEL_KEY, self.name),
            host_port_usage=self.host_port_usage.copy(),
            volume_usage=self.volume_usage.copy(),
            csi_allocatable=dict(self.node.csi_allocatable)
            if self.node is not None
            else {},
        )


class NodePoolState:
    """statenodepool.go: per-pool active / deleting / pending-disruption
    NodeClaim name sets plus node-count reservations. The reservation path
    lets static provisioning and StaticDrift scale decisions coordinate
    against a pool's `nodes` limit without bursting over it
    (statenodepool.go:137 ReserveNodeCount)."""

    def __init__(self) -> None:
        self._pools: dict[str, dict[str, set[str]]] = {}
        self._claim_to_pool: dict[str, str] = {}
        self._reserved: dict[str, int] = {}

    def _entry(self, pool: str) -> dict[str, set[str]]:
        e = self._pools.get(pool)
        if e is None:
            e = {"active": set(), "deleting": set(), "pending": set()}
            self._pools[pool] = e
            self._reserved.setdefault(pool, 0)
        return e

    def mark_active(self, pool: str, claim: str) -> None:
        e = self._entry(pool)
        e["pending"].discard(claim)
        e["deleting"].discard(claim)
        e["active"].add(claim)
        self._claim_to_pool[claim] = pool

    def mark_deleting(self, pool: str, claim: str) -> None:
        e = self._entry(pool)
        e["pending"].discard(claim)
        e["active"].discard(claim)
        e["deleting"].add(claim)
        self._claim_to_pool[claim] = pool

    def mark_pending_disruption(self, pool: str, claim: str) -> None:
        e = self._entry(pool)
        e["active"].discard(claim)
        e["deleting"].discard(claim)
        e["pending"].add(claim)
        self._claim_to_pool[claim] = pool

    def cleanup(self, claim: str) -> None:
        """statenodepool.go:106: drop the claim; drop the pool entry once
        nothing active or deleting remains."""
        pool = self._claim_to_pool.pop(claim, None)
        if pool is None:
            return
        e = self._pools.get(pool)
        if e is None:
            return
        for s in e.values():
            s.discard(claim)
        if not e["active"] and not e["deleting"]:
            self._pools.pop(pool, None)
            # reservations held by in-flight commands must survive the pool
            # entry going empty, or a concurrent scale-up could burst the
            # node limit while the command's launch is still pending
            if self._reserved.get(pool, 0) == 0:
                self._reserved.pop(pool, None)

    def node_counts(self, pool: str) -> tuple[int, int, int]:
        """(active, deleting, pending_disruption)"""
        e = self._pools.get(pool)
        if e is None:
            return 0, 0, 0
        return len(e["active"]), len(e["deleting"]), len(e["pending"])

    def reserve_node_count(self, pool: str, limit: float, wanted: int) -> int:
        """Grant up to `wanted` new-node reservations without active +
        deleting + pending + reserved exceeding `limit`."""
        self._entry(pool)
        a, d, p = self.node_counts(pool)
        remaining = limit - (a + d + p) - self._reserved[pool]
        if remaining < 0:
            return 0
        granted = int(min(wanted, remaining))
        self._reserved[pool] += max(0, granted)
        return max(0, granted)

    def release_node_count(self, pool: str, count: int = 1) -> None:
        self._reserved[pool] = max(0, self._reserved.get(pool, 0) - count)

    def update_node_claim(self, claim: NodeClaim, marked_for_deletion: bool) -> None:
        pool = claim.nodepool_name
        if not pool:
            return
        if marked_for_deletion:
            self.mark_deleting(pool, claim.name)
        else:
            self.mark_active(pool, claim.name)


class Cluster:
    """cluster.go:54 — the shared in-memory mirror."""

    def __init__(self, clock) -> None:
        self.clock = clock
        # set by wire_informers: fills pod.volume_drivers from PVC ->
        # StorageClass.provisioner (VolumeTopology.resolve_drivers)
        self.volume_driver_resolver = None
        self.nodes: dict[str, StateNode] = {}  # provider id -> StateNode
        self.node_name_to_pid: dict[str, str] = {}
        self.claim_name_to_pid: dict[str, str] = {}
        self.bindings: dict[str, str] = {}  # pod uid -> node name
        self.pods: dict[str, Pod] = {}  # pod uid -> latest copy
        self.nodepools: dict[str, NodePool] = {}
        self.daemonsets: dict[str, object] = {}
        self.anti_affinity_pods: dict[str, Pod] = {}
        # pod uid -> (node name decided, timestamp) from the last Solve
        self.pod_scheduling_decisions: dict[str, tuple[str, float]] = {}
        self._consolidated_at: float = -1.0
        self.nodepool_state = NodePoolState()  # cluster.go:68

    # -- Synced barrier (cluster.go:118) ---------------------------------

    def synced(self, kube) -> bool:
        """The state must be a superset of the store: every NodeClaim and
        Node currently in the store is reflected here. Controllers requeue
        until this holds (the logical-race guard)."""
        for claim in kube.list("NodeClaim"):
            if claim.name not in self.claim_name_to_pid:
                return False
        for node in kube.list("Node"):
            if node.name not in self.node_name_to_pid:
                return False
        return True

    # -- consolidation timestamp (cluster.go:550) ------------------------

    def mark_unconsolidated(self) -> None:
        self._consolidated_at = -1.0

    def mark_consolidated(self) -> None:
        self._consolidated_at = self.clock.now()

    def consolidated(self) -> bool:
        """True while nothing changed since the last full consolidation scan
        (5-minute falloff like the reference)."""
        return (
            self._consolidated_at >= 0
            and self.clock.now() - self._consolidated_at < 300.0
        )

    # -- node/claim ingestion --------------------------------------------

    def _state_node_for(self, pid: str) -> StateNode:
        sn = self.nodes.get(pid)
        if sn is None:
            sn = StateNode()
            self.nodes[pid] = sn
        return sn

    def _rekey(self, old_pid: str, new_pid: str) -> None:
        if old_pid == new_pid or old_pid not in self.nodes:
            return
        moved = self.nodes.pop(old_pid)
        existing = self.nodes.get(new_pid)
        if existing is not None:
            # merge: keep the richer side (node from one, claim from other)
            existing.node = existing.node or moved.node
            existing.node_claim = existing.node_claim or moved.node_claim
            existing.marked_for_deletion |= moved.marked_for_deletion
            moved = existing
        self.nodes[new_pid] = moved
        for m in (self.node_name_to_pid, self.claim_name_to_pid):
            for name, pid in list(m.items()):
                if pid == old_pid:
                    m[name] = new_pid

    def update_nodeclaim(self, claim: NodeClaim) -> None:
        old_pid = self.claim_name_to_pid.get(claim.name)
        new_pid = claim.status.provider_id or f"claim://{claim.name}"
        if old_pid is not None and old_pid != new_pid:
            self._rekey(old_pid, new_pid)
        sn = self._state_node_for(new_pid)
        sn.node_claim = claim
        self.claim_name_to_pid[claim.name] = new_pid
        # cluster.go:331: keep the per-pool claim-state sets in step
        self.nodepool_state.update_node_claim(
            claim,
            claim.metadata.deletion_timestamp is not None or sn.marked_for_deletion,
        )
        self.mark_unconsolidated()

    def delete_nodeclaim(self, name: str) -> None:
        self.nodepool_state.cleanup(name)  # cluster.go:678
        pid = self.claim_name_to_pid.pop(name, None)
        if pid is None:
            return
        sn = self.nodes.get(pid)
        if sn is not None:
            sn.node_claim = None
            if sn.node is None:
                del self.nodes[pid]
        self.mark_unconsolidated()

    def update_node(self, node: Node) -> None:
        old_pid = self.node_name_to_pid.get(node.name)
        new_pid = node.provider_id or f"node://{node.name}"
        if old_pid is not None and old_pid != new_pid:
            self._rekey(old_pid, new_pid)
        # a claim may already hold this provider id
        if node.provider_id and node.provider_id not in self.nodes:
            # the claim might be keyed by claim:// placeholder; match by
            # status.node_name
            for pid, sn in list(self.nodes.items()):
                if (
                    sn.node_claim is not None
                    and sn.node_claim.status.provider_id == node.provider_id
                ):
                    self._rekey(pid, node.provider_id)
                    break
        sn = self._state_node_for(new_pid)
        sn.node = node
        self.node_name_to_pid[node.name] = new_pid
        # backfill pods bound to this node before it reached the cache (the
        # pod informer fired first): their requests were never tallied
        for uid, bound_node in self.bindings.items():
            if bound_node != node.name:
                continue
            pod = self.pods.get(uid)
            if pod is None or uid in sn.pod_requests or uid in sn.daemonset_requests:
                continue
            self._apply_bind(pod, sn)
        self.mark_unconsolidated()

    def delete_node(self, name: str) -> None:
        pid = self.node_name_to_pid.pop(name, None)
        if pid is None:
            return
        sn = self.nodes.get(pid)
        if sn is not None:
            sn.node = None
            if sn.node_claim is None:
                del self.nodes[pid]
        self.mark_unconsolidated()

    # -- pod ingestion ----------------------------------------------------

    def update_pod(self, pod: Pod) -> None:
        uid = pod.uid
        # only TERMINAL pods release their node usage (cluster.go UpdatePod):
        # a deleting-but-running pod still occupies capacity and still pins
        # its anti-affinity domains until the delete event arrives
        gone = pod.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED)
        old_node = self.bindings.get(uid)
        if old_node is not None and (gone or pod.node_name != old_node):
            self._unbind(uid, old_node)
        if not gone and pod.node_name and self.bindings.get(uid) != pod.node_name:
            self._bind(pod, pod.node_name)
        if gone:
            self.pods.pop(uid, None)
            self.anti_affinity_pods.pop(uid, None)
        else:
            self.pods[uid] = pod
            if has_required_anti_affinity(pod):
                self.anti_affinity_pods[uid] = pod
            else:
                self.anti_affinity_pods.pop(uid, None)
        self.mark_unconsolidated()

    def delete_pod(self, pod: Pod) -> None:
        uid = pod.uid
        old_node = self.bindings.get(uid)
        if old_node is not None:
            self._unbind(uid, old_node)
        self.pods.pop(uid, None)
        self.anti_affinity_pods.pop(uid, None)
        self.pod_scheduling_decisions.pop(uid, None)
        self.mark_unconsolidated()

    def _bind(self, pod: Pod, node_name: str) -> None:
        self.bindings[pod.uid] = node_name
        pid = self.node_name_to_pid.get(node_name)
        sn = self.nodes.get(pid) if pid else None
        if sn is None:
            return  # node not cached yet; update_node backfills on arrival
        self._apply_bind(pod, sn)

    def _apply_bind(self, pod: Pod, sn: StateNode) -> None:
        requests = res.requests_for_pods([pod])
        if pod.metadata.annotations.get("karpenter.sh/daemonset"):
            sn.daemonset_requests[pod.uid] = requests
        else:
            sn.pod_requests[pod.uid] = requests
        sn.host_port_usage.add(pod, get_host_ports(pod))
        if pod.volume_claims and self.volume_driver_resolver is not None:
            # attribute the bound pod's volumes to their CSI drivers the
            # same way the provisioner's inject does — per-driver budgets
            # must see existing usage in the right bucket
            self.volume_driver_resolver(pod)
        sn.volume_usage.add(pod)

    def _unbind(self, uid: str, node_name: str) -> None:
        self.bindings.pop(uid, None)
        pid = self.node_name_to_pid.get(node_name)
        sn = self.nodes.get(pid) if pid else None
        if sn is None:
            return
        sn.pod_requests.pop(uid, None)
        sn.daemonset_requests.pop(uid, None)
        sn.host_port_usage.remove(uid)
        sn.volume_usage.remove(uid)

    # -- nodepool / daemonset --------------------------------------------

    def update_nodepool(self, np: NodePool) -> None:
        self.nodepools[np.name] = np
        self.mark_unconsolidated()

    def delete_nodepool(self, name: str) -> None:
        self.nodepools.pop(name, None)
        self.mark_unconsolidated()

    def update_daemonset(self, ds) -> None:
        self.daemonsets[ds.name] = ds
        self.mark_unconsolidated()

    def delete_daemonset(self, name: str) -> None:
        self.daemonsets.pop(name, None)

    # -- queries ----------------------------------------------------------

    def state_nodes(self) -> list[StateNode]:
        return list(self.nodes.values())

    def node_by_name(self, name: str) -> Optional[StateNode]:
        pid = self.node_name_to_pid.get(name)
        return self.nodes.get(pid) if pid else None

    def node_by_claim_name(self, name: str) -> Optional[StateNode]:
        pid = self.claim_name_to_pid.get(name)
        return self.nodes.get(pid) if pid else None

    def pods_on(self, node_name: str) -> list[Pod]:
        return [
            self.pods[uid]
            for uid, n in self.bindings.items()
            if n == node_name and uid in self.pods
        ]

    def mark_for_deletion(self, *names: str) -> None:
        for name in names:
            sn = self.node_by_name(name) or self.node_by_claim_name(name)
            if sn is not None:
                sn.marked_for_deletion = True
                if sn.node_claim is not None:  # cluster.go:308
                    self.nodepool_state.mark_deleting(
                        sn.nodepool_name or "", sn.node_claim.name
                    )
        self.mark_unconsolidated()

    def unmark_for_deletion(self, *names: str) -> None:
        for name in names:
            sn = self.node_by_name(name) or self.node_by_claim_name(name)
            if sn is not None:
                sn.marked_for_deletion = False
                if sn.node_claim is not None:  # cluster.go:291
                    self.nodepool_state.mark_active(
                        sn.nodepool_name or "", sn.node_claim.name
                    )

    def schedulable_node_views(self) -> list[StateNodeView]:
        """The ExistingNode inputs for a provisioning Solve: registered,
        not deleting, not marked for deletion (scheduler.go existing-node
        selection).

        LAUNCHED claim-only StateNodes (no registered node yet) are
        in-flight capacity exactly as in the reference (cluster.Nodes
        feeds them to the scheduler): pods placed on them nominate and
        stay pending until the node registers — _bind_to_existing skips
        nodes that aren't ready — so a cross-batch pod arriving during
        the registration window packs onto the in-flight claim instead of
        forking a second one (suite_test.go:1832). StateNode.taints()
        rejects their startup/ephemeral taints until initialization
        (statenode.go:311-325)."""
        out = []
        for sn in self.nodes.values():
            if sn.marked_for_deletion or sn.deleting():
                continue
            registered_node = sn.node is not None and sn.registered()
            # in-flight capacity: a LAUNCHED claim (capacity known) counts
            # whether its node hasn't appeared yet OR has joined but not
            # registered — both are the same window to the scheduler
            launched_claim = (
                sn.node_claim is not None
                and bool(sn.node_claim.status.provider_id)
                and bool(sn.node_claim.status.allocatable)
            )
            if not registered_node and not launched_claim:
                continue
            out.append(sn.to_view())
        return out

    def mark_pod_scheduling_decisions(
        self, assignments: dict[str, str]
    ) -> None:
        now = self.clock.now()
        for uid, node in assignments.items():
            self.pod_scheduling_decisions[uid] = (node, now)


def cluster_source(kube, cluster: "Cluster", exclude_nodes: frozenset = frozenset()):
    """The ClusterSource every scheduling simulation feeds Topology: all
    scheduled pods by namespace, node objects by name, and namespace labels
    for affinity namespaceSelector resolution (topology.go:328 countDomains
    + :503 buildNamespaceList)."""
    from karpenter_tpu.solver.topology import ClusterSource

    pods_by_ns: dict[str, list[Pod]] = {}
    for p in cluster.pods.values():
        if exclude_nodes and cluster.bindings.get(p.uid) in exclude_nodes:
            continue
        pods_by_ns.setdefault(p.namespace, []).append(p)
    nodes_by_name = {
        sn.name: sn.node
        for sn in cluster.state_nodes()
        if sn.node is not None and sn.name not in exclude_nodes
    }
    namespace_labels = {
        ns.name: dict(ns.labels) for ns in kube.list("Namespace")
    }
    return ClusterSource(pods_by_ns, nodes_by_name, namespace_labels)


def wire_informers(kube, cluster: Cluster) -> None:
    """Subscribe the cluster cache to SimKube watch events — the analog of
    the reference's five informer controllers (state/informer/*.go)."""
    from karpenter_tpu.controllers.provisioning import VolumeTopology

    cluster.volume_driver_resolver = VolumeTopology(kube).resolve_drivers

    def handler(event: str, kind: str, obj) -> None:
        deleted = event == "deleted"
        if kind == "NodeClaim":
            cluster.delete_nodeclaim(obj.name) if deleted else cluster.update_nodeclaim(obj)
        elif kind == "Node":
            cluster.delete_node(obj.name) if deleted else cluster.update_node(obj)
        elif kind == "Pod":
            cluster.delete_pod(obj) if deleted else cluster.update_pod(obj)
        elif kind == "NodePool":
            cluster.delete_nodepool(obj.name) if deleted else cluster.update_nodepool(obj)
        elif kind == "DaemonSet":
            cluster.delete_daemonset(obj.name) if deleted else cluster.update_daemonset(obj)

    kube.subscribe(handler)
