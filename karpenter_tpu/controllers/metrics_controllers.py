"""Cluster-observability metric controllers: per-node gauges, per-nodepool
limits/usage, and pod lifecycle timings.

Reference /root/reference/pkg/controllers/metrics/:
- node/controller.go:176 (per-node allocatable/usage gauge families)
- nodepool/controller.go:93 (limit gauges)
- pod/controller.go:209-447 (pod state, scheduling-undecided/unbound
  durations, startup time)
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.api.objects import PodPhase
from karpenter_tpu.controllers.state import Cluster, is_provisionable
from karpenter_tpu import metrics

NODE_ALLOCATABLE = metrics.REGISTRY.gauge(
    "karpenter_nodes_allocatable",
    "Node allocatable by resource type.",
    ("node_name", "nodepool", "resource_type"),
)
NODE_USAGE = metrics.REGISTRY.gauge(
    "karpenter_nodes_total_pod_requests",
    "Total pod requests per node by resource type.",
    ("node_name", "nodepool", "resource_type"),
)
NODEPOOL_LIMIT = metrics.REGISTRY.gauge(
    "karpenter_nodepools_limit",
    "NodePool resource limits.",
    ("nodepool", "resource_type"),
)
POD_STATE = metrics.REGISTRY.gauge(
    "karpenter_pods_current_state", "Pods by phase.", ("phase",)
)
POD_STARTUP = metrics.REGISTRY.histogram(
    "karpenter_pods_startup_duration_seconds",
    "Time from pod creation to running.",
)
POD_UNDECIDED = metrics.REGISTRY.gauge(
    "karpenter_pods_scheduling_undecided", "Provisionable pods with no decision yet."
)
# pod lifecycle timing family (pod/controller.go:286-447, round 5):
# per-pod "still waiting" gauges deleted on resolution + duration
# histograms observed once at the transition
POD_BOUND_DURATION = metrics.REGISTRY.histogram(
    "karpenter_pods_bound_duration_seconds",
    "Time from pod creation to binding (PodBoundDurationSeconds).",
)
POD_UNBOUND_TIME = metrics.REGISTRY.gauge(
    "karpenter_pods_current_unbound_time_seconds",
    "Per-pod time since creation while still unbound.",
    ("name", "namespace"),
)
POD_UNSTARTED_TIME = metrics.REGISTRY.gauge(
    "karpenter_pods_unstarted_time_seconds",
    "Per-pod time since creation while not yet running.",
    ("name", "namespace"),
)
POD_SCHEDULING_DECISION = metrics.REGISTRY.histogram(
    "karpenter_pods_scheduling_decision_duration_seconds",
    "Time from first seeing a provisionable pod to a scheduling decision.",
)

_node_store = metrics.Store(NODE_ALLOCATABLE)
_usage_store = metrics.Store(NODE_USAGE)


class NodeMetricsController:
    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def reconcile_all(self) -> None:
        seen = set()
        for sn in self.cluster.state_nodes():
            if sn.node is None:
                continue
            seen.add(sn.name)
            np_name = sn.nodepool_name or ""
            _node_store.update(
                sn.name,
                [
                    (
                        {
                            "node_name": sn.name,
                            "nodepool": np_name,
                            "resource_type": rname,
                        },
                        float(v),
                    )
                    for rname, v in sn.allocatable().items()
                ],
            )
            _usage_store.update(
                f"usage/{sn.name}",
                [
                    (
                        {
                            "node_name": sn.name,
                            "nodepool": np_name,
                            "resource_type": rname,
                        },
                        float(v),
                    )
                    for rname, v in sn.pods_requests_total().items()
                ],
            )
        # GC series for vanished nodes
        for key in list(_node_store._owned):
            if key not in seen:
                _node_store.delete(key)
        for key in list(_usage_store._owned):
            if key.startswith("usage/") and key[len("usage/"):] not in seen:
                _usage_store.delete(key)


class NodePoolMetricsController:
    def __init__(self, kube):
        self.kube = kube

    def reconcile_all(self) -> None:
        for np in self.kube.list("NodePool"):
            for rname, v in np.limits.items():
                NODEPOOL_LIMIT.set(
                    float(v), {"nodepool": np.name, "resource_type": rname}
                )


class PodMetricsController:
    """metrics/pod/controller.go:209-447, reduced to the sim's pod model:
    binding = node_name set (no PodScheduled condition object), started =
    phase Running. Waiting gauges are per-pod and deleted idempotently on
    resolution exactly like the reference's; durations observe once."""

    def __init__(self, kube, cluster: Cluster, clock):
        self.kube = kube
        self.cluster = cluster
        self.clock = clock
        self._started: set[str] = set()
        self._bound: set[str] = set()
        self._acked: dict[str, float] = {}  # uid -> first provisionable time
        self._decided: set[str] = set()
        # waiting gauges GC through keyed Stores (store.go:33), same
        # pattern the node gauge families use above
        self._unbound_store = metrics.Store(POD_UNBOUND_TIME)
        self._unstarted_store = metrics.Store(POD_UNSTARTED_TIME)

    def reconcile_all(self) -> None:
        now = self.clock.now()
        counts: dict[str, int] = {}
        undecided = 0
        live_waiting: set[tuple[str, str]] = set()
        live_uids: set[str] = set()
        for pod in self.kube.list("Pod"):
            live_uids.add(pod.uid)
            counts[str(pod.phase.value)] = counts.get(str(pod.phase.value), 0) + 1
            labels = {"name": pod.name, "namespace": pod.namespace}
            created = pod.metadata.creation_timestamp
            if is_provisionable(pod):
                self._acked.setdefault(pod.uid, now)
                if pod.uid not in self.cluster.pod_scheduling_decisions:
                    undecided += 1
            # scheduling-decision latency (pod/controller.go:263): ack ->
            # first decision recorded in cluster state
            if (
                pod.uid in self._acked
                and pod.uid not in self._decided
                and (
                    pod.uid in self.cluster.pod_scheduling_decisions
                    or pod.node_name
                )
            ):
                self._decided.add(pod.uid)
                POD_SCHEDULING_DECISION.observe(
                    max(0.0, now - self._acked[pod.uid])
                )
            key = f"{pod.namespace}/{pod.name}"
            # bound family (recordPodBoundMetric)
            if pod.node_name:
                if pod.uid not in self._bound:
                    self._bound.add(pod.uid)
                    POD_BOUND_DURATION.observe(max(0.0, now - created))
            elif pod.phase == PodPhase.PENDING:
                self._unbound_store.update(key, [(labels, max(0.0, now - created))])
                live_waiting.add(("unbound", key))
            # startup family (recordPodStartupMetric)
            if pod.phase == PodPhase.RUNNING:
                if pod.uid not in self._started:
                    self._started.add(pod.uid)
                    POD_STARTUP.observe(max(0.0, now - created))
            elif pod.phase == PodPhase.PENDING:
                self._unstarted_store.update(
                    key, [(labels, max(0.0, now - created))]
                )
                live_waiting.add(("unstarted", key))
        # resolved/vanished waiting series GC through the stores
        for store, kind in (
            (self._unbound_store, "unbound"),
            (self._unstarted_store, "unstarted"),
        ):
            for key in list(store._owned):
                if (kind, key) not in live_waiting:
                    store.delete(key)
        # prune per-uid tracking for pods that no longer exist — a churning
        # cluster must not grow these maps without bound
        self._started &= live_uids
        self._bound &= live_uids
        self._decided &= live_uids
        for uid in list(self._acked):
            if uid not in live_uids:
                del self._acked[uid]
        for phase, n in counts.items():
            POD_STATE.set(float(n), {"phase": phase})
        POD_UNDECIDED.set(float(undecided))
