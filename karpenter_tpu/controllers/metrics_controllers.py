"""Cluster-observability metric controllers: per-node gauges, per-nodepool
limits/usage, and pod lifecycle timings.

Reference /root/reference/pkg/controllers/metrics/:
- node/controller.go:176 (per-node allocatable/usage gauge families)
- nodepool/controller.go:93 (limit gauges)
- pod/controller.go:209-447 (pod state, scheduling-undecided/unbound
  durations, startup time)
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.api.objects import PodPhase
from karpenter_tpu.controllers.state import Cluster, is_provisionable
from karpenter_tpu import metrics

NODE_ALLOCATABLE = metrics.REGISTRY.gauge(
    "karpenter_nodes_allocatable",
    "Node allocatable by resource type.",
    ("node_name", "nodepool", "resource_type"),
)
NODE_USAGE = metrics.REGISTRY.gauge(
    "karpenter_nodes_total_pod_requests",
    "Total pod requests per node by resource type.",
    ("node_name", "nodepool", "resource_type"),
)
NODEPOOL_LIMIT = metrics.REGISTRY.gauge(
    "karpenter_nodepools_limit",
    "NodePool resource limits.",
    ("nodepool", "resource_type"),
)
POD_STATE = metrics.REGISTRY.gauge(
    "karpenter_pods_current_state", "Pods by phase.", ("phase",)
)
POD_STARTUP = metrics.REGISTRY.histogram(
    "karpenter_pods_startup_duration_seconds",
    "Time from pod creation to running.",
)
POD_UNDECIDED = metrics.REGISTRY.gauge(
    "karpenter_pods_scheduling_undecided", "Provisionable pods with no decision yet."
)

_node_store = metrics.Store(NODE_ALLOCATABLE)
_usage_store = metrics.Store(NODE_USAGE)


class NodeMetricsController:
    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def reconcile_all(self) -> None:
        seen = set()
        for sn in self.cluster.state_nodes():
            if sn.node is None:
                continue
            seen.add(sn.name)
            np_name = sn.nodepool_name or ""
            _node_store.update(
                sn.name,
                [
                    (
                        {
                            "node_name": sn.name,
                            "nodepool": np_name,
                            "resource_type": rname,
                        },
                        float(v),
                    )
                    for rname, v in sn.allocatable().items()
                ],
            )
            _usage_store.update(
                f"usage/{sn.name}",
                [
                    (
                        {
                            "node_name": sn.name,
                            "nodepool": np_name,
                            "resource_type": rname,
                        },
                        float(v),
                    )
                    for rname, v in sn.pods_requests_total().items()
                ],
            )
        # GC series for vanished nodes
        for key in list(_node_store._owned):
            if key not in seen:
                _node_store.delete(key)
        for key in list(_usage_store._owned):
            if key.startswith("usage/") and key[len("usage/"):] not in seen:
                _usage_store.delete(key)


class NodePoolMetricsController:
    def __init__(self, kube):
        self.kube = kube

    def reconcile_all(self) -> None:
        for np in self.kube.list("NodePool"):
            for rname, v in np.limits.items():
                NODEPOOL_LIMIT.set(
                    float(v), {"nodepool": np.name, "resource_type": rname}
                )


class PodMetricsController:
    def __init__(self, kube, cluster: Cluster, clock):
        self.kube = kube
        self.cluster = cluster
        self.clock = clock
        self._started: set[str] = set()

    def reconcile_all(self) -> None:
        counts: dict[str, int] = {}
        undecided = 0
        for pod in self.kube.list("Pod"):
            counts[str(pod.phase.value)] = counts.get(str(pod.phase.value), 0) + 1
            if is_provisionable(pod):
                if pod.uid not in self.cluster.pod_scheduling_decisions:
                    undecided += 1
            if (
                pod.phase == PodPhase.RUNNING
                and pod.uid not in self._started
            ):
                self._started.add(pod.uid)
                POD_STARTUP.observe(
                    max(0.0, self.clock.now() - pod.metadata.creation_timestamp)
                )
        for phase, n in counts.items():
            POD_STATE.set(float(n), {"phase": phase})
        POD_UNDECIDED.set(float(undecided))
