"""NodePool auxiliary controllers + node health (repair).

Reference /root/reference/pkg/controllers/nodepool/{hash,counter,readiness,
registrationhealth,validation} and node/health/controller.go:106-203.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import COND_NODE_CLASS_READY, COND_NODE_REGISTRATION_HEALTHY
from karpenter_tpu.controllers.kube import Conflict, NotFound, SimKube
from karpenter_tpu.controllers.nodeclaim_aux import NODEPOOL_HASH_VERSION, nodepool_hash
from karpenter_tpu.controllers.state import Cluster
from karpenter_tpu.events import Event, Recorder
from karpenter_tpu.utils import resources as res
from karpenter_tpu import metrics

NODEPOOL_USAGE = metrics.REGISTRY.gauge(
    "karpenter_nodepools_usage",
    "Resource usage per nodepool.",
    ("nodepool", "resource_type"),
)
NODEPOOL_NODE_COUNT = metrics.REGISTRY.gauge(
    "karpenter_nodepools_node_count", "Node count per nodepool.", ("nodepool",)
)
NODES_REPAIRED = metrics.REGISTRY.counter(
    "karpenter_nodes_repaired_total", "Nodes force-deleted by auto-repair.", ("condition",)
)


class NodePoolHash:
    """nodepool/hash: propagate the drift hash onto the NodePool annotations
    (hash/controller.go:55). NodeClaims pick it up at hydration/creation."""

    def __init__(self, kube: SimKube):
        self.kube = kube

    def reconcile_all(self) -> None:
        for np in self.kube.list("NodePool"):
            want = nodepool_hash(np)
            ann = np.metadata.annotations
            if (
                ann.get(well_known.NODEPOOL_HASH_ANNOTATION_KEY) == want
                and ann.get(well_known.NODEPOOL_HASH_VERSION_ANNOTATION_KEY)
                == NODEPOOL_HASH_VERSION
            ):
                continue
            ann[well_known.NODEPOOL_HASH_ANNOTATION_KEY] = want
            ann[well_known.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = NODEPOOL_HASH_VERSION
            try:
                self.kube.update("NodePool", np)
            except (Conflict, NotFound):
                pass


class NodePoolCounter:
    """nodepool/counter: aggregate owned node resources into NodePool status
    (counter/controller.go:70)."""

    def __init__(self, kube: SimKube, cluster: Cluster):
        self.kube = kube
        self.cluster = cluster

    def reconcile_all(self) -> None:
        totals: dict[str, dict] = {}
        counts: dict[str, int] = {}
        for sn in self.cluster.state_nodes():
            np_name = sn.nodepool_name
            if np_name is None:
                continue
            totals[np_name] = res.merge(totals.get(np_name, {}), sn.capacity())
            counts[np_name] = counts.get(np_name, 0) + 1
        for np in self.kube.list("NodePool"):
            want_res = totals.get(np.name, {})
            want_count = counts.get(np.name, 0)
            if np.status_resources == want_res and np.status_node_count == want_count:
                continue
            np.status_resources = want_res
            np.status_node_count = want_count
            try:
                self.kube.update("NodePool", np)
            except (Conflict, NotFound):
                continue
            NODEPOOL_NODE_COUNT.set(float(want_count), {"nodepool": np.name})
            for rname, v in want_res.items():
                NODEPOOL_USAGE.set(
                    float(v), {"nodepool": np.name, "resource_type": rname}
                )


class NodePoolReadiness:
    """nodepool/readiness: NodeClassReady condition (readiness/controller.go:53).
    In-tree providers have no external NodeClass objects, so readiness is a
    provider callback (ready unless the provider objects)."""

    def __init__(self, kube: SimKube, cloud):
        self.kube = kube
        self.cloud = cloud

    def reconcile_all(self) -> None:
        for np in self.kube.list("NodePool"):
            ready = True
            checker = getattr(self.cloud, "node_class_ready", None)
            if checker is not None:
                ready = bool(checker(np))
            want = "True" if ready else "False"
            if np.conditions.get(COND_NODE_CLASS_READY) != want:
                np.conditions[COND_NODE_CLASS_READY] = want
                try:
                    self.kube.update("NodePool", np)
                except (Conflict, NotFound):
                    pass


class RegistrationHealth:
    """nodepool/registrationhealth: the NodeRegistrationHealthy condition
    from a launch/registration failure ring buffer
    (registrationhealth/controller.go:59 + pkg/state/nodepoolhealth)."""

    WINDOW = 10  # ring buffer size (tracker.go)
    THRESHOLD = 0.5  # unhealthy when >50% of the window failed

    def __init__(self, kube: SimKube):
        self.kube = kube
        self._window: dict[str, deque] = {}

    def record_launch(self, nodepool: str, ok: bool) -> None:
        buf = self._window.setdefault(nodepool, deque(maxlen=self.WINDOW))
        buf.append(ok)

    def reconcile_all(self) -> None:
        for np in self.kube.list("NodePool"):
            buf = self._window.get(np.name)
            if not buf:
                continue
            failure_rate = 1.0 - (sum(buf) / len(buf))
            healthy = not (
                len(buf) >= self.WINDOW // 2 and failure_rate > self.THRESHOLD
            )
            want = "True" if healthy else "False"
            if np.conditions.get(COND_NODE_REGISTRATION_HEALTHY) != want:
                np.conditions[COND_NODE_REGISTRATION_HEALTHY] = want
                try:
                    self.kube.update("NodePool", np)
                except (Conflict, NotFound):
                    pass


class NodePoolValidation:
    """nodepool/validation: runtime spec validation (validation/controller.go:51)."""

    def __init__(self, kube: SimKube, recorder: Optional[Recorder] = None):
        self.kube = kube
        self.recorder = recorder

    def reconcile_all(self) -> dict[str, str]:
        problems: dict[str, str] = {}
        for np in self.kube.list("NodePool"):
            err = self.validate(np)
            if err is not None:
                problems[np.name] = err
                if self.recorder:
                    self.recorder.publish(
                        Event("NodePool", np.name, "Warning", "FailedValidation", err)
                    )
        return problems

    @staticmethod
    def validate(np) -> Optional[str]:
        for budget in np.disruption.budgets:
            raw = budget.nodes.strip()
            try:
                if raw.endswith("%"):
                    v = float(raw[:-1])
                    if not 0 <= v <= 100:
                        return f"budget percent out of range: {raw}"
                else:
                    if int(raw) < 0:
                        return f"budget count negative: {raw}"
            except ValueError:
                return f"invalid budget nodes value: {raw!r}"
        if np.disruption.consolidate_after_seconds < 0:
            return "consolidateAfter must be >= 0"
        if np.weight < 0 or np.weight > 100:
            return "weight must be in [0, 100]"
        return None


class NodeHealth:
    """node/health: force-delete nodes whose provider repair-policy
    conditions stayed unhealthy past the toleration window
    (health/controller.go:106). Gated by the NodeRepair feature flag."""

    def __init__(self, kube: SimKube, cluster: Cluster, cloud, clock, recorder=None):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud
        self.clock = clock
        self.recorder = recorder
        self._unhealthy_since: dict[tuple[str, str], float] = {}

    def reconcile_all(self) -> int:
        policies = self.cloud.repair_policies()
        if not policies:
            return 0
        repaired = 0
        now = self.clock.now()
        for node in self.kube.list("Node"):
            if node.metadata.deletion_timestamp is not None:
                continue
            for policy in policies:
                key = (node.name, policy.condition_type)
                status = node.conditions.get(policy.condition_type)
                if status != policy.condition_status:
                    self._unhealthy_since.pop(key, None)
                    continue
                since = self._unhealthy_since.setdefault(key, now)
                if now - since < policy.toleration_seconds:
                    continue
                sn = self.cluster.node_by_name(node.name)
                claim = sn.node_claim if sn is not None else None
                if claim is not None:
                    try:
                        self.kube.delete("NodeClaim", claim.name)
                    except NotFound:
                        pass
                else:
                    try:
                        self.kube.delete("Node", node.name)
                    except NotFound:
                        pass
                NODES_REPAIRED.inc({"condition": policy.condition_type})
                if self.recorder:
                    self.recorder.publish(
                        Event(
                            "Node", node.name, "Warning", "NodeRepair",
                            f"condition {policy.condition_type} unhealthy for "
                            f"{now - since:.0f}s; replacing",
                        )
                    )
                repaired += 1
                break
        return repaired
